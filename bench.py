#!/usr/bin/env python
"""Benchmark: ResNet-50 train-step throughput on the local accelerator.

Prints ONE JSON line:
  {"metric": "resnet50_images_per_sec_per_chip", "value": N,
   "unit": "images/sec/chip", "vs_baseline": M}

The reference publishes no numbers (BASELINE.md: `published: {}`), so
``vs_baseline`` is anchored to the driver's north star — ≥70% MFU on the
tracking config — as achieved_MFU / 0.70. FLOPs per step are taken from
XLA's compiled cost analysis, not a hand model.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

# bf16 peak FLOP/s per chip by device kind (public spec sheets).
_PEAK_FLOPS = {
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v5": 459e12,        # v5p
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,   # v6e / Trillium
    "cpu": 1e12,             # nominal, keeps the metric finite in CI
}


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "cpu")
    for k, v in _PEAK_FLOPS.items():
        if kind.lower().startswith(k.lower()):
            return v
    return 1e12


def main() -> None:
    from tpuic.config import MeshConfig, ModelConfig, OptimConfig
    from tpuic.data.synthetic import synthetic_batch
    from tpuic.models import create_model
    from tpuic.runtime.mesh import make_mesh
    from tpuic.train.optimizer import make_optimizer
    from tpuic.train.state import create_train_state
    from tpuic.train.step import make_train_step

    n_chips = jax.device_count()
    # Mesh only when there is something to shard over (on the tunneled
    # single-chip dev platform SPMD executables dispatch ~100x slower).
    mesh = make_mesh(MeshConfig()) if n_chips > 1 else None
    mcfg = ModelConfig(name="resnet50", num_classes=1000, dtype="bfloat16")
    ocfg = OptimConfig(optimizer="sgd", learning_rate=0.1, class_weights=(),
                       milestones=())
    size, per_chip_batch = 224, 64
    global_batch = per_chip_batch * n_chips

    model = create_model(mcfg.name, mcfg.num_classes, dtype=mcfg.dtype)
    state = create_train_state(model, make_optimizer(ocfg), jax.random.key(0),
                               (global_batch, size, size, 3))
    batch = synthetic_batch(global_batch, size, mcfg.num_classes)
    if mesh is not None:
        sh = jax.NamedSharding(mesh, jax.sharding.PartitionSpec("data"))
        batch = {k: jax.device_put(v, sh) for k, v in batch.items()}
    else:
        batch = {k: jax.device_put(jnp.asarray(v)) for k, v in batch.items()}
    step = make_train_step(ocfg, mcfg, mesh, donate=True)

    # FLOPs per step from the compiled executable.
    try:
        flops_per_step = float(
            step.lower(state, batch).compile().cost_analysis()["flops"])
    except Exception:
        flops_per_step = 3 * 2 * 4.1e9 * global_batch / 2  # fwd+bwd estimate

    # Warmup (compile) then timed steps. Completion is forced with a scalar
    # device->host readback: on the tunneled dev platform block_until_ready
    # returns before execution finishes, silently inflating throughput.
    state, m = step(state, batch)
    float(m["loss"])
    n_steps = 20
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, m = step(state, batch)
    float(m["loss"])
    dt = time.perf_counter() - t0

    steps_per_sec = n_steps / dt
    images_per_sec = steps_per_sec * global_batch
    images_per_sec_per_chip = images_per_sec / n_chips
    peak = _peak_flops(jax.devices()[0]) * n_chips
    mfu = flops_per_step * steps_per_sec / peak
    print(json.dumps({
        "metric": "resnet50_images_per_sec_per_chip",
        "value": round(images_per_sec_per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(mfu / 0.70, 4),
        "detail": {
            "mfu": round(mfu, 4),
            "global_batch": global_batch,
            "n_chips": n_chips,
            "device": getattr(jax.devices()[0], "device_kind", "unknown"),
            "flops_per_step": flops_per_step,
            "step_time_ms": round(1000 * dt / n_steps, 2),
        },
    }))


if __name__ == "__main__":
    main()
