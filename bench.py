#!/usr/bin/env python
"""Benchmark: ResNet-50 train-step throughput on the local accelerator.

Prints ONE JSON line:
  {"metric": "resnet50_images_per_sec_per_chip", "value": N,
   "unit": "images/sec/chip", "vs_baseline": M}

The reference publishes no numbers (BASELINE.md: `published: {}`), so
``vs_baseline`` is anchored to the driver's north star — >=70% MFU on the
tracking config — as achieved_MFU / 0.70. FLOPs per step are taken from
XLA's compiled cost analysis, not a hand model.

Robustness: the measurement runs in a child process with a wall-clock
timeout, because TPU backend init on the tunneled dev platform can hang
indefinitely (round-1 failure mode). On TPU failure the parent falls back
to a bounded CPU run (marked ``detail.fallback``), and if everything fails
it still emits one parseable JSON line with an ``error`` field — never a
bare traceback.

Env knobs: TPUIC_BENCH_TIMEOUT (TPU child seconds, default 420),
TPUIC_BENCH_CPU_TIMEOUT (CPU child seconds, default 420),
TPUIC_BENCH_PLATFORMS (comma list, default "tpu,cpu").
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.abspath(__file__))
METRIC = "resnet50_images_per_sec_per_chip"
UNIT = "images/sec/chip"

# Peak-FLOPs table + analytic per-model FLOPs now live in the telemetry
# subsystem (tpuic/telemetry/goodput.py) so the in-band MFU accounting
# and this bench headline share one formula; imported back here.
from tpuic.telemetry.goodput import (PEAK_FLOPS as _PEAK_FLOPS,  # noqa: E402,F401
                                     analytic_flops_per_step,
                                     peak_flops as _peak_flops)


def _measure(platform: str) -> dict:
    """The actual benchmark. Runs inside the child process."""
    import jax

    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    # Persistent compile cache (shared with the test suite) so repeated
    # bench runs skip the model-sized compiles.
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(_REPO, "tests", ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

    import jax.numpy as jnp

    from tpuic.config import MeshConfig, ModelConfig, OptimConfig
    from tpuic.data.synthetic import synthetic_batch
    from tpuic.models import create_model
    from tpuic.runtime.mesh import make_mesh
    from tpuic.train.optimizer import make_optimizer
    from tpuic.train.state import create_train_state
    from tpuic.train.step import make_eval_step, make_train_step

    t_init = time.perf_counter()
    n_chips = jax.device_count()
    init_s = time.perf_counter() - t_init
    on_cpu = jax.devices()[0].platform == "cpu"
    # Mesh only when there is something to shard over (on the tunneled
    # single-chip dev platform SPMD executables dispatch ~100x slower).
    mesh = make_mesh(MeshConfig()) if n_chips > 1 else None
    mcfg = ModelConfig(name="resnet50", num_classes=1000,
                       dtype="float32" if on_cpu else "bfloat16")
    ocfg = OptimConfig(optimizer="sgd", learning_rate=0.1, class_weights=(),
                       milestones=())
    # CPU fallback: small batch / few steps — the point is a finite,
    # honestly-labeled number, not CPU throughput tuning.
    size = 224
    # Per-chip batch 128: the round-3 sweep's peak (perf/sweep.json —
    # 2674 img/s vs 2291@64, 2551@256, 2327@160; 128 aligns the batch dim
    # with MXU tiling). PERF_ANALYSIS.md has the full grid.
    per_chip_batch, n_steps = (8, 3) if on_cpu else (128, 20)
    global_batch = per_chip_batch * n_chips

    model = create_model(mcfg.name, mcfg.num_classes, dtype=mcfg.dtype)
    state = create_train_state(model, make_optimizer(ocfg), jax.random.key(0),
                               (global_batch, size, size, 3))
    batch = synthetic_batch(global_batch, size, mcfg.num_classes)
    if mesh is not None:
        sh = jax.NamedSharding(mesh, jax.sharding.PartitionSpec("data"))
        batch = {k: jax.device_put(v, sh) for k, v in batch.items()}
    else:
        batch = {k: jax.device_put(jnp.asarray(v)) for k, v in batch.items()}
    step = make_train_step(ocfg, mcfg, mesh, donate=True)

    # One AOT compile through the compiled-program registry
    # (tpuic/compiled/): the same executable feeds the FLOPs headline
    # (cost analysis is captured at build) and the timed loop — the old
    # path compiled the program twice (lower().compile() for FLOPs, then
    # the first jit call again).
    from tpuic.compiled import ProgramKey, avals_crc, registry, tree_avals
    t_comp = time.perf_counter()
    key = ProgramKey(
        model=f"bench:train_step:{mcfg.name}",
        shapes=((global_batch, size, size, 3),
                avals_crc(tree_avals(state.params))),
        mesh=tuple((str(a), int(n)) for a, n in mesh.shape.items())
        if mesh is not None else (),
        dtype=mcfg.dtype)
    entry = registry.get_or_compile(
        key, lambda: step.lower(state, batch).compile())
    run = entry.executable
    flops_drift = None
    try:
        from tpuic.telemetry.goodput import check_flops_drift
        flops_per_step = float(entry.cost["flops"])
        # Ride-along cross-check (docs/observability.md): the analytic
        # table the in-band MFU accounting uses vs the compiler's count
        # this headline uses — a >10% drift warns loudly (stderr; the
        # stdout JSON contract is untouched) instead of letting the two
        # MFU sources silently diverge.  Per-CHIP batch: under SPMD the
        # compiled cost analysis describes one device's program shard.
        flops_drift = check_flops_drift(
            "resnet50", size, per_chip_batch, flops_per_step,
            warn=lambda msg: print(f"[bench] WARNING: {msg}",
                                   file=sys.stderr))
    except Exception:
        # Analytic fwd+bwd estimate — the telemetry subsystem's formula.
        # (2x the old inline 3*2*4.1e9*B/2: that constant was the GMAC
        # count pasted as FLOPs, fixed by the PR-16 zoo cross-check.)
        flops_per_step = analytic_flops_per_step("resnet50", size,
                                                 global_batch)

    # Warmup (first dispatch) then timed steps. Completion is forced with
    # a scalar device->host readback: on the tunneled dev platform
    # block_until_ready returns before execution finishes, silently
    # inflating throughput.
    state, m = run(state, batch)
    float(m["loss"])
    compile_s = time.perf_counter() - t_comp
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, m = run(state, batch)
    float(m["loss"])
    dt = time.perf_counter() - t0

    steps_per_sec = n_steps / dt
    images_per_sec = steps_per_sec * global_batch

    # Variance attribution (round-5 VERDICT: the cross-round MFU drift
    # was unfalsifiable without it): (a) two more timed trials of the
    # same pipelined loop -> across-trial spread of the headline rate;
    # (b) a serialized pass — one blocking scalar readback per step — ->
    # per-step latency percentiles via the shared LatencyMeter (the same
    # primitive serve stats and the telemetry StepTimer use).  The
    # serialized mode measures step+sync, NOT the pipelined headline;
    # it is labeled as such in the detail.
    from tpuic.metrics.meters import LatencyMeter
    trial_rates = [images_per_sec]
    for _ in range(2):
        t1 = time.perf_counter()
        for _ in range(n_steps):
            state, m = run(state, batch)
        float(m["loss"])
        trial_rates.append(n_steps * global_batch
                           / (time.perf_counter() - t1))
    per_step = LatencyMeter(window=n_steps)
    for _ in range(n_steps):
        t1 = time.perf_counter()
        state, m = run(state, batch)
        float(m["loss"])
        per_step.update(time.perf_counter() - t1)
    rates = sorted(trial_rates)
    med_rate = rates[len(rates) // 2]
    mean_rate = sum(trial_rates) / len(trial_rates)
    spread = {
        "images_per_sec_per_chip": [round(r / n_chips, 2)
                                    for r in trial_rates],
        "std": round((sum((r - mean_rate) ** 2 for r in trial_rates)
                      / len(trial_rates)) ** 0.5 / n_chips, 2),
        "spread_pct": round(100.0 * (rates[-1] - rates[0])
                            / max(med_rate, 1e-9), 2),
    }
    step_latency = {**per_step.percentiles_ms((50, 95, 99)),
                    "std_ms": per_step.std_ms, "n": per_step.count,
                    "mode": "serialized (blocking readback per step; "
                            "bounds per-step variance, not comparable "
                            "to the pipelined headline)"}

    # Companion: inference (eval-step) throughput at the same config — the
    # reference's val pass is half its loop (train.py:78-97); tpuic.predict
    # runs this exact step. Guarded: an optional enrichment must never sink
    # the primary train measurement (same rule as the artifact companions
    # below).
    eval_images_per_sec = None
    try:
        estep = make_eval_step(ocfg, mcfg, mesh)
        em = estep(state, batch)
        float(em["count"])  # compile + sync
        t0 = time.perf_counter()
        for _ in range(n_steps):
            em = estep(state, batch)
        float(em["count"])
        eval_images_per_sec = (n_steps * global_batch
                               / (time.perf_counter() - t0))
    except Exception:
        pass
    peak = _peak_flops(jax.devices()[0]) * n_chips
    mfu = flops_per_step * steps_per_sec / peak
    # Device-time breakdown from the committed round-3 profile artifact
    # (scripts/perf_profile.py; VERDICT r2 asked for the step-time
    # breakdown in the BENCH detail). Re-run the script to refresh.
    breakdown = None
    try:
        path = os.path.join(_REPO, "perf", "profile.json")
        with open(path) as f:
            prof = json.load(f)
        breakdown = {"per_step_ms": prof.get("per_step_ms"),
                     "by_category_ms": prof.get("by_category_ms"),
                     "source": "perf/profile.json",
                     # Provenance, NOT this run: consumers can judge
                     # staleness against their own clock/commit.
                     "profile_captured": time.strftime(
                         "%Y-%m-%dT%H:%M:%SZ",
                         time.gmtime(os.path.getmtime(path)))}
    except (OSError, ValueError):
        pass
    # Companion artifacts (same provenance rule as the profile breakdown):
    # the input-pipeline and end-to-end-loop numbers that bound this step
    # rate in real training.
    companions = {}
    # An optional enrichment artifact must never sink the measurement —
    # tolerate any malformed content, not just missing/unparseable files.
    try:
        with open(os.path.join(_REPO, "perf", "bench_data.json")) as f:
            ld = json.load(f)
        if isinstance(ld, dict) and ld.get("value") is not None:
            companions["loader_images_per_sec_per_host"] = ld["value"]
    except Exception:
        pass
    try:
        with open(os.path.join(_REPO, "perf", "fit_proof.json")) as f:
            fp = json.load(f)
        if isinstance(fp, dict):
            for src, dst in (("loop_images_per_sec_median_steady",
                              "fit_loop_images_per_sec"),
                             ("loop_vs_bench", "fit_loop_vs_bench"),
                             ("note", "fit_loop_note")):
                if fp.get(src) is not None:
                    companions[dst] = fp[src]
    except Exception:
        pass
    return {
        "metric": METRIC,
        "value": round(images_per_sec / n_chips, 2),
        "unit": UNIT,
        "vs_baseline": round(mfu / 0.70, 4),
        "detail": {
            "mfu": round(mfu, 4),
            "global_batch": global_batch,
            "n_chips": n_chips,
            "device": getattr(jax.devices()[0], "device_kind", "unknown"),
            "platform": jax.devices()[0].platform,
            "flops_per_step": flops_per_step,
            "analytic_flops_drift": (round(flops_drift, 4)
                                     if flops_drift is not None else None),
            "step_time_ms": round(1000 * dt / n_steps, 2),
            "step_latency_ms": step_latency,
            "trial_spread": spread,
            "eval_images_per_sec_per_chip": (
                round(eval_images_per_sec / n_chips, 2)
                if eval_images_per_sec else None),
            "backend_init_s": round(init_s, 1),
            "compile_s": round(compile_s, 1),
            "dtype": mcfg.dtype,
            "profile_breakdown": breakdown,
            "companions": companions or None,
            "analysis": "PERF_ANALYSIS.md",
        },
    }


def _child(platform: str) -> None:
    print(json.dumps(_measure(platform)), flush=True)


def _run_child(platform: str, timeout: float):
    """Run the measurement in a subprocess; return (result|None, error|None)."""
    env = dict(os.environ)
    if platform == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
        # Drop this image's remote-TPU backend triggers (see sitecustomize):
        # with them set, backend selection is forced back to 'axon' and can
        # hang init even when CPU was requested.
        from tpuic.runtime.axon_guard import drop_axon_vars
        drop_axon_vars(env)
    env.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--_child", platform],
            env=env, cwd=_REPO, capture_output=True, text=True,
            timeout=timeout)
    except subprocess.TimeoutExpired:
        return None, f"{platform}: timed out after {timeout:.0f}s"
    if proc.returncode != 0:
        tail = " | ".join((proc.stderr or "").strip().splitlines()[-3:])
        return None, f"{platform}: rc={proc.returncode}: {tail[:500]}"
    for line in reversed((proc.stdout or "").strip().splitlines()):
        try:
            return json.loads(line), None
        except (json.JSONDecodeError, ValueError):
            continue
    return None, f"{platform}: no JSON in child output"


def _measure_pattern() -> str:
    """The measurement-skewing process pattern, read from its single
    source of truth (scripts/chip_wait.sh MEASURE_PAT) so the two sides
    of the contention protocol cannot drift; hardcoded fallback only if
    the file is missing/unparseable."""
    try:
        with open(os.path.join(_REPO, "scripts", "chip_wait.sh")) as f:
            for line in f:
                if line.startswith("MEASURE_PAT="):
                    return line.split("=", 1)[1].strip().strip("'\"")
    except OSError:
        pass
    return (r"bench\.py|perf_sweep\.py|long_seq_bench\.py|pallas_smoke\.py|"
            r"packed_valid_smoke\.py|fit_proof\.py|resume_cache_proof\.py|"
            r"convergence_digits\.py|bench_data\.py|__graft_entry__|pytest")


def _ancestor_pids() -> set:
    """This process's ancestry — the driver invokes bench.py through
    shell/timeout wrappers whose argv also contains 'bench.py', and a
    waiter must never wait on its own ancestors."""
    pids = {os.getpid()}
    pid = os.getpid()
    for _ in range(32):
        try:
            with open(f"/proc/{pid}/status") as f:
                ppid = next((int(ln.split()[1]) for ln in f
                             if ln.startswith("PPid:")), 0)
        except OSError:
            break
        if ppid <= 1:
            break
        pids.add(ppid)
        pid = ppid
    return pids


def _wait_for_measurements(max_wait: float = 180.0) -> dict:
    """Bounded wait for other chip measurements before benching.

    The host has one core and one chip: a queue-script sweep (or pytest)
    running concurrently would skew BOTH measurements. The queue side
    already waits for bench.py (scripts/chip_wait.sh); this is the bench
    side. Bounded — the driver's round-end bench must produce a line even
    if a long measurement is mid-flight — and disclosed: the returned
    dict lands in detail so a contended line says so instead of quietly
    reading 5% slow. TPUIC_BENCH_NO_WAIT=1 skips it (bench_cache_timing
    sets this for its children: their wall clock IS the artifact, and a
    wait would silently inflate it).
    """
    if os.environ.get("TPUIC_BENCH_NO_WAIT") == "1":
        return {}
    pat = _measure_pattern()
    skip = _ancestor_pids()

    def contenders() -> tuple:
        """(check_ok, procs): an explicit flag instead of a sentinel
        string in the proc list — a legitimate contender whose argv
        happens to contain a marker word must neither end the wait early
        nor be persisted as a fake process (ADVICE r5)."""
        try:
            out = subprocess.run(["pgrep", "-fa", pat], capture_output=True,
                                 text=True, timeout=10).stdout
        except Exception:
            return False, []
        procs = []
        for line in out.splitlines():
            parts = line.split(None, 1)
            if len(parts) < 2:
                continue
            pid_s, cmd = parts
            # Skip self + wrapper ancestors, and the session driver whose
            # prompt argv contains these script names (same filter as
            # scripts/chip_wait.sh).
            if pid_s.isdigit() and int(pid_s) in skip:
                continue
            if "claude" in cmd or "append-system-prompt" in cmd:
                continue
            procs.append(cmd[:60])
        return True, procs

    t0 = time.time()
    ok, busy = contenders()
    while ok and busy and time.time() - t0 < max_wait:
        time.sleep(15)
        ok, busy = contenders()
    waited = round(time.time() - t0, 1)
    info = {}
    if waited >= 15:
        info["contention_wait_s"] = waited
    if not ok:
        info["contention_check"] = "failed: pgrep unavailable"
    elif busy:
        info["contended_with"] = busy[:3]
    return info


def main() -> None:
    if "--_child" in sys.argv:
        _child(sys.argv[sys.argv.index("--_child") + 1])
        return
    contention = _wait_for_measurements()
    platforms = os.environ.get("TPUIC_BENCH_PLATFORMS", "tpu,cpu").split(",")
    timeouts = {
        "tpu": float(os.environ.get("TPUIC_BENCH_TIMEOUT", "420")),
        "cpu": float(os.environ.get("TPUIC_BENCH_CPU_TIMEOUT", "420")),
    }
    errors = []
    for platform in [p.strip() for p in platforms if p.strip()]:
        result, err = _run_child(platform, timeouts.get(platform, 420.0))
        if result is not None:
            if contention:
                result.setdefault("detail", {}).update(contention)
            # Trust the child's OWN platform report, not the requested
            # label: a silent JAX CPU fallback must never be persisted as
            # chip evidence. Recording runs even if another platform
            # failed first (the result isn't mutated yet).
            if result.get("detail", {}).get("platform") == "tpu":
                _record_tpu_success(result)
            if (errors
                    and result.get("detail", {}).get("platform") != "tpu"):
                # A preferred platform failed AND this run is not itself
                # chip evidence (a live-TPU success after, say, a failed
                # CPU smoke must stay the headline). Dead-tunnel day: the
                # headline becomes the freshest recorded live-TPU line
                # (with explicit staleness) and this CPU run is demoted
                # to a labeled smoke detail.
                promoted = _promote_last_tpu(errors, cpu_result=result)
                if promoted is not None:
                    print(json.dumps(promoted), flush=True)
                    return
                result.setdefault("detail", {})["fallback"] = platform
                result["error"] = "; ".join(errors)
                _attach_last_tpu(result)
            print(json.dumps(result), flush=True)
            return
        errors.append(err)
    promoted = _promote_last_tpu(errors)
    if promoted is not None:
        print(json.dumps(promoted), flush=True)
        return
    out = {
        "metric": METRIC, "value": 0.0, "unit": UNIT, "vs_baseline": 0.0,
        "error": "; ".join(errors) or "no platforms attempted",
    }
    _attach_last_tpu(out)
    print(json.dumps(out), flush=True)


_LAST_TPU_PATH = os.path.join(_REPO, "perf", "bench_last_tpu.json")


def _record_tpu_success(result: dict) -> None:
    """Persist a successful live-TPU bench line so a later fallback run can
    surface THIS bench's own last real measurement, not just the sweep
    artifact (the tunnel has wedged mid-round twice; the scoreboard must
    never lose the chip evidence to a flap at round end)."""
    try:
        with open(_LAST_TPU_PATH, "w") as f:
            json.dump({"measured": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                                 time.gmtime()),
                       "result": result}, f, indent=2)
    except OSError:
        pass


def _promote_last_tpu(errors, cpu_result: dict = None):
    """TPU unreachable this run: build the output line FROM the freshest
    recorded live-TPU measurement (perf/bench_last_tpu.json), with
    ``measured_at`` + ``staleness_s`` at top level beside value, and the
    CPU fallback (when one ran) demoted to a clearly-labeled smoke
    detail.  A BENCH headline must never read 0.74 img/s on a
    dead-tunnel day when the chip's demonstrated number is on disk
    (VERDICT r5 item 2).  Returns None when no live-TPU line exists —
    callers then keep the old fallback shape (CPU value headlined,
    ``last_tpu_measurement`` attached beside it)."""
    try:
        with open(_LAST_TPU_PATH) as f:
            last = json.load(f)
        r = dict(last["result"])
        float(r["value"])  # malformed artifact -> old behavior
    except (OSError, ValueError, KeyError, TypeError):
        return None
    measured_at = last.get("measured")
    staleness = None
    if measured_at:
        try:
            import calendar
            staleness = max(0, int(time.time() - calendar.timegm(
                time.strptime(measured_at, "%Y-%m-%dT%H:%M:%SZ"))))
        except (ValueError, TypeError):  # corrupt/non-string 'measured'
            pass
    detail = dict(r.get("detail") or {})
    detail["source"] = ("perf/bench_last_tpu.json — this bench's last "
                        "live-TPU line, promoted to headline: TPU "
                        "unreachable this run")
    if cpu_result is not None:
        cd = cpu_result.get("detail") or {}
        detail["cpu_smoke"] = {
            "note": "CPU fallback ran this round — smoke signal only, "
                    "NOT comparable to the chip headline",
            "value": cpu_result.get("value"),
            "unit": cpu_result.get("unit"),
            "platform": cd.get("platform"),
            "global_batch": cd.get("global_batch"),
            "step_time_ms": cd.get("step_time_ms"),
        }
    r["detail"] = detail
    r["measured_at"] = measured_at
    r["staleness_s"] = staleness
    r["error"] = "; ".join(e for e in errors if e)
    return r


def _attach_last_tpu(result: dict) -> None:
    """When the TPU path failed (dev tunnel down — it hung for 8+ hours in
    round 3), surface the last committed real-chip measurement with
    provenance so the fallback artifact still carries the chip's
    demonstrated capability: this bench's own last successful TPU line
    (perf/bench_last_tpu.json) when available, else the sweep artifact.

    Attached at TOP level, beside value/vs_baseline: a scoreboard reader
    must never see the CPU fallback number without the TPU context next to
    it (VERDICT r3 weak #6 / next-round item 8)."""
    try:
        with open(_LAST_TPU_PATH) as f:
            last = json.load(f)
        r = last["result"]
        d = r.get("detail", {})
        result["last_tpu_measurement"] = {
            "images_per_sec_per_chip": r["value"],
            "mfu": d.get("mfu"),
            "per_chip_batch": (d["global_batch"] // max(1, d.get("n_chips", 1))
                               if "global_batch" in d else None),
            "device": d.get("device"),
            "source": "perf/bench_last_tpu.json (this bench, live TPU)",
            "measured": last.get("measured"),
        }
        return
    except (OSError, ValueError, KeyError, TypeError):
        pass
    try:
        path = os.path.join(_REPO, "perf", "sweep.json")
        with open(path) as f:
            sweep = json.load(f)
        rows = [r for r in sweep.get("results", [])
                if "images_per_sec_per_chip" in r]
        if not rows:
            return
        best = max(rows, key=lambda r: r["images_per_sec_per_chip"])
        result["last_tpu_measurement"] = {
            "images_per_sec_per_chip": best["images_per_sec_per_chip"],
            "mfu": best.get("mfu"),
            "per_chip_batch": best.get("per_chip_batch"),
            "device": sweep.get("device"),
            "source": "perf/sweep.json",
            # File mtime, NOT measurement time: git checkouts reset mtimes,
            # so this only bounds how recently the artifact was touched.
            "file_mtime": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ",
                time.gmtime(os.path.getmtime(path))),
        }
    except (OSError, ValueError, KeyError, TypeError):
        pass


if __name__ == "__main__":
    main()
