"""Configuration dataclasses.

The reference exposes 3 argparse flags (``--local_rank``, ``--datadir``,
``--batchsize``; train.py:27-31) and hard-codes everything else as inline
constants. Every one of those constants is surfaced here as a named field with
the reference's exact default (source lines cited per field), so behavior
parity is a config choice rather than an archaeology project.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence


@dataclasses.dataclass(frozen=True)
class DataConfig:
    """Input-pipeline settings (reference dp/loader.py + train.py:110-118)."""

    data_dir: str = ""
    # Image side length; reference hard-codes 299 (train.py:110).
    resize_size: int = 299
    # Per-device train batch size; reference default 4 per process (train.py:30).
    batch_size: int = 4
    # Reference uses val batch_size=1 (train.py:118). We default to the train
    # batch size because SPMD eval is exact regardless of batching (the
    # reference needed bs=1 only for its per-sample pickle all_gather), but the
    # knob exists for strict parity runs.
    val_batch_size: int = 0  # 0 => same as batch_size
    # Host-side prefetch depth and worker threads (reference: num_workers=6,
    # pin_memory=True, train.py:114).
    num_workers: int = 6
    prefetch: int = 2
    # ImageNet normalization stats (reference dp/loader.py:86-91).
    mean: Sequence[float] = (0.485, 0.456, 0.406)
    std: Sequence[float] = (0.229, 0.224, 0.225)
    # Use the fused C++ prep core (tpuic/native) when its build is available;
    # False forces the pure-NumPy transform path (identical numerics).
    native: bool = True
    # Packed uint8 cache (tpuic/data/pack.py): decode+resize once into a
    # memory-mapped .bin, then serve epochs at memory bandwidth with
    # augmentation/normalization on the TPU (tpuic/data/device_prep.py).
    # The round-3 measured reality: this host has ONE core, so per-epoch
    # decode (reference dp/loader.py:44 every epoch) caps at ~220 img/s
    # while the chip consumes ~2,200 — packing is how the chip stays fed.
    pack: bool = True
    cache_dir: str = ""  # '' => {data_dir}/.tpuic_pack
    # Device-resident dataset cache: when the packed uint8 dataset fits
    # this HBM budget, the Loader uploads it ONCE (replicated under a mesh)
    # and a training batch ships only [B] indices + [B,5] augment params —
    # the gather/augment/normalize runs on device. Decouples the loop from
    # host-link bandwidth entirely (round-3 measurement: the dev tunnel
    # sustains ~35 MB/s H2D under load, capping any per-batch-upload
    # design at ~230 img/s vs the chip's 2,674). 0 disables.
    device_cache_mb: int = 4096
    # Global shuffle seed. The reference shuffles the file list per-rank,
    # unseeded (dp/loader.py:23) — a correctness bug (ranks see inconsistent
    # shards). We seed identically on every host and fold in the epoch.
    shuffle_seed: int = 0
    # Train-fold augmentation master switch. The reference hard-wires its
    # rot90/flip/jitter chain on every train sample (dp/loader.py:63-83);
    # that chain assumes orientation-free imagery. For orientation-sensitive
    # datasets (digits: rot90/flip alias 6<->9, 2<->5) False trains on clean
    # decodes while val/normalization behavior is unchanged.
    augment: bool = True
    # Augmentation probabilities (reference dp/loader.py:63-83).
    p_vflip: float = 0.5
    p_hflip: float = 0.5
    p_saturation: float = 0.05
    p_brightness: float = 0.05
    p_contrast: float = 0.05
    jitter_lo: float = 0.9
    jitter_hi: float = 1.1
    # Sample quarantine (docs/robustness.md): a sample whose decode fails
    # (truncated/corrupt file) is retried ``quarantine_retries`` times with
    # ``quarantine_backoff_s`` between attempts (the file-mid-copy case),
    # then replaced by a deterministic same-class substitute and counted —
    # one corrupt file degrades the epoch by one sample instead of killing
    # the producer thread (reference dp/loader.py has no handling at all).
    # False restores fail-fast: the decode error propagates and aborts.
    quarantine: bool = True
    quarantine_retries: int = 1
    quarantine_backoff_s: float = 0.05

    def resolved_val_batch_size(self) -> int:
        return self.val_batch_size or self.batch_size


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Model settings (reference nn/classifier.py + train.py:122-123)."""

    # Backbone name; reference default 'inceptionv3' (train.py:122).
    name: str = "inceptionv3"
    num_classes: int = 7
    # MLP head widths (reference nn/classifier.py:26-34: in->128->64->32->n).
    head_widths: Sequence[int] = (128, 64, 32)
    # Compute dtype. bfloat16 feeds the MXU at full rate; params stay f32.
    dtype: str = "bfloat16"
    param_dtype: str = "float32"
    # BatchNorm momentum/eps matching torch defaults the reference inherits.
    bn_momentum: float = 0.9  # flax convention: ema = m*ema + (1-m)*batch
    bn_eps: float = 1e-5
    # BN batch-statistics accumulation dtype. True (default) reduces in
    # float32 — torch/SyncBN semantics. False reduces in the compute dtype
    # (bf16): the stat fusions re-read large activation tensors and are the
    # top HBM consumers in the ResNet-50 profile (perf/profile.json), so
    # halving their read width is a bandwidth experiment (VERDICT r3 item
    # 7); numerics tolerance is pinned in tests/test_models.py. ResNet
    # family only; inception/effnet keep f32 stats.
    bn_f32_stats: bool = True
    # Rematerialize the forward in the backward pass (jax.checkpoint with the
    # dots-without-batch-dims policy): trades recompute FLOPs for activation
    # HBM traffic/footprint — a win when the model is bandwidth-bound or
    # memory-limited. The reference has no equivalent (torch would need
    # torch.utils.checkpoint rewiring).
    remat: bool = False
    # What remat recomputes (effective only when remat=True):
    #   'dots'      — whole-forward jax.checkpoint saving only matmul/conv
    #                 outputs without batch dims; recomputes all
    #                 activation-sized tensors (the original behavior;
    #                 measured -15..20% on ResNet-50, PERF_ANALYSIS.md §1).
    #   'attention' — ViT ``remat_core``: just the logits->softmax->probs@v
    #                 core runs under jax.checkpoint, so the [B,H,N,N]
    #                 tensors that erase allocator headroom past b64 (§10b)
    #                 are never residuals; recompute is one einsum + softmax
    #                 per layer. No-op for models/impls with no dense
    #                 attention core (ResNet; flash never materializes it).
    #   'blocks'    — ViT ``remat_blocks``: each encoder block under
    #                 nn.remat with the save-nothing policy, so the only
    #                 N-sized residuals are the block inputs and the
    #                 backward recomputes one block at a time. The
    #                 long-context memory mode: at N=4097/b16 'dots' needs
    #                 19.5 GB (flash) / 41.1 GB (dense) vs 15.75 HBM
    #                 (PERF_ANALYSIS.md §10f). Composes with any attention
    #                 impl; ViT-only (warns and no-ops elsewhere).
    #   'gelu'      — ViT ``remat_mlp``: each block's Dense(mlp_up)+GELU
    #                 runs under nn.remat (models/vit.py MlpUpGelu), so
    #                 the [B,N,4D] pre-activation is never a residual —
    #                 the mlp_up fusion writes ONE output instead of two
    #                 and the backward recomputes W1·x per block. The
    #                 lightest policy, aimed at the dual-output mlp_up
    #                 writes the ViT-B b64 profile fingered (§10f).
    #                 ViT-only (warns and no-ops elsewhere); in MoE ViTs
    #                 the dense-MLP blocks still benefit (the routed
    #                 SwitchMoEMlp blocks are untouched).
    remat_policy: str = "dots"
    # Inception aux-logits loss weight (reference train.py:52).
    aux_loss_weight: float = 0.4
    # MoE load-balancing loss weight (Switch Transformer's alpha; only
    # active for *-moe models, which sow 'moe_router' stats that the train
    # step turns into a padding-masked switch_aux_loss).
    moe_aux_weight: float = 0.01
    # Inference-only Pallas fused conv+BN+ReLU for the ResNet family
    # (tpuic/kernels/conv_bn_relu.py): every conv -> BN -> ReLU block of
    # a train=False call runs as one VMEM-resident kernel (conv as tap
    # matmuls, BN folded to a per-channel affine epilogue) instead of
    # three HBM-roundtripping HLOs. Parameter structure is unchanged, so
    # the flag flips on any existing checkpoint; training and non-ResNet
    # backbones ignore it. Numerics parity vs the unfused graph is
    # pinned in tests/test_kernels.py (atol 1e-4 in float32).
    fused_conv_bn: bool = False
    # Attention implementation for attention-bearing backbones (ViT):
    # 'dense' (einsum softmax), 'flash' (Pallas blockwise online-softmax,
    # tpuic/kernels/flash_attention.py), 'ring' (sequence-parallel ring
    # attention over the mesh 'seq' axis, tpuic/parallel/ring_attention.py),
    # 'ring-flash' (the ring with the flash kernel as its per-step block
    # primitive — long-context), 'ulysses' (sequence-parallel all-to-all
    # head redistribution, tpuic/parallel/ulysses.py), or 'ulysses-flash'
    # (ulysses with its head-sharded local attention run through the flash
    # kernel). CNNs ignore this.
    attention: str = "dense"
    # Stochastic depth for ViT backbones (rate of the LAST block; rates
    # ramp linearly from 0 — the DeiT schedule). CNNs ignore this.
    drop_path: float = 0.0
    # Training compute-dtype POLICY ('' | 'bf16' | 'f32'), wired through
    # ``train.py --compute-dtype``. '' (default) leaves the per-model
    # ``dtype`` field in charge — bitwise the pre-policy behavior. 'bf16'
    # is the mixed-precision training tier: the forward/backward run in
    # bfloat16 (``dtype`` is forced, batch images are cast at the step
    # entry) while the differentiated MASTER params stay float32
    # (``param_dtype``), the optimizer moments stay float32 (optax init
    # mirrors the f32 params), the loss is computed on f32 logits, and
    # checkpoints stay float32 on disk — the lifecycle / hot-swap /
    # elastic machinery never sees a dtype change. 'f32' forces full
    # float32 compute: the convergence-parity reference arm
    # (scripts/bf16_parity.py, the tier-1 "bf16 parity" CI gate).
    compute_dtype: str = ""

    def __post_init__(self):
        resolve_compute_dtype(self)  # validate eagerly, not at trace time


# Accepted spellings of the ModelConfig.compute_dtype policy -> canonical
# tag. '' = legacy (per-model dtype field rules).
_COMPUTE_DTYPES = {"": "", "bf16": "bf16", "bfloat16": "bf16",
                   "f32": "f32", "float32": "f32"}


def resolve_compute_dtype(model: "ModelConfig") -> str:
    """Canonical compute-dtype tag for a ModelConfig: '', 'bf16' or 'f32'.

    The single normalization point: the Trainer (model dtype override +
    telemetry roofline choice) and the train step (batch cast, f32-loss
    guarantee) must agree on what the policy means."""
    key = str(getattr(model, "compute_dtype", "") or "").lower()
    if key not in _COMPUTE_DTYPES:
        raise ValueError(
            f"unknown compute_dtype {model.compute_dtype!r}; expected one "
            f"of {sorted(k for k in _COMPUTE_DTYPES if k)} (or '' for the "
            "per-model dtype default)")
    return _COMPUTE_DTYPES[key]


@dataclasses.dataclass(frozen=True)
class OptimConfig:
    """Optimizer + schedule (reference train.py:127, 156-158)."""

    optimizer: str = "adam"  # 'adam' | 'lars' | 'sgd'
    # Reference lr=0.5e-5 (train.py:127).
    learning_rate: float = 0.5e-5
    # MultiStepLR milestones=[50, 80], gamma=0.5 (train.py:156).
    milestones: Sequence[int] = (50, 80)
    gamma: float = 0.5
    # Class weights for CrossEntropy; reference hard-codes a 7-class imbalance
    # vector (train.py:157-158). Empty => unweighted.
    class_weights: Sequence[float] = (3.0, 3.0, 10.0, 1.0, 4.0, 4.0, 5.0)
    # Derive inverse-frequency weights from the train fold's class counts
    # (w_c = N / (K * n_c), mean ~1) at Trainer construction — what the
    # reference's hard-coded vector approximated by hand for its original
    # 7-class dataset. Overrides class_weights.
    auto_class_weights: bool = False
    weight_decay: float = 0.0
    # Mixup (Zhang et al., 2018): Beta(alpha, alpha) convex image/label
    # mixing, applied on-device inside the jitted train step (one lambda
    # per step). 0 disables; 0.2 is the common ImageNet setting.
    mixup_alpha: float = 0.0
    # CutMix (Yun et al., 2019): Beta(alpha, alpha)-sized box from the
    # permuted partner pasted per step, labels mixed by EXACT kept area.
    # 0 disables; 1.0 is the paper setting. When both mixup and cutmix
    # are set, one is chosen per step (50/50, torchvision recipe).
    cutmix_alpha: float = 0.0
    # Random erasing (Zhong et al., 2020): per-sample probability of
    # zeroing a random box (2-33% area) on-device in the train step.
    # 0 disables; 0.25 is the common timm setting.
    random_erase: float = 0.0
    # LARS settings for the large-batch config (BASELINE.md config 5).
    lars_momentum: float = 0.9
    lars_trust_coefficient: float = 0.001
    # LAMB (arXiv:1904.00962) moments — the Adam-flavored layer-wise
    # trust-ratio optimizer for large-batch attention models
    # (optimizer='lamb'; weight_decay rides the shared knob).
    lamb_b1: float = 0.9
    lamb_b2: float = 0.999
    lamb_eps: float = 1e-6
    # Goyal linear-scaling rule (arXiv:1706.02677; every 15-minute-
    # ImageNet recipe's ingredient): when > 0, the peak LR becomes
    # learning_rate * global_batch / base_batch_size, reached by a
    # LINEAR warmup from the unscaled learning_rate over warmup_epochs
    # (train/schedule.py batch_scaled_warmup_schedule). The global batch
    # is per-device batch x data-parallel extent, so the SAME config
    # stays correctly tuned as the fleet grows — or elastically shrinks
    # (the re-formed mesh rebuilds the schedule at its new extent).
    # 0 (default) disables scaling entirely.
    base_batch_size: int = 0
    warmup_epochs: int = 0
    grad_clip_norm: float = 0.0
    # Accumulate gradients over K steps before applying one optimizer
    # update (effective batch = K * global batch). 1 = off.
    grad_accum_steps: int = 1
    label_smoothing: float = 0.0
    # Exponential moving average of params (0 = off; typical 0.9999).
    # ema = d*ema + (1-d)*params after each real optimizer update;
    # validation, checkpoint 'best' selection, and predict then use the
    # EMA weights — the standard modern image-classification recipe
    # (EfficientNet/ViT). Must be in [0, 1): 1.0 would freeze the EMA at
    # its seed forever (validated in __post_init__).
    ema_decay: float = 0.0
    # Head-only fine-tuning: zero updates for the backbone scope, so only
    # the MLP head trains (pairs with RunConfig.init_from). Gradient-level
    # freeze — BN running stats still update in train mode.
    freeze_backbone: bool = False
    # Use the fused Pallas cross-entropy kernel
    # (tpuic/kernels/cross_entropy.py) in the train step.
    fused_loss: bool = False
    # Fused one-pass optimizer-update kernel for 'lars' / 'lamb'
    # (tpuic/kernels/optimizer_update.py): params, grads and moments make
    # ONE VMEM round trip per leaf instead of the optax chain's stacked
    # elementwise HLOs (decay -> trust -> lr -> momentum each
    # materializing an update-sized tree). Trajectory parity vs the
    # optax chain and the numpy trust-ratio references is golden-pinned
    # in tests/test_fused_optimizer.py; off-TPU the same math runs as a
    # single fused jnp pass (graceful fallback — no Pallas required).
    # NOTE: the fused opt_state layout differs from optax's chain state,
    # so flipping this over an existing checkpoint restores through the
    # lenient path (optimizer moments reset; params are untouched).
    fused_optimizer: bool = False
    # Static loss scaling for bf16 training (ModelConfig.compute_dtype):
    # the step multiplies the loss by this factor before the backward
    # pass and unscales the gradients after, lifting tiny gradients over
    # bf16 underflow. 1.0 = off, the right default for the TPU-style
    # bf16 recipe (f32 master weights, f32 grads out of the cast-site
    # VJPs) — the knob exists for stress runs. An overflowed scaled step
    # surfaces as non-finite grads and rides the skip_nonfinite guard.
    loss_scale: float = 1.0
    # Non-finite step guard (docs/robustness.md): the train step checks
    # loss/grad-norm finiteness in-graph and applies the optimizer update
    # under lax.cond — a NaN/Inf batch leaves params, opt_state, EMA, BN
    # stats, and the step counter UNCHANGED and sets metrics['skipped'],
    # with zero recompiles (the guard is part of the one compiled program).
    # Large-batch regimes make transient non-finite steps an expected
    # event, not an anomaly (arXiv:1711.04325). False removes the cond
    # (bitwise the unguarded step; NaN then poisons state permanently).
    skip_nonfinite: bool = True

    def __post_init__(self):
        if not 0.0 <= self.ema_decay < 1.0:
            raise ValueError(
                f"ema_decay must be in [0, 1); got {self.ema_decay} "
                "(1.0 would freeze the EMA at its random seed forever)")
        if not 0.0 <= self.random_erase <= 1.0:
            raise ValueError(
                f"random_erase is a PROBABILITY in [0, 1]; got "
                f"{self.random_erase} (mixup/cutmix use alpha-style "
                "knobs, this one does not)")
        if not self.loss_scale > 0.0:
            raise ValueError(
                f"loss_scale must be > 0; got {self.loss_scale} "
                "(1.0 disables scaling)")


@dataclasses.dataclass(frozen=True)
class RunConfig:
    """Training-loop + checkpoint settings (reference train.py:131-188)."""

    epochs: int = 100  # reference range(100), train.py:161
    ckpt_dir: str = "dtmodel/cp"  # reference train.py:136
    save_period: int = 5  # 'latest' every 5 epochs, train.py:183
    resume: bool = True
    # Initialize params/batch_stats from a torch checkpoint (reference-layout
    # ``{'state_dict': ...}`` file or bare state_dict; backbone family
    # auto-detected) via the converter + lenient restore. The reference
    # starts every backbone from pretrained torch weights
    # (nn/classifier.py:9-21); this is the switch-over path for those users.
    init_from: str = ""
    # Console/JSONL metric cadence. Every log forces a device->host scalar
    # readback that blocks dispatch, so logging every step serializes the
    # pipeline (round-2 finding: bench-grade throughput is unattainable at
    # 1). 50 keeps the readback off the steady-state critical path; the
    # progress-bar UX (reference train.py:67-68 updates every step) is
    # preserved via the async metrics buffer in train/loop.py.
    log_every_steps: int = 50
    # Collect the image ids of misclassified val samples each epoch
    # (Trainer.last_misclassified + a logged count). The per-sample
    # correctness vector is returned replicated from the sharded eval step —
    # GSPMD's all-gather over ICI — the fixed-shape redesign of the
    # reference's pickle all_gather of ragged per-sample data
    # (ddp_utils.py:16-56).
    collect_misclassified: bool = False
    # Per-class validation metrics: the eval step adds a fixed-shape [C,C]
    # confusion contraction (true x predicted counts, GSPMD-reduced like
    # every other eval sum); val_epoch logs exact global per-class accuracy
    # and saves the summed confusion matrix beside the metrics JSONL.
    # The aggregate view of the reference's misclassified-image analysis
    # (train.py:88-92).
    per_class_metrics: bool = False
    # Profiler trace dir ('' disables). The reference has no profiling at all
    # (SURVEY.md §5); jax.profiler makes it nearly free so it is first-class.
    profile_dir: str = ""
    profile_steps: int = 0
    # Install a SIGTERM latch (runtime/preemption.py): on pod preemption /
    # scheduler eviction the loop finishes its step, flushes a 'latest'
    # checkpoint, and returns instead of dying mid-epoch. The reference
    # loses everything since the last periodic save (SURVEY.md §5).
    handle_preemption: bool = True
    # Rollback on a non-finite streak (docs/robustness.md): when the
    # in-graph guard (OptimConfig.skip_nonfinite) has skipped this many
    # CONSECUTIVE steps, the Trainer stops grinding forward, restores the
    # last good checkpoint through the integrity ladder, and continues
    # from there. Detection rides the deferred metrics drain, so latency
    # is up to ~2 log intervals (log_every_steps). 0 disables detection.
    skip_threshold: int = 10
    rollback: bool = True
    # Give up after this many rollbacks in one fit() — persistent
    # non-finite data would otherwise loop restore->skip->restore forever.
    max_rollbacks: int = 3
    # After a rollback, ramp the LR linearly from ~0 back to the schedule
    # over this many steps (loss-spike hygiene per the large-batch
    # literature). Costs ONE retrace of the train step per rollback
    # (the optimizer schedule changes); 0 keeps the plain schedule and
    # stays retrace-free.
    rollback_rewarm_steps: int = 0
    seed: int = 0
    # -- telemetry (tpuic/telemetry, docs/observability.md) ------------
    # Stop after this many optimizer steps regardless of epochs (0 = no
    # cap). Smoke runs and the CI telemetry gate use it; a mid-epoch
    # stop skips the epoch's val pass.
    max_steps: int = 0
    # Telemetry event JSONL sink ('' disables): one line per bus event —
    # per-step time breakdown, skip/rollback/quarantine/checkpoint
    # events, compile durations, and the final goodput report.
    metrics_jsonl: str = ""
    # Triggered profiler traces (telemetry/tracing.py): when set, a step
    # slower than trace_threshold x the rolling median starts a
    # jax.profiler window of trace_steps steps under trace_dir, keeping
    # at most trace_keep traces. '' disables; the TPUIC_TRACE env var
    # overrides the dir AND forces one immediate window.
    trace_dir: str = ""
    trace_threshold: float = 3.0
    trace_steps: int = 3
    trace_keep: int = 4
    # Device-time attribution (telemetry/profile.py): auto-analyze every
    # captured trace window (and the run's full step history at fit()
    # end) into a per-op-class roofline waterfall published as 'profile'
    # events.  Off by default: the analysis AOT-compiles the train step
    # once for its HLO/cost-analysis view.
    trace_analyze: bool = False
    # Step-time SLOs (telemetry/slo.py): comma list of objective specs,
    # e.g. 'train_step:p99<=500ms@0.99'. Rolling attainment and
    # error-budget burn rate ride the goodput log line, the 'slo' bus
    # events, and the Prometheus exposition. '' disables.
    slo: str = ""
    # Async checkpoint commits (docs/robustness.md "Async checkpoint
    # commits"): a save stages its write and returns; the manifest walk
    # and the .new -> track rotation run on a background thread, so the
    # goodput 'checkpoint' bucket measures ~0 instead of the blocking
    # commit span. Deferred, never early — the track-level manifest that
    # gang.committed_steps / fleet_resume_step read still appears only
    # at rotation, so a rank can never advertise a commit the fleet
    # cannot restore. Multi-host runs fall back to synchronous commits
    # (the commit barrier is a collective and must stay on the main
    # thread). False restores blocking commits everywhere.
    async_checkpoint: bool = True


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Device-mesh axes.

    The reference's only strategy is data parallelism (train.py:128). We build
    the mesh with a ``data`` axis (batch sharding — the DDP equivalent), a
    ``seq`` axis (sequence/context parallelism: ring attention shards the
    token dim of attention-bearing models over it), and a ``model`` axis
    (Megatron-style tensor parallelism over attention heads / MLP hidden).
    seq=1, model=1 means pure DP — reference parity.
    data=0 => inferred from device count.
    """

    data: int = 0  # 0 => all devices / (seq * model)
    seq: int = 1
    model: int = 1
    axis_names: Sequence[str] = ("data", "seq", "model")
    # FSDP/ZeRO-3: shard large params + Adam moments over the data axis
    # (tpuic/parallel/sharding.py). False => replicated state, DDP semantics.
    fsdp: bool = False
    # ZeRO-1 weight-update sharding (arXiv:2004.13336): params replicated
    # (pure-DP forward, no weight gathers) but optimizer moments sharded
    # over 'data' — 1/N Adam memory and update compute per device, one
    # update all-gather per step. Subsumed by fsdp=True.
    zero1: bool = False
    # Map models' logical 'model' axis onto the mesh model axis (Megatron TP).
    # Only meaningful when model > 1.
    tensor_parallel: bool = True


@dataclasses.dataclass(frozen=True)
class Config:
    data: DataConfig = dataclasses.field(default_factory=DataConfig)
    model: ModelConfig = dataclasses.field(default_factory=ModelConfig)
    optim: OptimConfig = dataclasses.field(default_factory=OptimConfig)
    run: RunConfig = dataclasses.field(default_factory=RunConfig)
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)

    def replace(self, **kw) -> "Config":
        return dataclasses.replace(self, **kw)


def cifar10_config(data_dir: str = "") -> Config:
    """BASELINE.md parity config 1: ResNet-18 / CIFAR-10, single process."""
    return Config(
        data=DataConfig(data_dir=data_dir, resize_size=32, batch_size=128),
        model=ModelConfig(name="resnet18", num_classes=10),
        optim=OptimConfig(optimizer="adam", learning_rate=1e-3, class_weights=()),
    )


def imagenet_resnet50_config(data_dir: str = "") -> Config:
    """BASELINE.md parity config 2: ResNet-50 / ImageNet, data parallel."""
    return Config(
        data=DataConfig(data_dir=data_dir, resize_size=224, batch_size=256),
        model=ModelConfig(name="resnet50", num_classes=1000),
        optim=OptimConfig(optimizer="lars", learning_rate=4.8, class_weights=(),
                          weight_decay=1e-4, warmup_epochs=5),
        run=RunConfig(epochs=90),
    )
