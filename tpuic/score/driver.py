"""Elastic bulk-scoring driver: the whole corpus, exactly once, any deaths.

``python -m tpuic.score`` re-scores a packed image corpus against a
trained checkpoint at burst throughput, as one member of an elastic
gang of independent worker processes sharing a results directory:

- the corpus is split into fixed shards (tpuic/score/work.py plan);
- each worker leases shards (O_EXCL files, mtime-TTL liveness, PR-15
  membership-accelerated stealing), scores them through the serving
  engine's bucketed AOT executables (zero steady-state compiles,
  optional bf16/int8 quant rung), and commits each shard via the
  stage → link → CRC-manifest ladder (tpuic/score/commit.py);
- every committed shard is recorded in an append-only JSONL ledger
  (one durable ``JsonlSink`` stream per rank) the fleet aggregator
  audits offline: ``python -m tpuic.telemetry.fleet --score-ledger
  <dir>`` proves scored + quarantined == corpus, per shard and total.

Exactly-once = lease ∩ committed-manifest: a SIGKILL anywhere leaves
the shard either unpublished (rescored by the next lease holder),
published-without-manifest (adopted — the bytes are complete and
deterministic), or committed-without-ledger-record (recovered — the
next holder rescans every rank's stream under the lease and appends
the missing ``score_commit`` with ``recovered: true``).  A committed
shard is never rescored; an uncommitted one is never dropped.  Ledger
appends happen only while holding the shard's lease, so without
injected clock skew (``lease_skew``) each shard gets exactly one
``score_commit`` record fleet-wide; WITH skew the commit layer still
keeps the results exactly-once and the audit reports the duplicate
record loudly instead of double-counting silently.

Result rows are canonical bytes (commit.result_line: sorted keys,
%.6f probabilities) in corpus order, so a degraded-and-recovered run's
shard files are bitwise equal to an undisturbed single-worker run's —
the CI soak (scripts/score_soak.py) asserts exactly that.
"""

from __future__ import annotations

import argparse
import collections
import glob
import json
import os
import sys
import time
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from tpuic.runtime import faults
from tpuic.score import work
from tpuic.score.commit import ShardStore, result_line
from tpuic.score.work import DEFAULT_TTL_S


def _ledger_records(out_dir: str) -> List[dict]:
    """Every record in every rank's ledger stream (tolerant reader)."""
    from tpuic.telemetry.events import read_jsonl
    recs: List[dict] = []
    for path in sorted(glob.glob(os.path.join(out_dir, "*.jsonl"))):
        recs.extend(read_jsonl(path))
    return recs


def _recorded_shards(out_dir: str) -> Set[int]:
    return {int(r["shard"]) for r in _ledger_records(out_dir)
            if r.get("event") == "score_commit" and "shard" in r}


def _counts_from_result(path: str) -> Tuple[int, int]:
    """(scored, quarantined) re-derived from a published result file —
    the adopt path's row accounting (the file is complete by
    construction; see commit.py)."""
    from tpuic.telemetry.events import read_jsonl
    scored = quarantined = 0
    for rec in read_jsonl(path):
        if rec.get("quarantined"):
            quarantined += 1
        else:
            scored += 1
    return scored, quarantined


def _score_shard(packed, engine, shard: int, lo: int, hi: int,
                 batch_size: int, dtype: str, lease) -> Tuple[List[str],
                                                              int, int]:
    """Score rows [lo, hi) through the engine; returns (canonical
    lines in corpus order, scored, quarantined).

    Row integrity first: a packed row whose stored CRC32 no longer
    matches its bytes (at-rest bit-rot in the .bin) is quarantined into
    the ledger's accounting instead of being scored as garbage — the
    pack-time quarantine policy (data/pack.py) extended to read time.
    ``shard_corrupt`` (step = shard id, #PARAM = row offset in shard,
    default 0) injects exactly that verdict deterministically."""
    recs: Dict[int, dict] = {}
    quarantined = 0
    injected_row = None
    if faults.fire("shard_corrupt", step=shard):
        off = faults.param("shard_corrupt")
        injected_row = lo + int(off or 0)
    ok_rows: List[int] = []
    for i in range(lo, hi):
        if i == injected_row or not packed.verify_row(i):
            recs[i] = {"index": i, "id": packed.image_id(i),
                       "quarantined": True,
                       "reason": ("injected" if i == injected_row
                                  else "row_crc")}
            quarantined += 1
        else:
            ok_rows.append(i)

    def consume(fut, chunk) -> None:
        probs, order = fut.result()
        probs, order = np.asarray(probs), np.asarray(order)
        for j, i in enumerate(chunk):
            top = int(order[j, 0])
            recs[i] = {"index": i, "id": packed.image_id(i),
                       "label": int(packed.label(i)), "pred": top,
                       "prob": f"{probs[j, top]:.6f}"}

    pending = collections.deque()
    for k in range(0, len(ok_rows), batch_size):
        chunk = ok_rows[k:k + batch_size]
        imgs = packed.raw_batch(chunk)
        if dtype == "fp32":
            fut = engine.submit(imgs)
        else:
            fut = engine.submit(imgs, dtype=dtype)
        pending.append((fut, chunk))
        lease.renew(shard)
        while len(pending) >= 3:
            consume(*pending.popleft())
    while pending:
        consume(*pending.popleft())
    lines = [result_line(recs[i]) for i in range(lo, hi)]
    return lines, len(ok_rows), quarantined


def run_score(*, data_dir: str, out_dir: str, model_name: str = "",
              num_classes: int = 0, resize: int = 32,
              batch_size: int = 16, shard_size: int = 16,
              dtype: str = "int8", ckpt_dir: str = "", init_from: str = "",
              track: str = "best", fold: str = "val", cache_dir: str = "",
              ttl_s: float = DEFAULT_TTL_S, poll_s: float = 0.25,
              membership_file: Optional[str] = None, max_commits: int = 0,
              rank: Optional[int] = None, ranks: Optional[int] = None,
              _forward=None, log=print) -> dict:
    """One worker's whole life over the shared scoring job.

    Idempotent and elastic: run it once for a single-process job, run N
    with ``TPUIC_FLEET_RANK``/``TPUIC_FLEET_RANKS`` set for a gang, run
    it AGAIN after any kill to resume.  Returns the job summary (also
    published as the ``score_done`` ledger event).  ``max_commits``
    bounds fresh commits this life (tests simulate a bounded life
    without a SIGKILL); ``_forward`` injects a stub forward_fn in place
    of the checkpoint ladder (unit tests)."""
    from tpuic.config import DataConfig
    from tpuic.data.folder import ImageFolderDataset
    from tpuic.data.pack import pack_dataset
    from tpuic.serve import InferenceEngine, default_buckets
    from tpuic.telemetry.events import EventBus, JsonlSink
    from tpuic.telemetry.fleet import rank_stream_path, tag_bus_with_rank

    if membership_file is None:
        from tpuic.runtime.membership import ENV_MEMBERSHIP_FILE
        membership_file = os.environ.get(ENV_MEMBERSHIP_FILE, "")
    os.makedirs(out_dir, exist_ok=True)

    # A PRIVATE bus: score events must not leak into a co-resident
    # trainer's stream, and tests run several ranks in one process.
    bus = EventBus()
    rank, ranks = tag_bus_with_rank(bus=bus, rank=rank, ranks=ranks)
    sink = JsonlSink(rank_stream_path(os.path.join(out_dir,
                                                   "ledger.jsonl"), rank))
    unsub = bus.subscribe(sink)

    dcfg = DataConfig(data_dir=data_dir, resize_size=resize,
                      batch_size=batch_size, val_batch_size=batch_size,
                      cache_dir=cache_dir)
    ds = ImageFolderDataset(data_dir, fold, resize, dcfg,
                            allow_unlabeled=True)
    packed = pack_dataset(ds, cache_dir or os.path.join(
        data_dir, ".tpuic_pack"), verbose=False)
    n = len(packed)

    plan, created = work.write_or_verify_plan(
        out_dir, n=n, shard_size=shard_size,
        token=work.corpus_token(n, resize, [packed.image_id(i)
                                            for i in range(n)]),
        dtype=dtype)
    shards = [(int(lo), int(hi)) for lo, hi in plan["shards"]]
    bus.publish("score_plan", n=n, shards=len(shards),
                shard_size=int(plan["shard_size"]), dtype=dtype,
                corpus_token=int(plan["corpus_token"]), created=created,
                shard_table=[[lo, hi] for lo, hi in shards])

    if _forward is not None:
        engine = InferenceEngine(
            forward_fn=_forward, variables={}, image_size=resize,
            input_dtype=np.uint8, buckets=default_buckets(batch_size),
            max_wait_ms=0.0, queue_size=8)
    else:
        from tpuic import quant
        from tpuic.checkpoint.loading import load_inference_variables
        from tpuic.config import (Config, ModelConfig, OptimConfig,
                                  RunConfig)
        ncls = num_classes or packed.num_classes
        cfg = Config(data=dcfg,
                     model=ModelConfig(name=model_name, num_classes=ncls),
                     optim=OptimConfig(),
                     run=RunConfig(ckpt_dir=ckpt_dir, init_from=init_from))
        model, variables = load_inference_variables(
            cfg, track=track, log=lambda *a: log("[score]", *a))
        variants = {}
        if dtype != "fp32":
            variants = {k: v for k, v in quant.serve_variants(
                model, variables, (dtype,), normalize=True,
                mean=dcfg.mean, std=dcfg.std).items() if k != "fp32"}
        engine = InferenceEngine(
            model, variables, image_size=resize, input_dtype=np.uint8,
            normalize=True, mean=dcfg.mean, std=dcfg.std,
            buckets=default_buckets(batch_size), max_wait_ms=0.0,
            queue_size=8, variants=variants)
    engine.warmup()
    # Zero the compile counter AFTER warmup: everything the steady loop
    # compiles from here on is a contract violation the soak asserts on.
    engine.stats.reset()

    lease = work.LeaseDir(out_dir, rank, ttl_s=ttl_s)
    store = ShardStore(out_dir, rank)
    recorded: Set[int] = _recorded_shards(out_dir)
    recovered_records = 0
    # Ranks start their sweep at different offsets so a healthy gang
    # mostly avoids lease contention without any coordination.
    start = (rank * len(shards)) // max(ranks, 1)
    t0 = time.perf_counter()
    halted = False

    while not halted:
        progress = False
        outstanding = False
        active = work.active_ranks(membership_file)
        for k in range(len(shards)):
            s = (start + k) % len(shards)
            lo, hi = shards[s]
            if store.state(s) == "committed" and s in recorded:
                continue
            outstanding = True
            if not lease.acquire(s, active):
                continue
            try:
                st = store.state(s)  # re-judge under the lease
                recovered = False
                if st == "corrupt":
                    # Manifest and bytes disagree (at-rest rot): the
                    # integrity ladder's refuse-and-redo rung.
                    bus.publish("score_shard", shard=s, lo=lo, hi=hi,
                                action="rescore_corrupt")
                    store.discard(s)
                    st = "missing"
                if st == "missing":
                    bus.publish("score_shard", shard=s, lo=lo, hi=hi,
                                action="score")
                    lines, scored, quar = _score_shard(
                        packed, engine, s, lo, hi, batch_size, dtype,
                        lease)
                    verdict, man = store.commit(s, lo, hi, lines, scored,
                                                quar)
                    if verdict == "committed":
                        bus.publish("score_commit", shard=s, lo=lo, hi=hi,
                                    scored=man["scored"],
                                    quarantined=man["quarantined"],
                                    size=man["size"], crc32=man["crc32"],
                                    recovered=False)
                        recorded.add(s)
                    else:
                        # Lost the link race (lease_skew / steal-steal):
                        # the winner's record is theirs to write; ours
                        # is only the loud evidence of double work.
                        bus.publish("score_duplicate", shard=s,
                                    lo=lo, hi=hi)
                    progress = True
                elif st == "orphan":
                    bus.publish("score_shard", shard=s, lo=lo, hi=hi,
                                action="adopt")
                    scored, quar = _counts_from_result(
                        store.result_path(s))
                    store.adopt(s, lo, hi, scored, quar)
                    recovered = True
                    progress = True
                if store.state(s) == "committed" and s not in recorded:
                    # Committed but unrecorded (crashed after manifest,
                    # or adopted just now): rescan EVERY rank's stream
                    # under the lease, then append the missing record.
                    recorded |= _recorded_shards(out_dir)
                    if s not in recorded:
                        man = store.manifest(s) or {}
                        bus.publish("score_commit", shard=s, lo=lo, hi=hi,
                                    scored=man.get("scored"),
                                    quarantined=man.get("quarantined"),
                                    size=man.get("size"),
                                    crc32=man.get("crc32"),
                                    recovered=True)
                        recorded.add(s)
                        recovered_records += 1
                        progress = True
            finally:
                lease.release(s)
            if max_commits and store.commits >= max_commits:
                halted = True
                break
        if halted or not outstanding:
            break
        if not progress:
            # Everything left is leased to peers: wait for them to
            # finish, die, or leave the membership, then resweep.
            time.sleep(poll_s)
            recorded |= _recorded_shards(out_dir)

    manifests = [store.manifest(s) for s in range(len(shards))]
    done = [m for m in manifests if m is not None]
    summary = {
        "n": n, "shards": len(shards),
        "shards_committed": sum(1 for s in range(len(shards))
                                if store.state(s) == "committed"),
        "rows_scored": sum(int(m["scored"]) for m in done),
        "rows_quarantined": sum(int(m["quarantined"]) for m in done),
        "commits_this_life": store.commits,
        "duplicates_this_life": store.duplicates,
        "steals_this_life": lease.steals,
        "recovered_records": recovered_records,
        "steady_compiles": int(engine.stats.snapshot()["compiles"]),
        "dtype": dtype, "halted": bool(halted),
        "wall_s": round(time.perf_counter() - t0, 3),
    }
    bus.publish("score_done", **summary)
    engine.close()
    sink.close()
    unsub()
    return summary


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tpuic.score", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--datadir", required=True)
    p.add_argument("--out", required=True,
                   help="shared scoring workdir (plan, leases, results, "
                        "manifests, per-rank ledgers)")
    p.add_argument("--fold", default="val")
    p.add_argument("--model", default="auto",
                   help="backbone name, or 'auto' to read the single "
                        "trained model's config.json under --ckpt-dir")
    p.add_argument("--num-classes", type=int, default=0)
    p.add_argument("--resize", type=int, default=None)
    p.add_argument("--batchsize", type=int, default=16)
    p.add_argument("--shard-size", type=int, default=16,
                   help="corpus rows per shard (the lease/commit unit)")
    p.add_argument("--dtype", default="int8",
                   choices=("fp32", "bf16", "int8"),
                   help="quant ladder rung to score with")
    p.add_argument("--ckpt-dir", default="dtmodel/cp")
    p.add_argument("--track", default="best", choices=("best", "latest"))
    p.add_argument("--init-from", default="",
                   help="torch checkpoint instead of a tpuic one")
    p.add_argument("--ttl", type=float, default=DEFAULT_TTL_S,
                   help="lease TTL seconds (liveness horizon for steals)")
    p.add_argument("--poll", type=float, default=0.25,
                   help="idle resweep interval while peers hold leases")
    p.add_argument("--prom-dump", default="",
                   help="write tpuic_score_* Prometheus exposition here "
                        "at exit")
    args = p.parse_args(argv)

    model, num_classes, resize = args.model, args.num_classes, args.resize
    if model == "auto":
        from tpuic.predict import resolve_model_auto
        saved = resolve_model_auto(args.ckpt_dir)
        model = saved["name"]
        num_classes = num_classes or saved["num_classes"]
        if resize is None:
            resize = saved["resize_size"]
        print(f"[score] auto-resolved model '{model}' "
              f"(num_classes={num_classes}, resize={resize}) from "
              f"{args.ckpt_dir}")
    if resize is None:
        resize = 299  # the reference's hard-coded size (train.py:110)

    summary = run_score(
        data_dir=args.datadir, out_dir=args.out, model_name=model,
        num_classes=num_classes, resize=resize, batch_size=args.batchsize,
        shard_size=args.shard_size, dtype=args.dtype,
        ckpt_dir=args.ckpt_dir, init_from=args.init_from, track=args.track,
        fold=args.fold, ttl_s=args.ttl, poll_s=args.poll)
    print(json.dumps(summary))
    if args.prom_dump:
        from tpuic.telemetry.prom import render, score_rows, \
            write_exposition
        write_exposition(args.prom_dump, render(score_rows(summary)))
        print(f"[score] prom exposition -> {args.prom_dump}")
    ok = (summary["shards_committed"] == summary["shards"]
          and not summary["halted"])
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
