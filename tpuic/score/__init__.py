"""tpuic.score — elastic, exactly-once bulk scoring over a packed corpus.

The offline workload counterpart of tpuic.serve: ``python -m
tpuic.score`` re-scores an image corpus against a trained checkpoint as
a gang of independent workers sharing a results directory — shard
leases for work distribution (rank loss degrades throughput, never the
job), the checkpoint integrity ladder for per-shard commits (SIGKILL
anywhere resumes without re-scoring or dropping a shard), and an
append-only per-rank ledger ``python -m tpuic.telemetry.fleet
--score-ledger`` audits (scored + quarantined == corpus, duplicates
loud).  docs/robustness.md "Bulk scoring" is the design reference.

Re-exports resolve lazily (the tpuic/__init__.py idiom): the lease and
commit layers are stdlib-only; the driver pulls numpy/jax.
"""

from __future__ import annotations

_LAZY = {
    "LeaseDir": ("tpuic.score.work", "LeaseDir"),
    "plan_shards": ("tpuic.score.work", "plan_shards"),
    "write_or_verify_plan": ("tpuic.score.work", "write_or_verify_plan"),
    "ShardStore": ("tpuic.score.commit", "ShardStore"),
    "result_line": ("tpuic.score.commit", "result_line"),
    "run_score": ("tpuic.score.driver", "run_score"),
    "main": ("tpuic.score.driver", "main"),
}

__all__ = sorted(_LAZY)


def __getattr__(name):
    try:
        mod_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None
    import importlib
    return getattr(importlib.import_module(mod_name), attr)


def __dir__():
    return __all__
