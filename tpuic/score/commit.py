"""Per-shard result commits: stage → link → CRC manifest, exactly once.

The checkpoint manager's integrity ladder (checkpoint/manager.py: stage
the complete artifact, fingerprint it, atomically rotate it into place,
verify before trusting) applied to shard results, with one twist — a
shard may be scored by TWO live ranks at once (expired-lease steal,
``lease_skew`` clock drift), so the rotation step must also be the
arbitration step:

1. **Stage**: the shard's result lines are written to a token-unique
   tmp file, flushed and fsynced — the staged file is COMPLETE before
   step 2, so a result file, once visible, is never torn.
2. **Link** (the rotate rung): ``os.link(tmp, final)`` publishes it.
   Hard-link creation is atomic and fails with EEXIST if the name
   exists — of N concurrent committers exactly one wins; losers get a
   typed ``duplicate`` verdict (their bytes are identical anyway:
   result content is deterministic per shard).
3. **Manifest**: ``manifests/shard-NNNNN.json`` — size + CRC32 of the
   published file plus the row accounting (scored/quarantined counts),
   written atomically (checkpoint/manager.py ``_atomic_json``).  A
   shard is *committed* iff its manifest exists AND the result file
   re-hashes to it — the exactly-once set is the lease ∩ manifest
   intersection the driver resumes from.

Crash windows (who repairs what, always under the shard's lease):

- died between stage and link → an orphaned ``*.tmp.*`` nobody trusts;
  the next holder rescores.
- died between link and manifest (the ``scorer_crash`` fault's window)
  → result-without-manifest; the next holder **adopts** it: the staged
  file was complete by construction, so it re-hashes the bytes and
  writes the missing manifest instead of rescoring.
- manifest that no longer matches its file (at-rest bit-rot,
  ``corrupt_file``) → ``discard()`` both under lease and rescore.
"""

from __future__ import annotations

import json
import os
import signal
import uuid
import zlib
from typing import Dict, List, Optional, Tuple

from tpuic.checkpoint.manager import _atomic_json
from tpuic.runtime import faults


def _file_crc(path: str) -> Tuple[int, int]:
    """(size, crc32) of ``path`` — the manager's chunked fingerprint
    discipline (bit-rot and torn writes, not adversaries)."""
    crc = 0
    size = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
            size += len(chunk)
    return size, crc


def result_line(rec: Dict) -> str:
    """Canonical byte encoding of one result row: sorted keys, no
    whitespace, probabilities pre-formatted as %.6f STRINGS by the
    caller — identical row facts encode to identical bytes on every
    rank, which is what makes the link-arbitrated duplicate commit
    harmless and the soak's bitwise-equality assertion meaningful."""
    return json.dumps(rec, sort_keys=True, separators=(",", ":")) + "\n"


class ShardStore:
    """Results + manifests for one scoring job's workdir."""

    def __init__(self, workdir: str, rank: int) -> None:
        self.results_dir = os.path.join(workdir, "results")
        self.manifest_dir = os.path.join(workdir, "manifests")
        os.makedirs(self.results_dir, exist_ok=True)
        os.makedirs(self.manifest_dir, exist_ok=True)
        self.rank = int(rank)
        self.commits = 0      # shards THIS life linked (scorer_crash step)
        self.duplicates = 0   # commits we lost to a faster rank

    def result_path(self, shard: int) -> str:
        return os.path.join(self.results_dir,
                            f"shard-{int(shard):05d}.jsonl")

    def manifest_path(self, shard: int) -> str:
        return os.path.join(self.manifest_dir,
                            f"shard-{int(shard):05d}.json")

    def manifest(self, shard: int) -> Optional[dict]:
        try:
            with open(self.manifest_path(shard)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def state(self, shard: int) -> str:
        """``committed`` (manifest present and the result re-hashes to
        it), ``corrupt`` (manifest disagrees with the bytes — at-rest
        rot; discard + rescore), ``orphan`` (result published without a
        manifest — the winner died in the scorer_crash window; adopt),
        or ``missing``."""
        have_result = os.path.exists(self.result_path(shard))
        man = self.manifest(shard)
        if man is not None and have_result:
            size, crc = _file_crc(self.result_path(shard))
            if size == man.get("size") and crc == man.get("crc32"):
                return "committed"
            return "corrupt"
        if man is not None:  # manifest without bytes: equally untrusted
            return "corrupt"
        if have_result:
            return "orphan"
        return "missing"

    def discard(self, shard: int) -> None:
        """Drop a corrupt result + manifest pair (caller holds the
        lease) so the shard re-enters the queue as ``missing``."""
        for p in (self.manifest_path(shard), self.result_path(shard)):
            try:
                os.unlink(p)
            except OSError:
                pass

    def _write_manifest(self, shard: int, lo: int, hi: int, scored: int,
                        quarantined: int, *, adopted: bool) -> dict:
        size, crc = _file_crc(self.result_path(shard))
        man = {"shard": int(shard), "lo": int(lo), "hi": int(hi),
               "rows": int(hi - lo), "scored": int(scored),
               "quarantined": int(quarantined), "size": size,
               "crc32": crc, "rank": self.rank, "adopted": bool(adopted)}
        _atomic_json(self.manifest_path(shard), man)
        return man

    def commit(self, shard: int, lo: int, hi: int, lines: List[str],
               scored: int, quarantined: int) -> Tuple[str, dict]:
        """Stage + link + manifest for a freshly scored shard.

        Returns ``(verdict, manifest)`` with verdict ``committed`` (we
        won the link) or ``duplicate`` (another rank's identical result
        was already published; we adopt its manifest, writing it if the
        winner died inside the scorer_crash window)."""
        final = self.result_path(shard)
        tmp = os.path.join(self.results_dir,
                           f".shard-{int(shard):05d}.tmp.{uuid.uuid4().hex}")
        with open(tmp, "w") as f:
            f.writelines(lines)
            f.flush()
            os.fsync(f.fileno())
        try:
            os.link(tmp, final)
            won = True
        except FileExistsError:
            won = False
        finally:
            os.unlink(tmp)
        if won:
            self.commits += 1
            # The SIGKILL-between-link-and-manifest window
            # (docs/robustness.md "Bulk scoring"): step is this life's
            # 1-based shard-commit ordinal, #PARAM the victim rank
            # (default 0, the rank_crash convention). The dead rank's
            # published-but-unmanifested result is what the adopt path
            # exists for.
            if faults.fire("scorer_crash", step=self.commits):
                target = faults.param("scorer_crash")
                if self.rank == int(target or 0):
                    os.kill(os.getpid(), signal.SIGKILL)
            man = self._write_manifest(shard, lo, hi, scored, quarantined,
                                       adopted=False)
            return "committed", man
        self.duplicates += 1
        man = self.manifest(shard)
        if man is None:
            # Winner died in the scorer_crash window; its bytes are
            # deterministic (== ours), so finish ITS commit.
            man = self._write_manifest(shard, lo, hi, scored, quarantined,
                                       adopted=True)
        return "duplicate", man

    def adopt(self, shard: int, lo: int, hi: int, scored: int,
              quarantined: int) -> dict:
        """Write the missing manifest for an orphaned (published,
        complete-by-construction) result file the caller re-derived the
        row accounting for.  Caller holds the shard's lease."""
        return self._write_manifest(shard, lo, hi, scored, quarantined,
                                    adopted=True)
