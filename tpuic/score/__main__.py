"""``python -m tpuic.score`` — the elastic bulk-scoring worker CLI."""

import sys

from tpuic.score.driver import main

if __name__ == "__main__":
    sys.exit(main())
