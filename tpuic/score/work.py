"""Shard work-queue + file leases: who scores what, survivable by design.

The bulk scorer (tpuic/score/driver.py) splits the packed corpus into
fixed-size shards and lets the elastic gang's ranks claim them through
two filesystem primitives — no coordinator process, no RPC, nothing
that can die and take the queue with it:

- **The plan** (``plan.json``): the shard table ``[(lo, hi), ...]`` plus
  a corpus token (n, image size, image-id CRC).  Written once with
  ``O_CREAT | O_EXCL`` — first worker wins, every later worker (and
  every resumed life) must read back an IDENTICAL plan or fail loudly:
  two workers scoring different shard geometries into one results dir
  would corrupt the exactly-once accounting silently.
- **Leases** (``leases/shard-NNNNN.lease``): a shard is claimed by
  ``O_CREAT | O_EXCL``-creating its lease file (atomic on POSIX — two
  racers get exactly one winner).  The lease carries the owner's rank
  and a random token; liveness is the file's **mtime** against the
  owner's declared TTL, renewed with ``os.utime`` between batches.  A
  dead rank stops renewing, the lease ages out, and any survivor
  **steals** it (tmp + rename, then read-back of the token to detect a
  steal/steal race).  The PR-15 membership file accelerates the steal:
  a lease whose owner is no longer in the active set is orphaned NOW,
  not a TTL from now.

The lease is a work-partitioning optimization, not the correctness
boundary: clock skew (``lease_skew`` fault) or a steal/steal race can
make two live ranks score the same shard concurrently, and the commit
layer (tpuic/score/commit.py ``os.link`` first-wins) still keeps the
results exactly-once.  docs/robustness.md "Bulk scoring" has the state
machine.
"""

from __future__ import annotations

import json
import os
import time
import uuid
import zlib
from typing import List, Optional, Sequence, Tuple

from tpuic.runtime import faults

_PLAN_VERSION = 1

# Default lease TTL. Long enough that a healthy rank renewing once per
# device batch never ages out; short enough that a dead rank's shard is
# back in the queue within one human sigh. Membership-informed steals
# don't wait for it.
DEFAULT_TTL_S = 30.0


def plan_shards(n: int, shard_size: int) -> List[Tuple[int, int]]:
    """``[(lo, hi), ...]`` half-open row ranges covering ``0..n``."""
    if n <= 0:
        raise ValueError(f"plan_shards: empty corpus (n={n})")
    if shard_size <= 0:
        raise ValueError(f"plan_shards: shard_size must be > 0 "
                         f"(got {shard_size})")
    return [(lo, min(lo + shard_size, n)) for lo in range(0, n, shard_size)]


def corpus_token(n: int, size: int, image_ids: Sequence[str]) -> int:
    """Cheap corpus identity: CRC32 over (n, image size, every id) — the
    guard against two workers scoring DIFFERENT corpora into one
    results directory (wrong --datadir, stale pack)."""
    crc = zlib.crc32(f"{n}:{size}".encode())
    for iid in image_ids:
        crc = zlib.crc32(str(iid).encode(), crc)
    return crc


def plan_path(workdir: str) -> str:
    return os.path.join(workdir, "plan.json")


def write_or_verify_plan(workdir: str, *, n: int, shard_size: int,
                         token: int, dtype: str) -> Tuple[dict, bool]:
    """Create ``plan.json`` first-wins, or verify the existing one.

    Returns ``(plan, created)``.  ``created`` is True only for the one
    worker whose O_EXCL create won; everyone else (including every
    resumed life) reads the winner's plan back and must find the same
    (n, shard_size, corpus token, dtype) — a geometry or corpus mismatch
    raises instead of silently interleaving two jobs' shards.
    """
    os.makedirs(workdir, exist_ok=True)
    path = plan_path(workdir)
    plan = {"version": _PLAN_VERSION, "n": int(n),
            "shard_size": int(shard_size), "corpus_token": int(token),
            "dtype": str(dtype),
            "shards": [[lo, hi] for lo, hi in plan_shards(n, shard_size)]}
    tmp = f"{path}.tmp.{uuid.uuid4().hex}"
    with open(tmp, "w") as f:
        json.dump(plan, f)
    try:
        # Atomic first-wins claim of the plan slot: link the complete tmp
        # into place; EEXIST means another worker (or a prior life)
        # already planned — verify against it below.
        os.link(tmp, path)
        created = True
    except FileExistsError:
        created = False
    finally:
        os.unlink(tmp)
    with open(path) as f:
        existing = json.load(f)
    for key in ("version", "n", "shard_size", "corpus_token", "dtype"):
        if existing.get(key) != plan[key]:
            raise ValueError(
                f"score plan mismatch at {path}: {key}={existing.get(key)!r}"
                f" on disk vs {plan[key]!r} requested — this results dir "
                "belongs to a different job/corpus; refusing to mix")
    return existing, created


class LeaseDir:
    """The lease protocol over ``{workdir}/leases`` for one rank."""

    def __init__(self, workdir: str, rank: int,
                 ttl_s: float = DEFAULT_TTL_S) -> None:
        self.dir = os.path.join(workdir, "leases")
        os.makedirs(self.dir, exist_ok=True)
        self.rank = int(rank)
        self.ttl_s = float(ttl_s)
        self.token = uuid.uuid4().hex
        self.steals = 0

    def path(self, shard: int) -> str:
        return os.path.join(self.dir, f"shard-{int(shard):05d}.lease")

    def _payload(self) -> str:
        return json.dumps({"rank": self.rank, "token": self.token,
                           "ttl_s": self.ttl_s, "t": time.time()})

    def owner(self, shard: int) -> Optional[dict]:
        """The lease record on disk, or None (absent/torn — a torn lease
        reads as absent: it was mid-write, the writer owns the race)."""
        try:
            with open(self.path(shard)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _expired(self, shard: int,
                 active: Optional[Sequence[int]] = None) -> bool:
        """Whether the shard's lease is stealable: its owner left the
        membership's active set, or its mtime aged past the OWNER's
        declared TTL.  The ``lease_skew`` fault (param = skew seconds,
        default one full TTL) ages every observed lease — the
        clock-drift double-claim the commit layer must absorb."""
        p = self.path(shard)
        try:
            st = os.stat(p)
        except OSError:
            return False  # gone: release beat us; acquire, don't steal
        rec = self.owner(shard)
        if rec is None:
            # Mid-write by a live racer; let the TTL clock judge it.
            rec = {}
        if active is not None and rec.get("rank") is not None \
                and int(rec["rank"]) not in set(int(a) for a in active):
            return True
        ttl = float(rec.get("ttl_s", self.ttl_s))
        age = time.time() - st.st_mtime
        if faults.fire("lease_skew", step=int(shard)):
            skew = faults.param("lease_skew")
            age += float(skew) if skew is not None else ttl + 1.0
        return age > ttl

    def acquire(self, shard: int,
                active: Optional[Sequence[int]] = None) -> bool:
        """Claim ``shard``: O_EXCL create, else steal an expired lease.
        True iff this rank now holds it."""
        p = self.path(shard)
        try:
            fd = os.open(p, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            if not self._expired(shard, active):
                return False
            return self._steal(shard)
        with os.fdopen(fd, "w") as f:
            f.write(self._payload())
        return True

    def _steal(self, shard: int) -> bool:
        """Replace an expired lease with our own (tmp + rename), then
        read back: if the surviving token is not ours, a concurrent
        stealer's rename landed after ours — they own it, we back off.
        The loser of the read-back race may still have scored a few
        rows; the commit layer dedups that work."""
        p = self.path(shard)
        tmp = f"{p}.tmp.{self.token}"
        with open(tmp, "w") as f:
            f.write(self._payload())
        os.replace(tmp, p)
        rec = self.owner(shard)
        if rec is not None and rec.get("token") == self.token:
            self.steals += 1
            return True
        return False

    def renew(self, shard: int) -> bool:
        """Refresh our lease's mtime (between batches).  False when the
        lease is no longer ours — the holder should abandon the shard
        (its work will be deduped at commit if it races the thief)."""
        rec = self.owner(shard)
        if rec is None or rec.get("token") != self.token:
            return False
        try:
            os.utime(self.path(shard))
            return True
        except OSError:
            return False

    def release(self, shard: int) -> None:
        """Drop our lease (only ours — never unlink a thief's)."""
        rec = self.owner(shard)
        if rec is not None and rec.get("token") == self.token:
            try:
                os.unlink(self.path(shard))
            except OSError:
                pass


def active_ranks(membership_file: str) -> Optional[List[int]]:
    """The membership file's current active set, or None when elastic
    membership isn't wired (no file configured / not yet written) — the
    lease layer then falls back to pure TTL expiry."""
    if not membership_file:
        return None
    from tpuic.runtime.membership import read_membership
    m = read_membership(membership_file)
    if m is None:
        return None
    return [int(r) for r in m.active]
