"""Graceful-preemption guard: SIGTERM -> checkpoint -> clean exit.

TPU pods (and most cluster schedulers) deliver SIGTERM with a grace window
before killing the worker. The reference has no preemption story at all —
a killed rank loses everything since the last periodic save and wedges the
other ranks' NCCL collectives (SURVEY.md §5 "Failure detection: Absent").
Here the Trainer polls this guard between steps; on a pending signal it
saves a ``latest`` checkpoint at the current epoch and returns instead of
dying mid-write. Resume then continues from that epoch.

The flag-poll design (rather than doing work inside the handler) is
deliberate: Python signal handlers run between bytecodes on the main
thread, and checkpoint saving from inside a handler could re-enter Orbax
mid-save. The handler only records; the training loop acts.
"""

from __future__ import annotations

import signal
import threading
from typing import Iterable


class PreemptionGuard:
    """Installable SIGTERM (by default) latch.

    Usage::

        guard = PreemptionGuard().install()
        ...
        if guard.triggered:  # between steps / epochs
            save_and_exit()

    ``install`` chains any previously-installed handler (so outer runtimes
    still observe the signal) and is a no-op off the main thread, where
    CPython forbids signal.signal.
    """

    def __init__(self, signals: Iterable[int] = (signal.SIGTERM,)) -> None:
        self._signals = tuple(signals)
        self._event = threading.Event()
        self._prev = {}
        self._installed = False

    def _handler(self, signum, frame):
        self._event.set()
        prev = self._prev.get(signum)
        if callable(prev):
            prev(signum, frame)

    def install(self) -> "PreemptionGuard":
        if not self._installed:
            # Fresh span: a latch left set by a PREVIOUS install/uninstall
            # span (fit() N-1's SIGTERM — uninstall deliberately leaves the
            # flag readable so callers can branch on it post-span) must not
            # make a reused guard report 'triggered' at step 0 of the next
            # fit(). Cleared only when beginning a new span — a trigger()
            # fired after install() (cooperative shutdown, tests) survives
            # the re-entrant install() calls an installed guard sees.
            self._event.clear()
            # Span state is marked BEFORE the thread check: off the main
            # thread no handler can be registered, but the span is still
            # begun — otherwise every re-entrant install() there would
            # re-run the clear above and wipe a cooperative trigger().
            self._installed = True
        # Handler registration is tracked separately (by _prev) from the
        # span flag: a span begun off the main thread still gets its
        # handlers when a later install() runs ON the main thread — e.g.
        # a guard constructed in a worker and handed to fit() — without
        # re-clearing a latch set in between.
        if self._prev or threading.current_thread() is not threading.main_thread():
            return self  # registered already / signal.signal would raise
        for s in self._signals:
            try:
                self._prev[s] = signal.signal(s, self._handler)
            except (ValueError, OSError):  # exotic embedding; stay inert
                pass
        return self

    def uninstall(self) -> None:
        for s, prev in self._prev.items():
            try:
                signal.signal(s, prev if prev is not None else signal.SIG_DFL)
            except (ValueError, OSError):
                pass
        self._prev.clear()
        self._installed = False

    @property
    def triggered(self) -> bool:
        return self._event.is_set()

    def trigger(self) -> None:
        """Programmatic trip (tests; cooperative shutdown)."""
        self._event.set()


def agree(flag: bool) -> bool:
    """Cross-host OR of the local latch.

    On a multi-host pod the scheduler delivers SIGTERM per host, at
    slightly different times (or to a subset). A host that acted on its
    LOCAL flag alone would leave the step loop while the others enter the
    next step's collectives — a mutual hang that burns the whole grace
    window (the exact wedge this module exists to avoid). So the loop only
    acts on the flag at common step boundaries, through this agreement:
    every host calls agree() at the same point, the flags are OR-reduced
    across processes, and all hosts see the same verdict. Single-process
    runs pay nothing.
    """
    import jax

    if jax.process_count() == 1:
        return bool(flag)
    import numpy as np
    from jax.experimental import multihost_utils

    return bool(np.any(multihost_utils.process_allgather(
        np.asarray([bool(flag)]))))
