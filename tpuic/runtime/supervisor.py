"""Training supervisor: heartbeat watchdog, hang detection, auto-restart.

Every recovery path PR 2 shipped is *cooperative* — the trainer must stay
alive to poll the ``PreemptionGuard`` latch, roll back a non-finite step,
or quarantine a sample. A hard crash, a wedged device call, or a
data-pipeline deadlock still loses the run: exactly the "killed rank
wedges the other ranks' collectives" failure the reference inherits
(SURVEY.md §5). Multi-hour runs on preemptible fleets are the operating
point of the large-batch ImageNet literature (arXiv:1711.04325,
arXiv:1511.00175) — a production system must survive the *process*
dying, not just the loss going NaN.

This module is the out-of-process half of that story
(``python -m tpuic.supervise``):

- **Heartbeat protocol.** The trainer publishes ``step``/``eval``/
  ``checkpoint_commit``/... events on the telemetry bus anyway; when
  ``TPUIC_HEARTBEAT_FILE`` is set (the supervisor sets it for its
  child), a :class:`HeartbeatWriter` sink rewrites that file atomically
  (tmp + rename) with the last global step and wall time. Pure host-side
  piggybacking on the existing deferred drain: zero new device syncs,
  zero compiles (asserted with the ``tpuic.analysis.runtime`` checkers
  in tests/test_supervisor.py).
- **Liveness enforcement.** No heartbeat change within ``watchdog_s``
  (``startup_grace_s`` before the first beat — imports and the first
  compile are legitimately silent) → the child is declared hung:
  SIGQUIT first (the trainer registers a ``faulthandler`` all-thread
  stack dump at startup — :func:`install_stack_dump_handler`), then
  SIGTERM for the PR-2 preemption flush, then SIGKILL after ``grace_s``.
- **Exit-code contract** (the branch table on child death):

  ====================  =====  ==========================================
  meaning               code   supervisor action
  ====================  =====  ==========================================
  clean completion      0      exit 0
  clean preemption      43     restart with resume (no backoff) — or
  flush                        exit 43 when the supervisor itself was
                               SIGTERMed (the eviction is shared)
  non-retryable poison  44     exit 44 with the child's diagnosis (e.g.
                               rollback budget exhausted, every
                               integrity-ladder rung corrupt)
  anything else         *      retryable crash: restart with ``--resume``
  (incl. signal death)         under an exponential-backoff restart
                               budget
  ====================  =====  ==========================================

- **Crash-loop policy.** The supervisor keeps a cross-restart progress
  ledger (JSONL). An attempt only counts as *useful* when the child's
  best global step advanced past the best of all previous attempts;
  ``crash_loop_k`` consecutive attempts with no step progress — whatever
  their exit codes — declare a crash loop and the supervisor gives up
  with exit 45 and a non-retryable diagnosis instead of restarting
  forever. (Clean preemption flushes are exempt from the restart
  *budget*, not from this: a preemption that re-fires before any step
  lands would otherwise respawn unboundedly at full speed.) The ledger also
  flags step-accounting violations: a resumed attempt whose first
  heartbeat step jumps PAST the previous attempt's last step would mean
  steps were silently skipped (``Trainer._validated_start_step`` is the
  in-process half of that contract).

This module imports only the stdlib on purpose: the supervisor parent
must never initialize jax (it would grab the device the child needs, and
a supervisor must outlive any backend wedge its child hits).
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

# -- exit-code contract ------------------------------------------------------
# Child codes (train.py maps its outcomes onto these; the supervisor
# branches on them). 43+ to stay clear of shell/python conventions
# (1 generic, 2 usage, 126-165 signal/permission ranges).
EXIT_OK = 0
EXIT_PREEMPTED = 43   # clean preemption flush: state is on disk, resume me
EXIT_POISON = 44      # non-retryable: restarting cannot help
EXIT_CRASH_LOOP = 45  # supervisor verdict: retries exhausted / no progress
EXIT_BELOW_MIN = 46   # elastic gang verdict: fleet fell below min replicas

# Environment protocol between supervisor and child.
ENV_HEARTBEAT_FILE = "TPUIC_HEARTBEAT_FILE"
ENV_HEARTBEAT_INTERVAL = "TPUIC_HEARTBEAT_INTERVAL_S"
ENV_STACK_DUMP = "TPUIC_STACK_DUMP"
ENV_FLIGHT_DUMP = "TPUIC_FLIGHT_DUMP"  # telemetry/flight.py reads it
ENV_RESTART = "TPUIC_RESTART"
ENV_DOWN_SINCE = "TPUIC_DOWN_SINCE"
# Fleet-consistent resume cap (runtime/gang.py): on a gang restart the
# supervisor computes the newest checkpoint step every rank's committed
# manifest agrees on and passes it here; CheckpointManager.restore_into
# then refuses rungs ahead of it, so no rank resumes past the fleet.
ENV_RESUME_STEP = "TPUIC_RESUME_STEP"


class NonRetryableError(RuntimeError):
    """A failure restarting cannot fix (rollback budget exhausted, every
    checkpoint rung corrupt, bad config): train.py maps it to
    ``EXIT_POISON`` so the supervisor reports instead of retrying.
    Subclasses RuntimeError — existing handlers and tests that match the
    message keep working."""


# -- heartbeat protocol ------------------------------------------------------
class HeartbeatWriter:
    """Telemetry-bus sink that mirrors liveness into an atomically
    rewritten file: ``{"step", "t", "pid", "beats"}``.

    Subscribes to every event kind (any bus activity proves the process
    is alive; ``step`` events additionally carry progress), throttled to
    one write per ``min_interval_s`` so millisecond steps don't turn the
    heartbeat into an I/O load. Each actual write publishes a
    ``heartbeat`` event back on the bus (guarded against self-echo), so
    supervised runs record their own beats in ``--metrics-jsonl``.

    Everything here is host-side file I/O on data the caller already
    has: no jax import, no device syncs, no compiles.
    """

    def __init__(self, path: str, min_interval_s: float = 1.0,
                 publish: Optional[Callable] = None) -> None:
        self.path = path
        self.min_interval_s = max(0.0, float(min_interval_s))
        self._publish = publish
        self.first_step: Optional[int] = None
        self.last_step: Optional[int] = None
        self.beats = 0
        self._last_write = 0.0
        # Beats arrive from more than one thread (serve's batcher thread
        # publishes serve_batch events while the accept loop ticks
        # manually; data producer threads publish quarantine events):
        # without the lock, two beat() calls share one tmp path and can
        # rename torn JSON into place — which reads as a STALL to the
        # supervisor, the exact false positive a watchdog must not have.
        self._lock = threading.RLock()
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)

    @classmethod
    def from_env(cls, publish: Optional[Callable] = None
                 ) -> Optional["HeartbeatWriter"]:
        """The child half of the supervision env protocol: a writer on
        ``$TPUIC_HEARTBEAT_FILE`` at the supervisor-chosen throttle, or
        None when this process is not supervised."""
        path = os.environ.get(ENV_HEARTBEAT_FILE, "")
        if not path:
            return None
        try:
            interval = float(os.environ.get(ENV_HEARTBEAT_INTERVAL, "1"))
        except ValueError:
            interval = 1.0
        return cls(path, min_interval_s=interval, publish=publish)

    def __call__(self, ev) -> None:
        if ev.kind == "heartbeat":
            return  # our own echo
        step = ev.data.get("step") if ev.kind == "step" else None
        with self._lock:
            if ev.kind == "checkpoint_commit":
                # A commit moves the resume point: the next life may
                # legally start right past the committed step, so the
                # file must never lag behind it (steps faster than the
                # write throttle would otherwise leave the supervisor's
                # best_step stale and flag a spurious accounting
                # violation after resume). Commits are save-period-rare;
                # forcing the write costs nothing.
                self._last_write = 0.0
            self._observe(step)

    def _observe(self, step) -> None:
        if step is not None:
            step = int(step)
            if self.first_step is None:
                # Exact, write-throttle-proof: every step EVENT passes
                # through here even when most of them don't WRITE, so
                # the supervisor's step-accounting check compares the
                # true first step of this life, not the first one a
                # throttled write + poll happened to sample.
                self.first_step = step
            self.last_step = step
        self.beat()

    def beat(self) -> bool:
        """Write the heartbeat file if the throttle allows; returns
        whether a write happened. Also the manual tick for loops with no
        bus traffic (an idle ``tpuic.serve`` poll loop is alive even
        when no requests arrive)."""
        with self._lock:
            # Throttle/age on the monotonic clock: a backward NTP/VM-resume
            # wall-clock step must not suppress writes until the clock
            # re-passes the old timestamp — a stale file reads as a HANG
            # and the watchdog kills a healthy child. Wall time is only
            # ever payload data.
            now = time.monotonic()
            if (self._last_write
                    and now - self._last_write < self.min_interval_s):
                return False
            self.beats += 1
            payload = {"step": self.last_step, "first_step": self.first_step,
                       "t": round(time.time(), 3), "pid": os.getpid(),
                       "beats": self.beats}
            tmp = f"{self.path}.{os.getpid()}.tmp"
            try:
                with open(tmp, "w") as f:
                    json.dump(payload, f)
                os.replace(tmp, self.path)
            except OSError:
                # A full/readonly disk must never take down the run the
                # heartbeat exists to protect; the supervisor sees
                # staleness and treats it as a hang, the honest signal.
                return False
            self._last_write = now
            step, beats = self.last_step, self.beats
        if self._publish is not None:
            self._publish("heartbeat", step=step, beats=beats)
        return True

    def age_s(self) -> Optional[float]:
        """Seconds since the last successful write (None before any)."""
        if not self._last_write:
            return None
        return max(0.0, time.monotonic() - self._last_write)


def read_heartbeat(path: str) -> Optional[dict]:
    """Parse a heartbeat file; None when absent or unreadable (the
    atomic rename makes torn reads impossible, but a crashed writer may
    have left nothing)."""
    try:
        with open(path) as f:
            out = json.load(f)
    except (OSError, ValueError):
        return None
    return out if isinstance(out, dict) else None


def restart_info() -> Optional[Tuple[int, float]]:
    """(restart_count, downtime_s) when this process is a supervisor
    restart, else None. ``downtime_s`` is measured from the previous
    child's death (supervisor-stamped env) to *now* — call it where the
    downtime ends (fit() start), so backoff + respawn + re-init + restore
    are all charged to the ``restart`` goodput bucket."""
    try:
        count = int(os.environ.get(ENV_RESTART, "0"))
    except ValueError:
        return None
    if count <= 0:
        return None
    try:
        since = float(os.environ.get(ENV_DOWN_SINCE, ""))
    except ValueError:
        since = time.time()
    return count, max(0.0, time.time() - since)


_DUMP_FILES: List = []  # keep registered faulthandler files alive


def install_stack_dump_handler(chain: bool = False) -> Optional[str]:
    """Register a ``faulthandler`` all-thread stack dump on SIGQUIT.

    The supervisor's hang escalation sends SIGQUIT first precisely so a
    wedged trainer explains *where* it is stuck before being killed.
    Dumps go to ``$TPUIC_STACK_DUMP`` when the supervisor set it (the
    captured artifact the chaos soak asserts on), else stderr. Returns
    the destination, or None when registration is impossible (no
    SIGQUIT on this platform, non-main thread).

    ``chain=True`` additionally invokes whatever Python-level SIGQUIT
    handler was installed *before* this call, after the C-level stack
    dump — how the flight recorder's event-timeline dump
    (telemetry/flight.py) rides the same signal: register the Python
    handler first, then call this with ``chain=True``, and a SIGQUIT
    yields stacks (always, C-level) plus the event history (when the
    main thread still executes bytecode)."""
    if not hasattr(signal, "SIGQUIT"):
        return None
    import faulthandler
    path = os.environ.get(ENV_STACK_DUMP, "")
    target = sys.stderr
    if path:
        try:
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            target = open(path, "w")
        except OSError:
            path, target = "", sys.stderr
    try:
        faulthandler.register(signal.SIGQUIT, file=target, all_threads=True,
                              chain=chain)
    except (ValueError, OSError, RuntimeError):
        return None
    if target is not sys.stderr:
        _DUMP_FILES.append(target)  # GC would close the fd under faulthandler
    return path or "<stderr>"


# -- exit classification -----------------------------------------------------
RETRYABLE = "retryable"
PREEMPTED = "preempted"
POISON = "poison"
DONE = "done"


def classify_exit(returncode: int, shutting_down: bool = False) -> str:
    """Map a child's exit code onto the contract table (module
    docstring). ``shutting_down``: the supervisor itself received
    SIGTERM/SIGINT — nothing restarts, a clean flush (or completion)
    propagates and anything else is reported as-is."""
    if returncode == EXIT_OK:
        return DONE
    if returncode == EXIT_POISON:
        return POISON
    if returncode == EXIT_PREEMPTED:
        return PREEMPTED if not shutting_down else DONE
    return POISON if shutting_down else RETRYABLE


@dataclasses.dataclass
class AttemptResult:
    """One child run, as the supervisor observed it."""
    attempt: int
    returncode: int
    hung: bool
    first_step: Optional[int]
    last_step: Optional[int]
    duration_s: float


class _Child:
    """One supervised OS process: the spawn-time artifact environment
    (heartbeat file, per-attempt stack/flight dump paths), heartbeat
    observation, and the escalation ladder (SIGQUIT stack+flight dump →
    SIGTERM flush window → SIGKILL).

    Shared by the single-child :class:`Supervisor` below and the gang
    supervisor (``runtime/gang.py``), so the escalation semantics — and
    their hard-won flake fixes, above all *one SIGTERM per pid* (a
    second TERM can land inside the child's flush ``sys.exit(43)`` after
    interpreter finalization restored the default handler and kill it
    -15 mid-exit) — exist exactly once instead of as a copy per
    supervisor flavor."""

    def __init__(self, cmd: Sequence[str], *, heartbeat_file: str,
                 stack_dump: str, flight_dump: str, label: str = "") -> None:
        self.cmd = list(cmd)
        self.heartbeat_file = heartbeat_file
        self.stack_dump = stack_dump
        self.flight_dump = flight_dump
        self.label = label  # "" for the single child; "rank k" in a gang
        self.proc: Optional[subprocess.Popen] = None
        self._term_pid: Optional[int] = None  # pid already SIGTERMed
        self.hung = False
        self.first_step: Optional[int] = None
        self.last_step: Optional[int] = None
        self.last_beats = -1
        self.spawned_at = 0.0
        self.last_change = 0.0

    def spawn(self, env: Dict[str, str], stdout=None,
              stderr=None) -> subprocess.Popen:
        """Start the process with the artifact env injected. Heartbeat
        freshness is per-attempt: any stale file is removed first.
        ``stdout``/``stderr`` pass through to Popen (the router redirects
        each replica's streams to per-replica log files — the
        failure artifacts CI uploads); None inherits, as before."""
        try:
            os.remove(self.heartbeat_file)
        except OSError:
            pass
        env = dict(env)
        env[ENV_HEARTBEAT_FILE] = self.heartbeat_file
        env[ENV_STACK_DUMP] = self.stack_dump
        env[ENV_FLIGHT_DUMP] = self.flight_dump
        self.hung = False
        self.first_step = self.last_step = None
        self.last_beats = -1
        self._term_pid = None
        self.spawned_at = self.last_change = time.monotonic()
        self.proc = subprocess.Popen(self.cmd, env=env, stdout=stdout,
                                     stderr=stderr)
        return self.proc

    @property
    def pid(self) -> Optional[int]:
        return self.proc.pid if self.proc is not None else None

    def poll(self) -> Optional[int]:
        return self.proc.poll() if self.proc is not None else None

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def signal(self, sig: int) -> None:
        if self.alive():
            try:
                self.proc.send_signal(sig)
            except OSError:
                pass

    def term(self) -> bool:
        """SIGTERM (the PR-2 flush path), at most once per pid — callable
        from both the supervisor's signal handler and its poll loop
        without risking the double-TERM flake documented above. Returns
        whether a TERM was actually sent."""
        if self.proc is None or self._term_pid == self.proc.pid:
            return False
        self.signal(signal.SIGTERM)
        self._term_pid = self.proc.pid
        return True

    def observe(self, now: Optional[float] = None) -> None:
        """Fold the heartbeat file into the liveness view: beat-count
        changes move ``last_change``; the payload's exact ``first_step``
        wins over whichever step a throttled write + poll happened to
        sample first (the accounting check compares true first steps)."""
        now = time.monotonic() if now is None else now
        hb = read_heartbeat(self.heartbeat_file)
        if hb is None:
            return
        beats = int(hb.get("beats", 0))
        if beats != self.last_beats:
            self.last_beats = beats
            self.last_change = now
        fs = hb.get("first_step")
        if fs is not None:
            self.first_step = int(fs)
        step = hb.get("step")
        if step is not None:
            step = int(step)
            if self.first_step is None:
                self.first_step = step
            self.last_step = step

    def stale_s(self, now: Optional[float] = None) -> float:
        return (time.monotonic() if now is None else now) - self.last_change

    def window_s(self, watchdog_s: float, startup_grace_s: float) -> float:
        """The liveness window currently in force: startup grace before
        the first observed beat, the watchdog after."""
        return watchdog_s if self.last_beats >= 0 else startup_grace_s

    def escalate(self, quit_wait_s: float, grace_s: float) -> None:
        """The hang ladder: SIGQUIT (faulthandler stacks + flight-recorder
        timeline land in the per-attempt artifacts), a pause for the
        dumps, then SIGTERM with the flush grace window, then SIGKILL."""
        self.hung = True
        if hasattr(signal, "SIGQUIT"):
            self.signal(signal.SIGQUIT)
            try:  # let faulthandler finish writing the dump
                self.proc.wait(timeout=quit_wait_s)
            except subprocess.TimeoutExpired:
                pass
        self.term()
        self.wait_or_kill(grace_s)

    def wait_or_kill(self, grace_s: float) -> bool:
        """Wait up to ``grace_s`` for exit; SIGKILL on timeout. Returns
        whether the kill was needed."""
        try:
            self.proc.wait(timeout=grace_s)
            return False
        except subprocess.TimeoutExpired:
            self.signal(signal.SIGKILL)
            self.proc.wait()
            return True

    def finalize(self) -> int:
        """Reap the process and fold the FINAL heartbeat state (the last
        write may have landed after the last poll). Returns the exit
        code."""
        rc = self.proc.wait()
        hb = read_heartbeat(self.heartbeat_file)
        if hb is not None and hb.get("step") is not None:
            self.last_step = int(hb["step"])
            if hb.get("first_step") is not None:
                self.first_step = int(hb["first_step"])
            if self.first_step is None:
                self.first_step = self.last_step
        return rc


class Supervisor:
    """Run ``cmd`` as a supervised child; see the module docstring for
    the protocol. ``state_dir`` holds the heartbeat file, the progress
    ledger (``ledger.jsonl``), and per-attempt stack dumps.

    ``chaos``: optional per-attempt ``TPUIC_FAULTS`` specs (attempt i
    gets ``chaos[i]``; attempts past the end run fault-free). This is how
    ``scripts/chaos_soak.py`` schedules one deterministic fault per life
    of the child — a plain env spec would re-fire at the same global step
    after every resume and crash-loop the run it is supposed to test.
    """

    def __init__(self, cmd: Sequence[str], state_dir: str, *,
                 watchdog_s: float = 300.0, startup_grace_s: float = 1800.0,
                 quit_wait_s: float = 3.0, grace_s: float = 30.0,
                 poll_s: float = 0.5, max_restarts: int = 16,
                 backoff_s: float = 1.0, backoff_max_s: float = 300.0,
                 crash_loop_k: int = 3, heartbeat_interval_s: float = 1.0,
                 chaos: Optional[Sequence[str]] = None,
                 env: Optional[Dict[str, str]] = None,
                 log: Optional[Callable[[str], None]] = None) -> None:
        self.cmd = list(cmd)
        self.state_dir = os.path.abspath(state_dir)
        os.makedirs(self.state_dir, exist_ok=True)
        self.heartbeat_file = os.path.join(self.state_dir, "heartbeat.json")
        self.ledger_file = os.path.join(self.state_dir, "ledger.jsonl")
        self.watchdog_s = float(watchdog_s)
        self.startup_grace_s = float(startup_grace_s)
        self.quit_wait_s = float(quit_wait_s)
        self.grace_s = float(grace_s)
        self.poll_s = float(poll_s)
        self.max_restarts = int(max_restarts)
        self.backoff_s = float(backoff_s)
        self.backoff_max_s = float(backoff_max_s)
        self.crash_loop_k = int(crash_loop_k)
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self.chaos = list(chaos) if chaos else []
        self.extra_env = dict(env or {})
        self._log = log or (lambda msg: print(f"[supervise] {msg}",
                                              file=sys.stderr, flush=True))
        self._child: Optional[_Child] = None
        self._shutdown = False
        self.restarts = 0        # total (incl. clean preemption flushes)
        self.crash_restarts = 0  # retryable failures only — the budget
        self.attempts: List[AttemptResult] = []
        self.best_step: Optional[int] = None
        self.violations = 0
        if "--no-resume" in self.cmd:
            # Restart-with-resume is the whole point; a child that starts
            # from scratch every life turns the restart budget into a
            # training-from-zero loop.
            self._log("WARNING: child command has --no-resume; restarts "
                      "will replay from scratch instead of resuming")

    # -- ledger ---------------------------------------------------------
    def _ledger(self, event: str, **data) -> None:
        rec = {"event": event, "t": round(time.time(), 3), **data}
        with open(self.ledger_file, "a") as f:
            f.write(json.dumps(rec) + "\n")

    # -- signals --------------------------------------------------------
    def _on_signal(self, signum, frame) -> None:
        self._shutdown = True
        child = self._child
        # One SIGTERM per child, here too (the _Child.term() guard): a
        # repeated external SIGTERM (impatient orchestrator) must not
        # deliver a second TERM that can land inside the child's flush
        # sys.exit(43) after finalization restored the default handler
        # (see the shutdown branch in _run_attempt).
        if child is not None:
            child.term()  # the PR-2 flush path

    # -- one attempt ----------------------------------------------------
    def _spawn_env(self, attempt: int, down_since: float) -> Dict[str, str]:
        # Artifact paths (heartbeat file, per-attempt stack/flight dump)
        # are injected by _Child.spawn; this builds everything else.
        env = dict(os.environ)
        env.update(self.extra_env)
        env[ENV_HEARTBEAT_INTERVAL] = repr(self.heartbeat_interval_s)
        env[ENV_RESTART] = str(attempt)
        env[ENV_DOWN_SINCE] = repr(down_since)
        if self.chaos:
            spec = self.chaos[attempt] if attempt < len(self.chaos) else ""
            env["TPUIC_FAULTS"] = spec
        return env

    def _run_attempt(self, attempt: int, down_since: float) -> AttemptResult:
        env = self._spawn_env(attempt, down_since)
        child = _Child(
            self.cmd, heartbeat_file=self.heartbeat_file,
            stack_dump=os.path.join(self.state_dir,
                                    f"stackdump-{attempt}.txt"),
            # Flight recorder (telemetry/flight.py): the child dumps its
            # last-N-events ring here on SIGQUIT — the hang escalation
            # yields stacks AND the event timeline leading into the wedge.
            flight_dump=os.path.join(self.state_dir,
                                     f"flightdump-{attempt}.jsonl"))
        self._child = child
        t0 = time.monotonic()
        child.spawn(env)
        self._ledger("spawn", attempt=attempt, pid=child.pid,
                     restart=attempt > 0,
                     faults=env.get("TPUIC_FAULTS", "") if self.chaos else "")
        while child.poll() is None:
            time.sleep(self.poll_s)
            now = time.monotonic()
            child.observe(now)
            if self._shutdown:
                # Usually the handler already forwarded SIGTERM — but a
                # child spawned AFTER the flag was set (signal landed
                # between attempts, when _child was None) never got it;
                # term() here is a no-op in the forwarded case (one TERM
                # per pid — a SECOND SIGTERM can land inside the child's
                # flush sys.exit(43) after interpreter finalization
                # restored the default handler and kill it -15 mid-exit,
                # a ~1-in-12 flake caught live in PR 8). Then the full
                # grace window to flush, then make sure it dies.
                child.term()
                if child.wait_or_kill(self.grace_s):
                    self._log(f"attempt {attempt}: no exit "
                              f"{self.grace_s:.0f}s after forwarded "
                              "SIGTERM; killing")
                break
            window = child.window_s(self.watchdog_s, self.startup_grace_s)
            if child.stale_s(now) > window:
                stale = child.stale_s(now)
                self._log(f"attempt {attempt}: HANG — no heartbeat for "
                          f"{stale:.1f}s (window {window:.0f}s, last step "
                          f"{child.last_step}); SIGQUIT for a stack dump, "
                          f"then SIGTERM, then SIGKILL")
                self._ledger("hang", attempt=attempt, stale_s=round(stale, 1),
                             last_step=child.last_step,
                             stack_dump=child.stack_dump,
                             flight_dump=child.flight_dump)
                child.escalate(self.quit_wait_s, self.grace_s)
                break
        rc = child.finalize()
        res = AttemptResult(attempt=attempt, returncode=rc, hung=child.hung,
                            first_step=child.first_step,
                            last_step=child.last_step,
                            duration_s=round(time.monotonic() - t0, 3))
        self._child = None
        self._ledger("exit", attempt=attempt, returncode=rc, hung=child.hung,
                     first_step=child.first_step, last_step=child.last_step,
                     duration_s=res.duration_s,
                     outcome=classify_exit(rc, self._shutdown))
        return res

    # -- the supervision loop -------------------------------------------
    def run(self) -> int:
        installed = {}
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                installed[sig] = signal.signal(sig, self._on_signal)
            except (ValueError, OSError):  # non-main thread (tests)
                pass
        try:
            return self._run()
        finally:
            for sig, prev in installed.items():
                try:
                    signal.signal(sig, prev)
                except (ValueError, OSError):
                    pass

    def _give_up(self, reason: str, code: int) -> int:
        self._log(f"GIVING UP (non-retryable): {reason}")
        self._ledger("giveup", reason=reason, restarts=self.restarts,
                     best_step=self.best_step, returncode=code)
        return code

    def _run(self) -> int:
        attempt = 0
        no_progress = 0
        down_since = time.time()
        while True:
            res = self._run_attempt(attempt, down_since)
            self.attempts.append(res)
            down_since = time.time()
            # Step-accounting check: a resumed attempt may REPLAY steps
            # (resume from an older checkpoint) but must never START
            # past the best observed step + 1 — that would mean the
            # resume silently skipped training steps.
            if (res.first_step is not None and self.best_step is not None
                    and res.first_step > self.best_step + 1):
                self.violations += 1
                self._log(f"LEDGER VIOLATION: attempt {attempt} first step "
                          f"{res.first_step} skips past best previous step "
                          f"{self.best_step}")
                self._ledger("violation", attempt=attempt,
                             first_step=res.first_step,
                             best_step=self.best_step)
            progressed = (res.last_step is not None
                          and (self.best_step is None
                               or res.last_step > self.best_step))
            if progressed:
                self.best_step = res.last_step
            outcome = classify_exit(res.returncode, self._shutdown)
            if outcome == DONE:
                self._log(f"child exited cleanly (code {res.returncode}) "
                          f"after {attempt + 1} attempt(s), best step "
                          f"{self.best_step}")
                self._ledger("done", attempts=attempt + 1,
                             restarts=self.restarts, best_step=self.best_step,
                             returncode=res.returncode)
                return res.returncode
            if outcome == POISON:
                code = res.returncode
                if code < 0:
                    # Signal death surfaced while shutting down: report
                    # the shell convention (128+N) — sys.exit(-9) would
                    # become OS status 247, outside any contract.
                    code = 128 - code
                return self._give_up(
                    f"child exit code {res.returncode} "
                    f"({'supervisor shutdown' if self._shutdown else 'poison: restarting cannot help'})",
                    code)
            # Retryable (crash / hang) or a clean preemption flush to be
            # resumed. Only real failures consume the restart budget — a
            # flush is the preemptible fleet working as designed, and a
            # multi-day run may absorb hundreds of them — but the
            # no-progress streak counts EVERY outcome: a preemption that
            # deterministically re-fires before any step lands (a stale
            # TPUIC_FAULTS env spec, an instantly-evicting scheduler)
            # must trip the crash-loop verdict, not respawn forever at
            # full speed with no bound at all. Counters increment only
            # when a restart actually happens, so giveup records report
            # restarts that occurred, not one that never did.
            if progressed:
                no_progress = 0
            elif (res.last_step is None and not res.hung
                  and res.duration_s >= self.startup_grace_s + self.watchdog_s):
                # A step-less child (a supervised tpuic.serve emits
                # beats, never steps) can't show step progress — but a
                # life that outlived startup grace plus a full watchdog
                # window without being hang-killed was demonstrably
                # alive and beating. Healthy crashes days apart must not
                # accumulate into a "deterministic failure" verdict.
                no_progress = 0
            else:
                no_progress += 1
            if (outcome == RETRYABLE
                    and self.crash_restarts >= self.max_restarts):
                return self._give_up(
                    f"restart budget exhausted ({self.max_restarts} "
                    "retryable failures)", EXIT_CRASH_LOOP)
            if no_progress >= self.crash_loop_k:
                return self._give_up(
                    f"crash loop: {no_progress} consecutive attempts "
                    f"with no step progress (stuck at step "
                    f"{self.best_step}) — the failure is deterministic, "
                    "restarting cannot help", EXIT_CRASH_LOOP)
            self.restarts += 1
            if outcome == RETRYABLE:
                self.crash_restarts += 1
            why = ("hang" if res.hung else
                   "preemption flush" if outcome == PREEMPTED else
                   f"crash (code {res.returncode})")
            delay = 0.0
            if outcome == RETRYABLE:
                # Exponential backoff on real failures — backoff_s for
                # the first no-progress retry, doubling per consecutive
                # one. A clean preemption flush resumes immediately; its
                # state is committed and waiting.
                delay = min(self.backoff_max_s,
                            self.backoff_s * (2.0 ** max(0, no_progress - 1)))
            budget = (f" (crash {self.crash_restarts}/{self.max_restarts})"
                      if outcome == RETRYABLE else "")
            self._log(f"attempt {attempt} ended ({why}); restart "
                      f"#{self.restarts} with resume{budget}"
                      + (f" after {delay:.1f}s backoff" if delay else ""))
            if delay:
                deadline = time.monotonic() + delay
                while time.monotonic() < deadline and not self._shutdown:
                    time.sleep(min(0.2, delay))
                if self._shutdown:
                    return self._give_up("shutdown requested during backoff",
                                         EXIT_PREEMPTED)
            attempt += 1
