"""Gang supervisor: N ranks spawned, watched, and restarted as ONE unit.

The single-child supervisor (``runtime/supervisor.py``) covers one
process dying; a data-parallel fleet fails *partially* — and in a
synchronous SPMD step one dead or wedged rank wedges every survivor
inside a collective (the large-cluster training literature's founding
observation, FireCaffe arXiv:1511.00175; the 15-minute-ImageNet recipes
arXiv:1711.04325 all assume gang-scheduled workers). Restarting only the
dead rank is useless: the survivors are blocked on a collective that
will never complete, and a rank resuming from a checkpoint the others
never committed desynchronizes the fleet. This module is the
consequence — ``python -m tpuic.supervise --gang N`` supervises the
whole fleet as one failure domain:

- **Per-rank heartbeat watchdogs.** Each rank gets its own heartbeat
  file (``heartbeat.json`` / ``heartbeat.rank<k>.json`` — the same
  ``<stem>.rank<k>`` convention as telemetry/fleet.py's per-rank event
  streams; the child side is the unchanged
  ``HeartbeatWriter.from_env()``) and its own per-attempt
  ``stackdump-<attempt>[.rank<k>].txt`` / ``flightdump-...jsonl``
  artifacts. A stale rank is escalated exactly like the single child
  (SIGQUIT dumps → SIGTERM → SIGKILL, the shared ``_Child`` ladder) —
  but the hang is *rank-attributed* in the ledger, and it tears the
  gang down.
- **Coordinated gang restart.** Any rank exiting retryable (crash,
  signal death) or being watchdog-killed tears the whole gang down:
  survivors get SIGTERM and the full ``--grace-s`` flush window — a
  healthy rank exits 43 with a step-exact checkpoint, nothing is lost —
  and then ALL ranks restart together. Preemption flushes (43, e.g. the
  whole fleet evicted) restart free; poison (44) from ANY rank stops
  the gang without restart (a deterministic failure replicated N times
  is still deterministic); exit 0 from one rank just waits for the rest.
- **Gang-wide crash-loop ledger.** The no-progress streak runs over the
  *fleet-min* best step — the smallest last-step across ranks — so one
  healthy rank making progress cannot mask a peer crash-looping at step
  0. Per-rank step-accounting violations (a resumed rank's first step
  jumping past its own best + 1) are checked exactly as in the single
  supervisor.
- **Restart-consistent resume.** With ``--gang-ckpt`` pointing at the
  per-rank checkpoint model dirs, a gang restart reads every rank's
  committed manifest sidecars (``{latest,best}[.prev].manifest.json``,
  checkpoint/manager.py) and picks the newest step EVERY rank has a
  committed checkpoint for; that step rides ``TPUIC_RESUME_STEP`` into
  each rank, where ``CheckpointManager.restore_into`` skips rungs ahead
  of it — so no rank resumes past the fleet (a survivor's mid-teardown
  flush is deliberately newer than the dead rank's last commit; without
  the cap it would resume ahead and desync the replay).
- **Rank-aware rendezvous.** Each rank is spawned with
  ``TPUIC_FLEET_RANK``/``TPUIC_FLEET_RANKS`` (telemetry rank tagging,
  per-rank streams, rank-targeted fault points) and — when
  ``--coordinator`` is given — the full ``TPUIC_COORDINATOR_ADDRESS`` /
  ``TPUIC_NUM_PROCESSES`` / ``TPUIC_PROCESS_ID`` trio for the
  jax.distributed env rendezvous (runtime/distributed.py), so telemetry,
  fleet streams, and collectives all agree on rank identity from one
  source. ``{rank}`` in the child command is substituted per rank
  (per-rank checkpoint dirs, log paths).

- **Elastic membership** (``elastic=True``; docs/parallelism.md,
  "Elastic data parallelism"). The coordinated restart above answers a
  lost rank by tearing every survivor down; the elastic mode answers it
  with a *degrade*: the lost rank is removed from the published
  membership view (``membership.json``, runtime/membership.py), the
  fleet-agreed resume step rides the same record, and the survivors —
  polling the file at step boundaries — re-form an (R−1)-replica view
  from that step **without their processes restarting** (they restore
  through the capped integrity ladder and recompile; same pids). A
  replacement rank is respawned with the resume cap in its env and
  *rejoins* at the next fleet-agreed boundary (its first post-restore
  step observed), bumping the membership back to R. A loss that would
  take the fleet below ``min_ranks`` stops the gang with the typed
  ``EXIT_BELOW_MIN`` verdict (survivors still get their flush window);
  a flapping replacement (dies during its catch-up restore —
  ``rank_rejoin_flap``) burns its per-rank respawn budget without ever
  touching the survivors' membership view.

Like the single supervisor, this module imports only the stdlib: the
parent must never initialize jax, and must outlive any backend wedge a
rank hits. The end-to-end proof is ``scripts/gang_soak.py`` (CI-gated):
a seeded single-rank crash triggers exactly one coordinated restart with
the survivor's 43 flush and a fleet-agreed resume step, final metrics
bitwise-equal to an undisturbed baseline; a seeded poison stops the gang.
The elastic mode's proof is ``scripts/elastic_soak.py``: a rank killed
mid-epoch degrades the fleet (zero survivor restarts, pids pinned), the
replacement rejoins, and the final metrics are bitwise-equal to an
undisturbed baseline; a second kill below ``min_ranks`` stops the gang
with the typed verdict.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import subprocess
import sys
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

from tpuic.runtime.membership import (ENV_MEMBERSHIP_FILE, Membership,
                                      write_membership)
from tpuic.runtime.supervisor import (DONE, ENV_DOWN_SINCE,
                                      ENV_HEARTBEAT_INTERVAL, ENV_RESTART,
                                      ENV_RESUME_STEP, EXIT_BELOW_MIN,
                                      EXIT_CRASH_LOOP, EXIT_POISON,
                                      EXIT_PREEMPTED, POISON, PREEMPTED,
                                      RETRYABLE, _Child, classify_exit)

# The rank-identity env the launcher half of telemetry/fleet.py reads
# (kept as string literals there too — both modules are import-light on
# purpose; tests/test_gang.py pins the two pairs equal).
ENV_FLEET_RANK = "TPUIC_FLEET_RANK"
ENV_FLEET_RANKS = "TPUIC_FLEET_RANKS"

# Committed-manifest sidecars a rank may hold (checkpoint/manager.py's
# track rotation), newest-first per track.
_MANIFEST_TRACKS = ("latest", "best", "latest.prev", "best.prev")


def rank_path(path: str, rank: int) -> str:
    """Per-rank artifact path: rank 0 keeps ``path``, rank k gets
    ``<stem>.rank<k><ext>`` — mirroring telemetry/fleet.py's
    ``rank_stream_path`` stream convention (stdlib-only copy on purpose:
    importing tpuic.telemetry from the parent would pull numpy/jax
    imports the supervisor must never make; tests pin the two
    implementations equal)."""
    if int(rank) == 0:
        return path
    root, ext = os.path.splitext(path)
    return f"{root}.rank{int(rank)}{ext or '.jsonl'}"


def committed_steps(ckpt_dir: str) -> Dict[str, int]:
    """{track: committed optimizer step} for every readable manifest
    sidecar under one rank's checkpoint model dir. Unreadable or
    step-less manifests are skipped (pre-ladder checkpoints carry no
    fleet-comparable step)."""
    out: Dict[str, int] = {}
    for track in _MANIFEST_TRACKS:
        try:
            with open(os.path.join(ckpt_dir,
                                   track + ".manifest.json")) as f:
                step = json.load(f).get("step")
            if step is not None:
                out[track] = int(step)
        except (OSError, ValueError, TypeError):
            continue
    return out


def fleet_resume_step(ckpt_dirs: Sequence[str]) -> Optional[int]:
    """The newest checkpoint step EVERY rank's committed manifests agree
    on — the step a coordinated restart resumes from.

    Per rank, the candidate set is every step with a committed manifest
    (latest/best and their ``.prev`` rotations). The fleet step is the
    max of the intersection; when no common step exists (pathological —
    ranks committing on the same save cadence always share one), the
    fallback is the slowest rank's newest commit, which every faster
    rank satisfies with an *older* rung (never a newer one — the child
    side's ``TPUIC_RESUME_STEP`` filter enforces ≤). None when any rank
    has no committed manifest at all (nothing to agree on: the run died
    before its first commit, every rank starts over together)."""
    per_rank: List[set] = []
    for d in ckpt_dirs:
        steps = set(committed_steps(d).values())
        if not steps:
            return None
        per_rank.append(steps)
    if not per_rank:
        return None
    common = set.intersection(*per_rank)
    if common:
        return max(common)
    return min(max(s) for s in per_rank)


@dataclasses.dataclass
class GangAttempt:
    """One gang life, as the supervisor observed it."""
    attempt: int
    codes: List[int]                    # per-rank exit codes
    hung_ranks: List[int]               # watchdog-escalated ranks
    first_steps: List[Optional[int]]
    last_steps: List[Optional[int]]
    fleet_step: Optional[int]           # min over ranks (None if any is)
    outcome: str                        # DONE/PREEMPTED/POISON/RETRYABLE
    duration_s: float


class GangSupervisor:
    """Run ``cmd`` (a template: ``{rank}`` substituted per rank) as a
    gang of ``ranks`` supervised children; module docstring has the
    protocol. Knobs mirror :class:`Supervisor` — one flush window, one
    watchdog, one restart budget for the whole gang.

    ``ckpt_dirs``: per-rank checkpoint MODEL dirs (the dirs holding the
    ``*.manifest.json`` sidecars) — a ``{rank}`` template string or an
    explicit per-rank sequence; enables the fleet-agreed resume step.
    ``coordinator``: when set, each rank additionally gets the full
    jax.distributed env rendezvous trio.

    ``elastic=True`` switches rank loss from coordinated-restart to the
    degrade/rejoin protocol (module docstring): ``min_ranks`` is the
    floor below which the gang stops with ``EXIT_BELOW_MIN``;
    ``max_respawns`` bounds how many times ONE rank's replacement may be
    respawned (default: ``max_restarts``) before that rank is declared
    lost and the fleet continues permanently degraded. In elastic mode
    the per-spawn ``chaos`` spec indexes by the rank's respawn count
    (original spawn = spec 0, first replacement = spec 1, …), mirroring
    the per-attempt semantics of the restart mode."""

    def __init__(self, cmd: Sequence[str], state_dir: str, *, ranks: int,
                 watchdog_s: float = 300.0, startup_grace_s: float = 1800.0,
                 quit_wait_s: float = 3.0, grace_s: float = 30.0,
                 poll_s: float = 0.5, max_restarts: int = 16,
                 backoff_s: float = 1.0, backoff_max_s: float = 300.0,
                 crash_loop_k: int = 3, heartbeat_interval_s: float = 1.0,
                 chaos: Optional[Sequence[str]] = None,
                 ckpt_dirs: Union[str, Sequence[str], None] = None,
                 coordinator: str = "",
                 elastic: bool = False, min_ranks: int = 1,
                 max_respawns: Optional[int] = None,
                 env: Optional[Dict[str, str]] = None,
                 log: Optional[Callable[[str], None]] = None) -> None:
        self.cmd = list(cmd)
        self.ranks = int(ranks)
        if self.ranks < 1:
            raise ValueError(f"gang needs >= 1 rank (got {ranks})")
        self.state_dir = os.path.abspath(state_dir)
        os.makedirs(self.state_dir, exist_ok=True)
        self.ledger_file = os.path.join(self.state_dir, "ledger.jsonl")
        self.watchdog_s = float(watchdog_s)
        self.startup_grace_s = float(startup_grace_s)
        self.quit_wait_s = float(quit_wait_s)
        self.grace_s = float(grace_s)
        self.poll_s = float(poll_s)
        self.max_restarts = int(max_restarts)
        self.backoff_s = float(backoff_s)
        self.backoff_max_s = float(backoff_max_s)
        self.crash_loop_k = int(crash_loop_k)
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self.chaos = list(chaos) if chaos else []
        if isinstance(ckpt_dirs, str):
            self.ckpt_dirs: Optional[List[str]] = [
                ckpt_dirs.replace("{rank}", str(k))
                for k in range(self.ranks)]
        elif ckpt_dirs is not None:
            self.ckpt_dirs = list(ckpt_dirs)
            if len(self.ckpt_dirs) != self.ranks:
                raise ValueError(
                    f"ckpt_dirs has {len(self.ckpt_dirs)} entries for "
                    f"{self.ranks} ranks")
        else:
            self.ckpt_dirs = None
        self.coordinator = coordinator
        self.elastic = bool(elastic)
        self.min_ranks = int(min_ranks)
        if self.elastic and not 1 <= self.min_ranks <= self.ranks:
            raise ValueError(f"min_ranks must be in [1, {self.ranks}] "
                             f"(got {min_ranks})")
        self.max_respawns = (self.max_restarts if max_respawns is None
                             else int(max_respawns))
        self.membership_file = os.path.join(self.state_dir,
                                            "membership.json")
        self._membership_version = 0
        self.respawns: Dict[int, int] = {k: 0 for k in range(self.ranks)}
        self.degrades = 0
        self.rejoins = 0
        self.extra_env = dict(env or {})
        self._log = log or (lambda msg: print(f"[gang] {msg}",
                                              file=sys.stderr, flush=True))
        self._children: List[_Child] = []
        self._shutdown = False
        self.restarts = 0        # total gang restarts (incl. flushes)
        self.crash_restarts = 0  # retryable gang failures — the budget
        self.attempts: List[GangAttempt] = []
        self.best_steps: List[Optional[int]] = [None] * self.ranks
        self.best_fleet_step: Optional[int] = None
        self.violations = 0
        self.last_resume_step: Optional[int] = None

    # -- plumbing -------------------------------------------------------
    def _ledger(self, event: str, **data) -> None:
        rec = {"event": event, "t": round(time.time(), 3), **data}
        with open(self.ledger_file, "a") as f:
            f.write(json.dumps(rec) + "\n")

    def _rank_cmd(self, rank: int) -> List[str]:
        return [a.replace("{rank}", str(rank)) for a in self.cmd]

    def _on_signal(self, signum, frame) -> None:
        # Shared eviction: forward ONE flush-window SIGTERM to every
        # live rank (the _Child.term() per-pid guard makes a repeated
        # external signal harmless — the single supervisor's flake fix).
        self._shutdown = True
        for c in self._children:
            if c is not None:   # elastic spawn loop may be mid-fill
                c.term()

    def _spawn_env(self, attempt: int, rank: int, down_since: float,
                   resume_step: Optional[int]) -> Dict[str, str]:
        env = dict(os.environ)
        env.update(self.extra_env)
        env[ENV_HEARTBEAT_INTERVAL] = repr(self.heartbeat_interval_s)
        env[ENV_RESTART] = str(attempt)
        env[ENV_DOWN_SINCE] = repr(down_since)
        # One rank-identity source for everything downstream: telemetry
        # tagging + per-rank streams (fleet.py), rank-targeted fault
        # points (rank_crash/rank_hang), and — with a coordinator — the
        # jax.distributed collectives themselves.
        env[ENV_FLEET_RANK] = str(rank)
        env[ENV_FLEET_RANKS] = str(self.ranks)
        if self.elastic:
            # The Trainer watches this file at step boundaries
            # (runtime/membership.py) and re-forms on degrade events.
            env[ENV_MEMBERSHIP_FILE] = self.membership_file
        if self.coordinator:
            env["TPUIC_COORDINATOR_ADDRESS"] = self.coordinator
            env["TPUIC_NUM_PROCESSES"] = str(self.ranks)
            env["TPUIC_PROCESS_ID"] = str(rank)
        if resume_step is not None:
            env[ENV_RESUME_STEP] = str(resume_step)
        else:
            env.pop(ENV_RESUME_STEP, None)
        if self.chaos:
            spec = self.chaos[attempt] if attempt < len(self.chaos) else ""
            env["TPUIC_FAULTS"] = spec
        return env

    # -- one gang attempt ------------------------------------------------
    def _teardown(self, why: str, rank: Optional[int]) -> None:
        """Coordinated gang teardown: one SIGTERM per live rank (the
        flush window — a healthy survivor commits a step-exact
        checkpoint and exits 43), then SIGKILL any straggler after the
        shared grace deadline. Survivors blocked inside a collective
        cannot make progress once any member died, so this is recovery,
        not collateral damage."""
        survivors = [k for k, c in enumerate(self._children) if c.alive()]
        if survivors:
            at = f" (rank {rank})" if rank is not None else ""
            self._log(f"tearing down gang [{why}{at}]: SIGTERM flush "
                      f"window ({self.grace_s:.0f}s) for rank(s) "
                      f"{survivors}")
        for c in self._children:
            c.term()
        deadline = time.monotonic() + self.grace_s
        for c in self._children:
            if c.proc is None:
                continue
            remaining = max(0.0, deadline - time.monotonic())
            try:
                c.proc.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                c.signal(signal.SIGKILL)
                c.proc.wait()
        self._ledger("teardown", why=why, rank=rank, survivors=survivors)

    def _monitor(self, attempt: int) -> Optional[Tuple[str, Optional[int]]]:
        """Poll the gang until it needs a coordinated action. Returns the
        teardown cause ``(why, rank)`` or None when every rank exited on
        its own."""
        children = self._children
        while True:
            if all(c.poll() is not None for c in children):
                return None
            time.sleep(self.poll_s)
            now = time.monotonic()
            for c in children:
                if c.alive():
                    c.observe(now)
            if self._shutdown:
                return ("shutdown", None)
            for k, c in enumerate(children):
                rc = c.poll()
                if rc is None:
                    continue
                outcome = classify_exit(rc)
                if outcome == DONE:
                    continue  # one rank finishing early just waits
                # 43 (a lone flush), 44 (poison), or a crash: the gang
                # cannot make progress with a member gone — tear down.
                return (outcome, k)
            for k, c in enumerate(children):
                if not c.alive():
                    continue
                window = c.window_s(self.watchdog_s, self.startup_grace_s)
                stale = c.stale_s(now)
                if stale > window:
                    self._log(f"attempt {attempt}: HANG on rank {k} — no "
                              f"heartbeat for {stale:.1f}s (window "
                              f"{window:.0f}s, last step {c.last_step}); "
                              f"SIGQUIT stack dump, then SIGTERM, then "
                              f"SIGKILL")
                    self._ledger("hang", attempt=attempt, rank=k,
                                 stale_s=round(stale, 1),
                                 last_step=c.last_step,
                                 stack_dump=c.stack_dump,
                                 flight_dump=c.flight_dump)
                    c.escalate(self.quit_wait_s, self.grace_s)
                    return ("hang", k)

    def _run_attempt(self, attempt: int, down_since: float) -> GangAttempt:
        resume_step = None
        if attempt > 0 and self.ckpt_dirs:
            resume_step = fleet_resume_step(self.ckpt_dirs)
            self.last_resume_step = resume_step
            if resume_step is not None:
                self._log(f"attempt {attempt}: fleet-agreed resume step "
                          f"{resume_step} (newest step every rank's "
                          "committed manifest covers)")
            else:
                self._log(f"attempt {attempt}: no fleet-agreed resume "
                          "step (some rank has no committed manifest) — "
                          "ranks resume independently")
            self._ledger("gang_resume", attempt=attempt, step=resume_step,
                         per_rank={str(k): sorted(set(
                             committed_steps(d).values()))
                             for k, d in enumerate(self.ckpt_dirs)})
        self._children = []
        t0 = time.monotonic()
        for k in range(self.ranks):
            child = _Child(
                self._rank_cmd(k),
                heartbeat_file=rank_path(
                    os.path.join(self.state_dir, "heartbeat.json"), k),
                stack_dump=rank_path(
                    os.path.join(self.state_dir,
                                 f"stackdump-{attempt}.txt"), k),
                flight_dump=rank_path(
                    os.path.join(self.state_dir,
                                 f"flightdump-{attempt}.jsonl"), k),
                label=f"rank {k}")
            child.spawn(self._spawn_env(attempt, k, down_since, resume_step))
            self._ledger("spawn", attempt=attempt, rank=k, pid=child.pid,
                         restart=attempt > 0,
                         faults=(self.chaos[attempt]
                                 if self.chaos and attempt < len(self.chaos)
                                 else ""))
            self._children.append(child)
        cause = self._monitor(attempt)
        if cause is not None:
            self._teardown(*cause)
        codes = [c.finalize() for c in self._children]
        hung = [k for k, c in enumerate(self._children) if c.hung]
        outcomes = [classify_exit(rc, self._shutdown) for rc in codes]
        if any(o == POISON for o in outcomes):
            # Poison wins even over a concurrent hang: a rank reporting
            # 44 during a hang-triggered teardown (e.g. its flush found
            # every checkpoint rung corrupt) is still a deterministic
            # failure the restart cannot fix — the documented contract
            # is "poison from ANY rank stops the gang".
            outcome = POISON
        elif hung:
            # A watchdog kill is a retryable failure even when the
            # SIGTERM half of the escalation produced a clean-looking 43.
            outcome = RETRYABLE
        elif any(o == RETRYABLE for o in outcomes):
            outcome = RETRYABLE
        elif any(o == PREEMPTED for o in outcomes):
            outcome = PREEMPTED
        else:
            outcome = DONE
        firsts = [c.first_step for c in self._children]
        lasts = [c.last_step for c in self._children]
        fleet = (min(lasts) if lasts and all(s is not None for s in lasts)
                 else None)
        res = GangAttempt(attempt=attempt, codes=codes, hung_ranks=hung,
                          first_steps=firsts, last_steps=lasts,
                          fleet_step=fleet, outcome=outcome,
                          duration_s=round(time.monotonic() - t0, 3))
        for k, c in enumerate(self._children):
            self._ledger("exit", attempt=attempt, rank=k, returncode=codes[k],
                         hung=c.hung, first_step=c.first_step,
                         last_step=c.last_step,
                         outcome=classify_exit(codes[k], self._shutdown))
        self._ledger("gang_exit", attempt=attempt, codes=codes,
                     hung_ranks=hung, fleet_step=fleet, outcome=outcome,
                     duration_s=res.duration_s)
        self._children = []
        return res

    # -- elastic membership ---------------------------------------------
    def _publish_membership(self, reason: str, active: Sequence[int],
                            resume_step: Optional[int],
                            rank: Optional[int] = None) -> Membership:
        """Atomically publish a new fleet view (version strictly
        increasing) and mirror it into the ledger — the one channel the
        ranks' step-boundary watchers read (runtime/membership.py)."""
        self._membership_version += 1
        m = Membership(version=self._membership_version, world=self.ranks,
                       active=sorted(int(k) for k in active),
                       resume_step=resume_step, reason=reason, rank=rank,
                       t=round(time.time(), 3))
        write_membership(self.membership_file, m)
        self._ledger("membership", version=m.version, reason=reason,
                     active=m.active, resume_step=resume_step, rank=rank)
        return m

    def _fleet_step_for(self, ranks: Sequence[int]) -> Optional[int]:
        """fleet_resume_step over the named ranks' checkpoint dirs (None
        without ``ckpt_dirs`` — stdlib test gangs have no checkpoints)."""
        if not self.ckpt_dirs:
            return None
        return fleet_resume_step([self.ckpt_dirs[k] for k in ranks])

    def _spawn_rank(self, k: int, respawn: int, down_since: float,
                    resume_step: Optional[int]) -> _Child:
        """(Re)spawn rank ``k``; ``respawn`` doubles as the ENV_RESTART
        attempt index and the per-spawn chaos-spec index, so a
        replacement life is distinguishable from the original (the
        ``rank_rejoin_flap`` fault point and the step-accounting checks
        both key on it)."""
        child = _Child(
            self._rank_cmd(k),
            heartbeat_file=rank_path(
                os.path.join(self.state_dir, "heartbeat.json"), k),
            stack_dump=rank_path(
                os.path.join(self.state_dir, f"stackdump-{respawn}.txt"),
                k),
            flight_dump=rank_path(
                os.path.join(self.state_dir,
                             f"flightdump-{respawn}.jsonl"), k),
            label=f"rank {k}")
        child.spawn(self._spawn_env(respawn, k, down_since, resume_step))
        self._ledger("spawn", attempt=respawn, rank=k, pid=child.pid,
                     restart=respawn > 0,
                     faults=(self.chaos[respawn]
                             if self.chaos and respawn < len(self.chaos)
                             else ""))
        self._children[k] = child
        return child

    def _book_rank_exit(self, k: int, c: _Child, rc: int) -> None:
        """Per-rank ledger + step-accounting bookkeeping for one life
        (the elastic twin of ``_book_progress``: there is no gang
        attempt to fold into, but first>best+1 violations and per-rank
        best steps are checked identically)."""
        c.observe()
        if (c.first_step is not None and self.best_steps[k] is not None
                and c.first_step > self.best_steps[k] + 1):
            self.violations += 1
            self._log(f"LEDGER VIOLATION: rank {k} first step "
                      f"{c.first_step} skips past its best previous "
                      f"step {self.best_steps[k]}")
            self._ledger("violation", rank=k, first_step=c.first_step,
                         best_step=self.best_steps[k])
        if c.last_step is not None and (self.best_steps[k] is None
                                        or c.last_step
                                        > self.best_steps[k]):
            self.best_steps[k] = c.last_step
        self._ledger("exit", rank=k, returncode=rc, hung=c.hung,
                     respawn=self.respawns[k], first_step=c.first_step,
                     last_step=c.last_step,
                     outcome=classify_exit(rc, self._shutdown))

    def _elastic_shutdown(self) -> int:
        """Shared eviction / operator stop: mirror the restart-mode
        semantics — flush everyone, propagate 43/0, report poison."""
        self._teardown("shutdown", None)
        codes = [c.finalize() for c in self._children]
        bad = [rc for rc in codes if classify_exit(rc, True) == POISON]
        if bad:
            code = bad[0]
            if code < 0:
                code = 128 - code
            return self._give_up(
                f"rank exit code(s) {codes} during supervisor shutdown",
                code)
        code = EXIT_PREEMPTED if EXIT_PREEMPTED in codes else 0
        self._log(f"elastic gang shut down (codes {codes}); exit {code}")
        self._ledger("done", restarts=self.restarts,
                     degrades=self.degrades, rejoins=self.rejoins,
                     best_fleet_step=self.best_fleet_step,
                     returncode=code)
        return code

    def _run_elastic(self) -> int:
        """The degrade/rejoin supervision loop (module docstring).

        Rank statuses: ``up`` (a mesh member), ``joining`` (a respawned
        replacement catching up — NOT yet in the published membership),
        ``down`` (dead, a respawn scheduled), ``lost`` (respawn budget
        exhausted — the fleet continues permanently degraded), ``done``
        (exited 0). The gang completes when every rank is done or lost;
        poison from ANY rank still stops it, and an active-member count
        below ``min_ranks`` stops it with the typed ``EXIT_BELOW_MIN``
        verdict."""
        down_since = time.time()
        resume_step: Optional[int] = None
        status = {k: "up" for k in range(self.ranks)}
        due: Dict[int, float] = {}     # rank -> respawn due (monotonic)
        down_at: Dict[int, float] = {}  # rank -> wall time of its death
        self._children = [None] * self.ranks  # type: ignore[list-item]
        self._publish_membership("init", list(range(self.ranks)), None)
        for k in range(self.ranks):
            self._spawn_rank(k, 0, down_since, None)

        def members() -> List[int]:
            """The mesh view: ranks in good standing — still training
            ("up") or having COMPLETED their run ("done"). A completed
            rank left cleanly, not by failure, so it stays in the
            published membership."""
            return [k for k in range(self.ranks)
                    if status[k] in ("up", "done")]

        def lose_member(k: int, why: str) -> Optional[int]:
            """A mesh member died: degrade (membership bump + scheduled
            replacement) or, below ``min_ranks``, stop the gang with the
            typed verdict. Returns an exit code to propagate, or None
            to keep supervising."""
            nonlocal resume_step
            survivors = [r for r in members() if r != k]
            # The caller booked this exit already — drop the rank out of
            # "up" FIRST so the teardown/restart paths below never book
            # (or TERM) the same death twice.
            status[k] = "down"
            down_at.setdefault(k, time.time())
            if len(survivors) < self.min_ranks:
                self._log(f"rank {k} lost ({why}) and "
                          f"{len(survivors)} survivor(s) < min_ranks "
                          f"{self.min_ranks}: stopping the gang "
                          f"(typed verdict, exit {EXIT_BELOW_MIN})")
                self._teardown(f"below min_ranks after {why}", k)
                for r, c in enumerate(self._children):
                    if status[r] in ("up", "joining"):
                        self._book_rank_exit(r, c, c.finalize())
                        status[r] = "down"
                return self._give_up(
                    f"fleet below min replicas: rank {k} lost ({why}), "
                    f"{len(survivors)} survivor(s) < min_ranks="
                    f"{self.min_ranks}", EXIT_BELOW_MIN)
            step = self._fleet_step_for(survivors + [k])
            if step is None and self.ckpt_dirs:
                # No commit anywhere yet (the run died before its first
                # checkpoint): there is no step to degrade FROM, so fall
                # back to the restart-mode answer — everyone starts over
                # together, budgeted like any retryable gang failure.
                return restart_all(f"{why} before any fleet commit")
            resume_step = step
            self.degrades += 1
            self._publish_membership("degrade", survivors, step, rank=k)
            self._ledger("degrade", rank=k, why=why, survivors=survivors,
                         resume_step=step)
            self._log(f"rank {k} lost ({why}): fleet degrades to "
                      f"{len(survivors)}/{self.ranks} from fleet-agreed "
                      f"step {step} — survivors re-form in place (no "
                      "process restart); replacement scheduled")
            return schedule_respawn(k)

        def schedule_respawn(k: int) -> Optional[int]:
            if self.respawns[k] >= self.max_respawns:
                status[k] = "lost"
                due.pop(k, None)
                self._ledger("respawn_giveup", rank=k,
                             respawns=self.respawns[k])
                self._log(f"rank {k}: respawn budget exhausted "
                          f"({self.respawns[k]}/{self.max_respawns}) — "
                          "continuing permanently degraded")
                return None
            delay = min(self.backoff_max_s,
                        self.backoff_s * (2.0 ** self.respawns[k]))
            due[k] = time.monotonic() + delay
            return None

        def restart_all(why: str) -> Optional[int]:
            nonlocal down_since, resume_step
            self.crash_restarts += 1
            self.restarts += 1
            if self.crash_restarts > self.max_restarts:
                self._teardown(why, None)
                for r, c in enumerate(self._children):
                    if status[r] in ("up", "joining"):
                        self._book_rank_exit(r, c, c.finalize())
                return self._give_up(
                    f"restart budget exhausted ({self.max_restarts}) "
                    f"after {why}", EXIT_CRASH_LOOP)
            self._teardown(why, None)
            for r, c in enumerate(self._children):
                if status[r] in ("up", "joining"):
                    self._book_rank_exit(r, c, c.finalize())
            down_since = time.time()
            down_at.clear()
            resume_step = self._fleet_step_for(list(range(self.ranks)))
            self._publish_membership("restart", list(range(self.ranks)),
                                     resume_step)
            self._log(f"elastic full restart #{self.restarts} ({why}); "
                      f"fleet resume step {resume_step}")
            for r in range(self.ranks):
                self.respawns[r] += 1
                status[r] = "up"
                due.pop(r, None)
                self._spawn_rank(r, self.respawns[r], down_since,
                                 resume_step)
            return None

        while True:
            time.sleep(self.poll_s)
            now = time.monotonic()
            for k, c in enumerate(self._children):
                if status[k] in ("up", "joining") and c.alive():
                    c.observe(now)
            if self._shutdown:
                return self._elastic_shutdown()
            # Exits.
            for k, c in enumerate(self._children):
                if status[k] not in ("up", "joining"):
                    continue
                rc = c.poll()
                if rc is None:
                    continue
                hung = c.hung
                joining = status[k] == "joining"
                self._book_rank_exit(k, c, c.finalize())
                outcome = classify_exit(rc)
                if outcome == POISON:
                    self._teardown("poison", k)
                    for r, cc in enumerate(self._children):
                        if r != k and status[r] in ("up", "joining"):
                            self._book_rank_exit(r, cc, cc.finalize())
                            status[r] = "down"
                    return self._give_up(
                        f"rank {k} exited poison ({rc}): respawning "
                        "cannot help", EXIT_POISON)
                if outcome == DONE and not hung:
                    status[k] = "done"
                    continue
                # Retryable crash, signal death, watchdog kill, or a
                # lone flush (43 outside a fleet eviction = that rank
                # alone was told to stop — it still needs replacing).
                if joining:
                    # The replacement died CATCHING UP (the
                    # rank_rejoin_flap shape): survivors' membership
                    # view never included it, so nothing re-forms —
                    # just burn its respawn budget and try again.
                    self._ledger("flap", rank=k, returncode=rc,
                                 respawns=self.respawns[k])
                    self._log(f"rank {k} replacement died before "
                              f"rejoin (code {rc}) — flapping; "
                              "survivors untouched")
                    status[k] = "down"
                    # The downtime clock keeps running from the ORIGINAL
                    # death (down_at already holds it) — a flap extends
                    # the outage, it doesn't restart the meter.
                    code = schedule_respawn(k)
                else:
                    code = lose_member(
                        k, "hang" if hung else f"exit {rc}")
                if code is not None:
                    return code
            # Hangs: escalate (rank-attributed), then the exit scan
            # above books the death on the next poll.
            for k, c in enumerate(self._children):
                if status[k] not in ("up", "joining") or not c.alive():
                    continue
                window = c.window_s(self.watchdog_s, self.startup_grace_s)
                stale = c.stale_s(now)
                if stale > window:
                    self._log(f"HANG on rank {k} — no heartbeat for "
                              f"{stale:.1f}s (window {window:.0f}s, last "
                              f"step {c.last_step}); SIGQUIT stack dump, "
                              "then SIGTERM, then SIGKILL")
                    self._ledger("hang", rank=k, stale_s=round(stale, 1),
                                 last_step=c.last_step,
                                 stack_dump=c.stack_dump,
                                 flight_dump=c.flight_dump)
                    c.escalate(self.quit_wait_s, self.grace_s)
            # Due respawns.
            for k in [r for r, t in due.items() if now >= t]:
                del due[k]
                self.respawns[k] += 1
                self.restarts += 1
                status[k] = "joining"
                self._ledger("respawn", rank=k, respawn=self.respawns[k],
                             resume_step=resume_step)
                # ENV_DOWN_SINCE carries the DEATH time, not the spawn
                # time, so the replacement's 'restart' event books the
                # full detection+backoff outage as downtime
                # (docs/observability.md: "death -> restore").
                self._spawn_rank(k, self.respawns[k],
                                 down_at.get(k, time.time()),
                                 resume_step)
            # Rejoins: a replacement that took its first post-restore
            # step is at the fleet boundary — fold it back in.
            for k, c in enumerate(self._children):
                if status[k] != "joining" or c.last_step is None:
                    continue
                status[k] = "up"
                self.rejoins += 1
                down_at.pop(k, None)
                # The rejoin record carries the standing resume cap, NOT
                # None: the membership file holds only the latest view,
                # so a survivor stalled through the whole degrade->rejoin
                # window (a long val pass) sees ONLY this record — with
                # the cap aboard (plus the watcher's skipped-version
                # count) it can still restore the fleet-agreed step
                # instead of silently training ahead of the re-formed
                # fleet.
                self._publish_membership("rejoin", members(), resume_step,
                                         rank=k)
                self._ledger("rejoin", rank=k, step=c.last_step,
                             respawn=self.respawns[k])
                self._log(f"rank {k} rejoined the fleet at step "
                          f"{c.last_step} (respawn {self.respawns[k]}) "
                          f"— membership back to {len(members())}/"
                          f"{self.ranks}")
            # Fleet-min progress bookkeeping (informational in elastic
            # mode — the crash-loop currency is the per-rank budget).
            lasts = [c.last_step for k, c in enumerate(self._children)
                     if status[k] in ("up", "done")]
            if lasts and all(s is not None for s in lasts):
                fleet = min(lasts)
                if (self.best_fleet_step is None
                        or fleet > self.best_fleet_step):
                    self.best_fleet_step = fleet
            if all(s in ("done", "lost") for s in status.values()):
                code = (0 if any(s == "done" for s in status.values())
                        else EXIT_CRASH_LOOP)
                self._log(f"elastic gang finished (statuses {status}); "
                          f"{self.degrades} degrade(s), "
                          f"{self.rejoins} rejoin(s), best fleet step "
                          f"{self.best_fleet_step}")
                self._ledger("done", restarts=self.restarts,
                             degrades=self.degrades,
                             rejoins=self.rejoins,
                             best_fleet_step=self.best_fleet_step,
                             returncode=code)
                return code

    # -- the supervision loop -------------------------------------------
    def run(self) -> int:
        installed = {}
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                installed[sig] = signal.signal(sig, self._on_signal)
            except (ValueError, OSError):  # non-main thread (tests)
                pass
        try:
            return self._run_elastic() if self.elastic else self._run()
        finally:
            for sig, prev in installed.items():
                try:
                    signal.signal(sig, prev)
                except (ValueError, OSError):
                    pass

    def _give_up(self, reason: str, code: int) -> int:
        self._log(f"GIVING UP (non-retryable): {reason}")
        self._ledger("giveup", reason=reason, restarts=self.restarts,
                     best_fleet_step=self.best_fleet_step, returncode=code)
        return code

    def _book_progress(self, res: GangAttempt) -> bool:
        """Fold one attempt into the per-rank and fleet-min ledgers;
        returns whether the FLEET made progress (the crash-loop
        currency — one healthy rank cannot mask a stuck peer)."""
        for k in range(self.ranks):
            first, last = res.first_steps[k], res.last_steps[k]
            if (first is not None and self.best_steps[k] is not None
                    and first > self.best_steps[k] + 1):
                self.violations += 1
                self._log(f"LEDGER VIOLATION: attempt {res.attempt} rank "
                          f"{k} first step {first} skips past its best "
                          f"previous step {self.best_steps[k]}")
                self._ledger("violation", attempt=res.attempt, rank=k,
                             first_step=first,
                             best_step=self.best_steps[k])
            if last is not None and (self.best_steps[k] is None
                                     or last > self.best_steps[k]):
                self.best_steps[k] = last
        progressed = (res.fleet_step is not None
                      and (self.best_fleet_step is None
                           or res.fleet_step > self.best_fleet_step))
        if progressed:
            self.best_fleet_step = res.fleet_step
        return progressed

    def _run(self) -> int:
        attempt = 0
        no_progress = 0
        down_since = time.time()
        while True:
            res = self._run_attempt(attempt, down_since)
            self.attempts.append(res)
            down_since = time.time()
            progressed = self._book_progress(res)
            if self._shutdown:
                # Shared eviction / operator stop: mirror the single
                # supervisor — flushes and completions propagate, any
                # other code is reported as-is (128+N for signal death).
                bad = [rc for rc in res.codes
                       if classify_exit(rc, True) == POISON]
                if bad:
                    code = bad[0]
                    if code < 0:
                        code = 128 - code
                    return self._give_up(
                        f"rank exit code(s) {res.codes} during supervisor "
                        "shutdown", code)
                code = (EXIT_PREEMPTED if EXIT_PREEMPTED in res.codes
                        else 0)
                self._log(f"gang shut down (codes {res.codes}); exit "
                          f"{code}")
                self._ledger("done", attempts=attempt + 1,
                             restarts=self.restarts,
                             best_fleet_step=self.best_fleet_step,
                             returncode=code)
                return code
            if res.outcome == DONE:
                self._log(f"gang completed cleanly (codes {res.codes}) "
                          f"after {attempt + 1} attempt(s), best fleet "
                          f"step {self.best_fleet_step}")
                self._ledger("done", attempts=attempt + 1,
                             restarts=self.restarts,
                             best_fleet_step=self.best_fleet_step,
                             returncode=0)
                return 0
            if res.outcome == POISON:
                ranks = [k for k, rc in enumerate(res.codes)
                         if rc == EXIT_POISON]
                return self._give_up(
                    f"rank(s) {ranks} exited poison (codes {res.codes}): "
                    "restarting the gang cannot help", EXIT_POISON)
            # Retryable (a rank crashed/hung) or a clean gang-wide
            # preemption flush. Budget/backoff/crash-loop semantics
            # mirror the single supervisor, but progress is FLEET-MIN:
            # a peer stuck at step 0 keeps the streak alive no matter
            # how far the healthy ranks run ahead.
            if progressed:
                no_progress = 0
            elif (all(s is None for s in res.last_steps)
                  and not res.hung_ranks
                  and res.duration_s >= self.startup_grace_s
                  + self.watchdog_s):
                # Step-less gang (supervised serve replicas beat, never
                # step): a life that outlived startup grace + a full
                # watchdog window was demonstrably beating on every rank.
                no_progress = 0
            else:
                no_progress += 1
            if (res.outcome == RETRYABLE
                    and self.crash_restarts >= self.max_restarts):
                return self._give_up(
                    f"restart budget exhausted ({self.max_restarts} "
                    "retryable gang failures)", EXIT_CRASH_LOOP)
            if no_progress >= self.crash_loop_k:
                return self._give_up(
                    f"gang crash loop: {no_progress} consecutive attempts "
                    f"with no fleet-min step progress (stuck at "
                    f"{self.best_fleet_step}; per-rank best "
                    f"{self.best_steps}) — the failure is deterministic, "
                    "restarting cannot help", EXIT_CRASH_LOOP)
            self.restarts += 1
            if res.outcome == RETRYABLE:
                self.crash_restarts += 1
            why = (f"hang on rank(s) {res.hung_ranks}" if res.hung_ranks
                   else "gang preemption flush"
                   if res.outcome == PREEMPTED
                   else f"rank crash (codes {res.codes})")
            delay = 0.0
            if res.outcome == RETRYABLE:
                delay = min(self.backoff_max_s,
                            self.backoff_s
                            * (2.0 ** max(0, no_progress - 1)))
            budget = (f" (crash {self.crash_restarts}/{self.max_restarts})"
                      if res.outcome == RETRYABLE else "")
            self._log(f"attempt {attempt} ended ({why}); coordinated gang "
                      f"restart #{self.restarts} with resume{budget}"
                      + (f" after {delay:.1f}s backoff" if delay else ""))
            if delay:
                deadline = time.monotonic() + delay
                while time.monotonic() < deadline and not self._shutdown:
                    time.sleep(min(0.2, delay))
                if self._shutdown:
                    return self._give_up(
                        "shutdown requested during backoff", EXIT_PREEMPTED)
            attempt += 1
