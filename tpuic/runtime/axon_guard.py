"""Guard against the dev image's tunneled TPU backend hanging at init.

This image's sitecustomize force-registers a remote 'axon' TPU backend
whenever PALLAS_AXON_POOL_IPS is set; when the tunnel is down, backend
initialization HANGS rather than erroring (rounds 1-3 failure mode, and a
killed client wedges the chip for hours). Every entry point that must not
hang shares these helpers:

- ``drop_axon_vars(env)``: strip the trigger vars from a child-process env
  so a CPU child stays a plain CPU interpreter.
- ``force_cpu()``: switch THIS process to CPU (env + jax.config — the env
  var alone loses to the sitecustomize's explicit platform registration).
- ``tpu_reachable(timeout)``: probe backend init in a killable child.

Real TPU hosts don't set the trigger vars; everything here is a no-op cost
for them.
"""

from __future__ import annotations

import os
import subprocess
import sys

AXON_ENV_VARS = ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE",
                 "AXON_POOL_SVC_OVERRIDE", "AXON_LOOPBACK_RELAY")


def is_tunneled() -> bool:
    return bool(os.environ.get("PALLAS_AXON_POOL_IPS"))


def drop_axon_vars(env: dict) -> dict:
    for v in AXON_ENV_VARS:
        env.pop(v, None)
    return env


def force_cpu() -> None:
    """Switch this process to the CPU backend (safe only before the first
    device use)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    drop_axon_vars(os.environ)
    import jax
    jax.config.update("jax_platforms", "cpu")


def tpu_reachable(timeout: float = 120.0) -> bool:
    """True when backend init completes in a killable child process.

    Risk note: killing the probe child on timeout terminates a client
    mid-init. A client killed mid-WORK wedges the remote chip for hours
    (measured round 3); an init-phase client has not yet been granted a
    claim, so the probe is the least-bad place to take that risk — but
    keep the timeout generous (backend init on a healthy tunnel takes
    seconds, the bench budget allows 420)."""
    try:
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout, capture_output=True)
        return probe.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def exit_if_unreachable(timeout: float | None = None,
                        exit_code: int = 2) -> None:
    """Refuse to start when the tunneled backend is down.

    Measurement entry points (perf_sweep, long_seq_bench, fit_proof,
    convergence_digits, *_smoke, *_proof) call this first: on the dev
    image a dead tunnel makes backend init HANG ~25 minutes before
    raising (measured 2026-08-01 08:56Z), which burns exactly the
    recovery windows the chip queues exist to exploit. Prints the shared
    machine-readable error line and exits. No-op off the tunneled image
    (real TPU hosts, or deliberate CPU runs with the axon vars stripped).
    """
    import json
    if timeout is None:
        # Same operator knob ensure_reachable_or_cpu honors, default 150
        # (the queue scripts' established probe budget).
        timeout = float(os.environ.get("TPUIC_TPU_PROBE_S", "150"))
    if is_tunneled() and not tpu_reachable(timeout):
        print(json.dumps({"error": "tpu tunnel unreachable; not starting"}))
        raise SystemExit(exit_code)


def ensure_reachable_or_cpu(timeout: float | None = None,
                            verbose: bool = True,
                            always_probe: bool = False) -> bool:
    """Probe the backend; fall back to CPU when unreachable.

    Returns True when the accelerator path is usable. Off the dev image
    the probe is skipped unless ``always_probe`` (benchmarks that promise
    a result on ANY failure — e.g. a chip held by another process, which
    raises rather than hangs — probe everywhere)."""
    if not is_tunneled() and not always_probe:
        return True
    t = timeout if timeout is not None else float(
        os.environ.get("TPUIC_TPU_PROBE_S", "120"))
    if tpu_reachable(t):
        return True
    if verbose:
        print("[tpuic] TPU tunnel unreachable — falling back to CPU "
              "(set TPUIC_TPU_PROBE_S to adjust the probe timeout)",
              flush=True)
    force_cpu()
    return False
