"""Device-mesh construction and sharding helpers.

The reference's topology model is one process per GPU with a fully replicated
model (DDP, train.py:128). The TPU-native model is a named
``jax.sharding.Mesh`` over the pod slice with a ``data`` axis (batch sharding —
the DDP equivalent) and a ``model`` axis (tensor sharding — reserved so TP is a
config change, SURVEY.md §2c). ``jax.make_mesh`` lays the axes onto the
physical ICI torus so the heavy ``data``-axis collectives ride neighbor links.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpuic.config import MeshConfig


def make_mesh(cfg: Optional[MeshConfig] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a (data, model) mesh over all devices.

    cfg.data == 0 infers the data-axis size as n_devices / model. jax.make_mesh
    picks an ICI-friendly device order on real TPU slices; on CPU test meshes
    the order is row-major over jax.devices().
    """
    cfg = cfg or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    seq, model = max(1, cfg.seq), max(1, cfg.model)
    if n % (seq * model):
        raise ValueError(f"seq*model axes {seq}x{model} do not divide "
                         f"device count {n}")
    data = cfg.data or n // (seq * model)
    shape = (data, seq, model)
    if data * seq * model != n:
        raise ValueError(f"mesh {shape} != device count {n}")
    # Auto axis types: shardings constrain data layout and GSPMD propagates /
    # inserts collectives (jax>=0.9 defaults make_mesh to Explicit
    # sharding-in-types, which instead demands out_sharding annotations on
    # every contraction touching a sharded dim — not the model we want).
    # jax < 0.6 has no AxisType (no sharding-in-types): the plain Mesh
    # fallback IS Auto semantics there.
    axis_type = getattr(jax.sharding, "AxisType", None)
    if axis_type is not None:
        try:
            return jax.make_mesh(shape, tuple(cfg.axis_names),
                                 axis_types=(axis_type.Auto,) * len(shape),
                                 devices=devices)
        except TypeError:
            pass  # older make_mesh signature without axis_types/devices
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, tuple(cfg.axis_names))


def replica_mesh(replicas: int, cfg: Optional[MeshConfig] = None,
                 devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """An R-replica data-parallel mesh over the FIRST ``replicas`` replica
    slots — the elastic re-form constructor (docs/parallelism.md,
    "Elastic data parallelism").

    ``make_mesh`` demands that the axes cover every device; a fleet that
    just lost a replica needs the opposite: the same (seq, model) inner
    shape laid over ``replicas`` of the surviving replica slots, with the
    rest of the host's devices idle. Each replica slot is ``seq*model``
    consecutive devices, so shrinking R keeps every surviving replica's
    inner axes on the same devices (no param migration inside a
    replica — only the data axis narrows, which is exactly what the
    ZeRO-sharded optimizer state reshards over on the capped restore)."""
    cfg = cfg or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    replicas = int(replicas)
    if replicas < 1:
        raise ValueError(f"replica_mesh needs >= 1 replica (got {replicas})")
    per_replica = max(1, cfg.seq) * max(1, cfg.model)
    need = replicas * per_replica
    if need > len(devices):
        raise ValueError(
            f"replica_mesh: {replicas} replicas x {per_replica} devices "
            f"each = {need} devices, but only {len(devices)} available")
    return make_mesh(dataclasses.replace(cfg, data=replicas),
                     devices=devices[:need])


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Batch-dim sharding over the data axis — the DDP-equivalent layout."""
    return NamedSharding(mesh, P("data"))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully replicated layout (params/opt state under pure DP)."""
    return NamedSharding(mesh, P())


def local_batch_slice(global_batch: int, mesh: Mesh) -> int:
    """Per-process share of a global batch under data sharding."""
    procs = jax.process_count()
    if global_batch % procs:
        raise ValueError(f"global batch {global_batch} not divisible by "
                         f"{procs} processes")
    return global_batch // procs
