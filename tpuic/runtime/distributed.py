"""Multi-host runtime initialization.

TPU-native replacement for the reference's NCCL process-group setup
(train.py:99-106):

- ``dist.init_process_group('nccl', rank=local_rank)`` + env-var rendezvous
  becomes ``jax.distributed.initialize()`` — the TPU runtime discovers the pod
  slice topology itself; no MASTER_ADDR/PORT plumbing.
- ``torch.cuda.set_device(local_rank)`` has no equivalent: one JAX process per
  host addresses all of its local chips; device binding is the mesh's job.
- ``args.distributed = world_size >= 1`` (reference train.py:104 — always True,
  a latent bug) becomes an honest ``is_distributed`` = process_count > 1 or
  device_count > 1.

Collectives are never issued eagerly from Python the way torch.distributed
does; they are traced into the jitted step and lowered by XLA onto ICI
(intra-slice torus) / DCN (across slices).
"""

from __future__ import annotations

import dataclasses
import os

import jax


@dataclasses.dataclass(frozen=True)
class RuntimeInfo:
    process_index: int
    process_count: int
    local_device_count: int
    global_device_count: int
    platform: str

    @property
    def is_distributed(self) -> bool:
        return self.process_count > 1 or self.global_device_count > 1


_initialized = False

# Env markers whose presence means a cluster launcher started this process and
# jax.distributed can auto-discover the topology (TPU pod runtime, GKE
# JobSet, or an explicit coordinator address).
_CLUSTER_ENV_MARKERS = ("TPU_WORKER_HOSTNAMES", "JAX_COORDINATOR_ADDRESS",
                        "COORDINATOR_ADDRESS", "MEGASCALE_COORDINATOR_ADDRESS")


def _looks_multi_host() -> bool:
    hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if hosts and len(hosts.split(",")) > 1:
        return True
    return any(os.environ.get(m) for m in _CLUSTER_ENV_MARKERS[1:])


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> RuntimeInfo:
    """Initialize the multi-host runtime (idempotent).

    jax.distributed.initialize() is called when (a) explicit coordinator
    arguments are given, (b) TPUIC_NUM_PROCESSES > 1, or (c) a cluster
    launcher's environment markers are present (multi-worker TPU pod /
    explicit coordinator address) — in case (c) with no arguments, letting
    JAX auto-discover the topology. Plain single-process runs skip it.

    Launchers without a cluster runtime (CPU fleets, the CI fleet smoke
    — scripts/fleet_smoke.py; the gang supervisor's ``--coordinator``
    path, runtime/gang.py) pass the rendezvous through the environment
    instead of code: TPUIC_COORDINATOR_ADDRESS + TPUIC_NUM_PROCESSES +
    TPUIC_PROCESS_ID fill any argument the caller left None, so
    ``python train.py`` joins a fleet without new flags. Explicit
    arguments always win over the env. A HALF-set env rendezvous
    (coordinator or process id without the full trio resolvable) raises
    instead of silently falling back to auto-detection — the same loud
    failure as telemetry/fleet.py's ``tag_bus_with_rank``: half a fleet
    identity is not an identity, and k workers silently collapsing to
    auto-discovered rank 0/1 would wedge the rendezvous (or worse,
    train as the wrong fleet) with nothing in the logs.
    """
    global _initialized
    env_addr = os.environ.get("TPUIC_COORDINATOR_ADDRESS") or None
    env_num = os.environ.get("TPUIC_NUM_PROCESSES") or None
    env_pid = os.environ.get("TPUIC_PROCESS_ID") or None
    if coordinator_address is None:
        coordinator_address = env_addr
    if num_processes is None and env_num:
        num_processes = int(env_num)
    if process_id is None and env_pid is not None:
        process_id = int(env_pid)
    if (env_addr is not None or env_pid is not None) and (
            coordinator_address is None or num_processes is None
            or process_id is None):
        # TPUIC_NUM_PROCESSES alone stays valid (the documented
        # auto-discovery trigger); naming a coordinator or a process id
        # commits the launcher to the full trio.
        raise ValueError(
            f"TPUIC env rendezvous is half-set: TPUIC_COORDINATOR_ADDRESS="
            f"{env_addr!r}, TPUIC_NUM_PROCESSES={env_num!r}, "
            f"TPUIC_PROCESS_ID={env_pid!r} — a launcher must set all "
            "three (or none; TPUIC_NUM_PROCESSES alone keeps the "
            "auto-discovery path)")
    multi = (coordinator_address is not None
             or num_processes not in (None, 1)
             or _looks_multi_host())
    if multi and not _initialized:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        _initialized = True
    return runtime_info()


def runtime_info() -> RuntimeInfo:
    return RuntimeInfo(
        process_index=jax.process_index(),
        process_count=jax.process_count(),
        local_device_count=jax.local_device_count(),
        global_device_count=jax.device_count(),
        platform=jax.devices()[0].platform,
    )


def data_parallel_replicas() -> int:
    """The CURRENT data-parallel extent of this process's fleet.

    Elastic runs (runtime/gang.py elastic mode) publish the live
    membership via ``TPUIC_MEMBERSHIP_FILE`` — its ``active`` count is
    the R the fleet is actually running at, which may be below the
    configured world mid-degrade. Without a membership file, the
    launcher's ``TPUIC_FLEET_RANKS`` override wins (independent-rank CPU
    fleets), then the live ``jax.process_count()``. Poll-cheap (one
    stat + read only when the file moved is the watcher's job; this is
    the one-shot read for wiring/telemetry, not the hot loop)."""
    from tpuic.runtime.membership import ENV_MEMBERSHIP_FILE, read_membership
    path = os.environ.get(ENV_MEMBERSHIP_FILE, "")
    if path:
        m = read_membership(path)
        if m is not None:
            return max(1, m.replicas)
    ranks = os.environ.get("TPUIC_FLEET_RANKS")
    if ranks:
        return max(1, int(ranks))
    return jax.process_count()
