"""Elastic fleet membership: the file protocol between the gang
supervisor and its ranks.

The gang supervisor (``runtime/gang.py``) treats a lost rank as a
*degrade* event, not a restart event (docs/parallelism.md, "Elastic
data parallelism"): survivors re-form an (R-1)-replica view from the
fleet-agreed checkpoint step without their processes restarting, and a
replacement rank rejoins at the next fleet-agreed boundary.  The only
channel wide enough for that — without the parent importing jax or the
ranks opening sockets — is a single atomically-rewritten JSON file,
exactly the heartbeat-file pattern in reverse:

- **Writer** (the supervisor): :func:`write_membership` rewrites the
  file tmp+rename on every membership transition, with a monotonically
  increasing ``version`` so readers order transitions without clocks.
- **Reader** (each rank): :class:`MembershipWatcher` polls from the
  train loop at step boundaries (one ``os.stat`` per poll; the file is
  re-read only when its mtime/size moved) and surfaces each *new*
  version exactly once — the trainer reacts at its next boundary, the
  same latch-then-act shape as the preemption guard.

The record itself (:class:`Membership`) carries the full fleet view:
``world`` (the configured replica count R), ``active`` (the rank ids
currently in the mesh), ``resume_step`` (the fleet-agreed checkpoint
step survivors re-form from — ``CheckpointManager.restore_into``'s
resume cap), and ``reason`` (``init``/``degrade``/``rejoin``/
``restart``).  Like the supervisor, this module imports only the
stdlib: the parent must never initialize jax.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import List, Optional

# Injected into every rank of an elastic gang; the Trainer watches the
# file when (and only when) the env is present — zero cost otherwise.
ENV_MEMBERSHIP_FILE = "TPUIC_MEMBERSHIP_FILE"

REASONS = ("init", "degrade", "rejoin", "restart")


@dataclasses.dataclass(frozen=True)
class Membership:
    """One fleet-membership view, as the supervisor published it."""
    version: int                  # strictly increasing per transition
    world: int                    # configured replica count R
    active: List[int]             # rank ids currently in the mesh
    resume_step: Optional[int]    # fleet-agreed checkpoint step (cap)
    reason: str                   # one of REASONS
    rank: Optional[int] = None    # the rank the transition is about
    t: float = 0.0                # wall time of the write (informational)

    @property
    def replicas(self) -> int:
        """The data-parallel extent of this view — len(active)."""
        return len(self.active)


def write_membership(path: str, m: Membership) -> None:
    """Atomically publish ``m`` (tmp + rename: readers never see a torn
    record, and a SIGKILL mid-write leaves the previous view in force)."""
    if m.reason not in REASONS:
        raise ValueError(f"membership reason {m.reason!r} not in {REASONS}")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(dataclasses.asdict(m), f)
    os.replace(tmp, path)


def read_membership(path: str) -> Optional[Membership]:
    """The current view, or None when absent/unreadable/torn (a reader
    mid-transition keeps its previous view rather than crashing)."""
    try:
        with open(path) as f:
            raw = json.load(f)
        return Membership(
            version=int(raw["version"]), world=int(raw["world"]),
            active=[int(r) for r in raw["active"]],
            resume_step=(None if raw.get("resume_step") is None
                         else int(raw["resume_step"])),
            reason=str(raw.get("reason", "init")),
            rank=(None if raw.get("rank") is None else int(raw["rank"])),
            t=float(raw.get("t", 0.0)))
    except (OSError, ValueError, KeyError, TypeError):
        return None


class MembershipWatcher:
    """Rank-side poller: surfaces each NEW membership version once.

    ``poll()`` is designed for the train loop's per-step cadence: one
    ``os.stat`` when nothing changed (no read, no parse).  The first
    poll swallows the initial view (``reason='init'`` — the world the
    rank was spawned into is not a transition), so only genuine
    mid-run changes reach the trainer."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._stamp = None            # (mtime_ns, size) last parsed
        self._version = -1            # last version surfaced or swallowed
        self.current: Optional[Membership] = None
        # Versions that came and went between polls before the one
        # ``poll()`` just surfaced (the file holds only the latest view,
        # so a degrade overwritten by its rejoin can coalesce): readers
        # that must not miss a restore directive check this — a surfaced
        # record with ``skipped > 0`` may stand in for an unseen degrade.
        self.skipped = 0
        # Prime on the spawn-time view: a rank joining an already-degraded
        # fleet must not treat the standing view as a fresh transition.
        first = self._read_if_changed()
        if first is not None:
            self._version = first.version

    @classmethod
    def from_env(cls) -> Optional["MembershipWatcher"]:
        path = os.environ.get(ENV_MEMBERSHIP_FILE, "")
        return cls(path) if path else None

    def _read_if_changed(self) -> Optional[Membership]:
        try:
            st = os.stat(self.path)
        except OSError:
            return None
        stamp = (st.st_mtime_ns, st.st_size)
        if stamp == self._stamp:
            return None
        self._stamp = stamp
        m = read_membership(self.path)
        if m is not None:
            self.current = m
        return m

    def poll(self) -> Optional[Membership]:
        """The new view if the membership CHANGED since last surfaced
        (or since the spawn-time view), else None. ``self.skipped``
        counts the versions that coalesced away between polls."""
        m = self._read_if_changed()
        if m is None or m.version <= self._version:
            return None
        self.skipped = (m.version - self._version - 1
                        if self._version >= 0 else 0)
        self._version = m.version
        return m
