"""Deterministic fault injection — the harness that proves the
fault-tolerance layer works (SURVEY.md §5: the reference has no failure
story at all, so none of its failure paths are *testable* either).

Every recovery path in this repo (non-finite step guard + rollback,
checkpoint integrity ladder, sample quarantine, preemption drain) has an
injection point registered here, so tests drive each failure
deterministically on CPU instead of waiting for a real corrupt JPEG or a
real scheduler kill. Injection is **off by default and free**: an unarmed
``fire()`` is one dict lookup.

Registered points (site → meaning of ``step``):

- ``nan_batch``     — train loop (train/loop.py): poison this step's batch
                      images with NaN before the jitted step. ``step`` is
                      the host-tracked global optimizer step.
- ``sigterm``       — train loop: deliver SIGTERM to this process at the
                      given global step (drives the PreemptionGuard flush).
- ``decode_error``  — ImageFolderDataset.load (data/folder.py): raise an
                      OSError in place of the decode. ``step`` is the
                      dataset index.
- ``ckpt_kill``     — CheckpointManager commit (checkpoint/manager.py):
                      raise InjectedFault after the staged save is written
                      but BEFORE it is rotated into its track — the
                      SIGKILL-mid-write simulation (the committed track
                      must survive untouched).
- ``hang_device``   — InferenceEngine._dispatch (serve/engine.py): sleep
                      ``param`` seconds before the device call — a stuck
                      device call for drain-timeout tests.
- ``slow_step``     — train loop: sleep ``param`` seconds (default 0.05)
                      before dispatching this step — a deterministic
                      step-time regression for the telemetry trace
                      trigger (telemetry/tracing.py). ``step`` is the
                      host-tracked global optimizer step.
- ``hard_crash``    — train loop: SIGKILL this process at the given
                      global step — abrupt death with no flush, no
                      atexit, no handler (the supervisor's retryable-
                      crash + resume path, runtime/supervisor.py).
- ``hang_step``     — train loop: stop making progress at the given
                      global step (sleep ``param`` seconds; forever
                      without a payload) — a wedged device call / data
                      deadlock for the supervisor's heartbeat watchdog.
- ``flood``         — serve driver (serve/__main__.py): a synthetic
                      low-priority request storm submitted from inside
                      the process at ``param`` requests/sec (default 50)
                      for the life of the server — reproducible overload
                      for the admission-control layer (docs/serving.md):
                      ``TPUIC_FAULTS='flood#200'`` drives the engine
                      past its knee with traffic the brownout/priority
                      machinery is supposed to shed.
- ``rank_crash``    — train loop: SIGKILL this process at the given
                      global step, but ONLY on the rank ``param`` names
                      (default 0; rank identity from the telemetry fleet
                      tag — TPUIC_FLEET_RANK or runtime_info). The
                      partial-failure trigger for the gang supervisor
                      (runtime/gang.py): ``rank_crash@8#1`` kills rank 1
                      at step 8 while every other rank keeps running —
                      exactly the one-dead-rank-wedges-the-fleet shape
                      the coordinated teardown exists for.
- ``rank_hang``     — train loop: wedge FOREVER at the given global
                      step, only on rank ``param`` (default 0) — the
                      partial-hang twin of ``rank_crash`` for the gang's
                      per-rank watchdog (rank-attributed SIGQUIT
                      escalation, then coordinated teardown).
- ``replica_crash`` — serve socket transport (serve/__main__.py,
                      ``--listen``): SIGKILL this replica process after
                      accepting the Nth request (``step`` = the accept
                      counter) — abrupt replica death mid-storm for the
                      router's breaker + in-flight failover path
                      (tpuic/serve/router.py, docs/serving.md "Replica
                      routing and failover").
- ``bf16_master_truncate`` — train step TRACE time (train/step.py
                      ``_apply_update``): bake a bf16 round-trip of the
                      updated master weights into the compiled step — the
                      classic no-f32-master mixed-precision bug. Drives
                      the ``scripts/bf16_parity.py --expect-fail`` arm
                      (the convergence-parity gate must catch it).
- ``swap_corrupt``  — hot-swap admission gate (checkpoint/loading.py
                      ``load_candidate_variables``): corrupt the swap
                      CANDIDATE's staged bytes (one payload file,
                      :func:`corrupt_file`) after it is located but
                      BEFORE the CRC/manifest verification — bit-rot
                      between producer and gate.  The gate must then
                      refuse the candidate with a typed
                      ``swap_corrupt`` verdict and the incumbent keeps
                      serving (docs/serving.md, "Model lifecycle").
- ``canary_degrade``— serve engine batcher (serve/engine.py
                      ``_dispatch``): sleep ``param`` seconds (default
                      0.05) per device batch, but ONLY while the engine
                      serves weights other than the ones it booted with
                      — i.e. the candidate a hot-swap installed.  A
                      fleet armed with ``canary_degrade#0.2`` degrades
                      exactly the canary replicas mid-rollout (the
                      SLO-burn auto-rollback trigger); rolling back to
                      the boot weights stands the fault down, so the
                      post-rollback fleet is provably healthy again.
- ``rank_rejoin_flap`` — fleet-capped checkpoint restore
                      (checkpoint/manager.py ``restore_into``): SIGKILL
                      this process while it is INSIDE its catch-up
                      restore (a resume cap is in force — the elastic
                      gang's degrade/rejoin path, runtime/gang.py), but
                      only on the rank ``param`` names (default 0, from
                      ``TPUIC_FLEET_RANK``) and only in a respawned
                      life (``TPUIC_RESTART`` > 0) — so the original
                      ranks' spawn-time restores never trip it. The
                      flapping-replacement trigger: a rejoining rank
                      that dies mid-catch-up must burn its own respawn
                      budget without wedging or desyncing the
                      survivors (scripts/elastic_soak.py proves the
                      second replacement rejoins and the final metrics
                      stay bitwise-equal to the undisturbed baseline).
- ``replica_wedge`` — serve socket transport: stop servicing the socket
                      at the Nth accepted request (sleep ``param``
                      seconds; effectively forever without a payload) —
                      pings go unanswered and the heartbeat goes stale,
                      the shape the router's wedge watchdog escalates
                      via the ``_Child`` SIGQUIT→TERM→KILL ladder.
- ``scorer_crash``  — bulk-score shard commit (score/commit.py): SIGKILL
                      this worker in the NASTIEST window — after its
                      result file is linked into place but before the
                      CRC manifest and the ledger record exist.
                      ``step`` is the worker's 1-based shard-commit
                      ordinal this life; ``#PARAM`` names the victim
                      rank (default 0, the ``rank_crash`` convention):
                      ``scorer_crash@1#1`` kills rank 1 at its first
                      commit.  Drives the survivor's adopt/recover path
                      (scripts/score_soak.py proves the resumed job's
                      ledger is exact and bitwise-equal to an
                      undisturbed baseline).
- ``shard_corrupt`` — bulk-score shard read (score/driver.py): report
                      one packed row of shard ``step`` as failing its
                      stored CRC32 — the at-rest .bin bit-rot verdict,
                      injected deterministically.  ``#PARAM`` is the row
                      offset within the shard (default 0).  The row must
                      land in the ledger's quarantined column with the
                      corpus accounting still exact (scored +
                      quarantined == corpus).
- ``lease_skew``    — shard-lease expiry check (score/work.py): age
                      every OBSERVED lease by ``param`` extra seconds
                      (default one full TTL — instant expiry), the
                      clock-drift that makes a live peer's lease look
                      dead.  ``step`` is the shard id.  Two live ranks
                      then score the same shard concurrently; the
                      commit layer's link-arbitrated exactly-once must
                      hold and the ledger audit must surface the
                      duplicate loudly.

Arming: programmatic (tests) via ``arm()``/``disarm()``/``reset()``, or
the ``TPUIC_FAULTS`` env var for whole-process CLI runs, a comma list of
``point[@STEP|@LO-HI][*TIMES][#PARAM]`` directives, e.g.::

    TPUIC_FAULTS='nan_batch@100-105,sigterm@200' python train.py ...
    TPUIC_FAULTS='slow_step#0.3' python train.py ...   # 0.3 s per step

``#PARAM`` (a float) sets the point's payload — the sleep seconds of
``slow_step``/``hang_device``/``hang_step`` — so a chaos spec can dial
the severity (the perf-regression gate seeds a decisive slowdown this
way; telemetry/regress.py).

Spec directives are validated at parse time: naming an unregistered
injection point (or a malformed step/times field) raises ValueError
listing the registered points, so a typo'd chaos spec fails the run
loudly instead of passing as "no faults fired". Programmatic ``arm()``
stays unchecked (unit tests may use ad-hoc points).

File-corruption helpers (``truncate_file``, ``corrupt_file``) live here
too: they are the test-side tools for the *at-rest* faults (truncated
image, corrupt checkpoint file) that have no code injection point.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Iterable, Optional, Union

__all__ = ["InjectedFault", "FaultPlan", "plan", "arm", "disarm", "reset",
           "fire", "param", "fired", "truncate_file", "corrupt_file",
           "REGISTERED_POINTS"]

# Every injection point a site actually calls fire() on. TPUIC_FAULTS
# directives must name one of these — the spec parser fails fast on
# anything else (a typo'd chaos directive that silently never fires would
# read as "the system survived the fault" when no fault happened).
REGISTERED_POINTS = frozenset({
    "nan_batch", "sigterm", "decode_error", "ckpt_kill", "hang_device",
    "slow_step", "hard_crash", "hang_step", "flood", "rank_crash",
    "rank_hang", "rank_rejoin_flap", "replica_crash", "replica_wedge",
    "swap_corrupt", "canary_degrade", "bf16_master_truncate",
    "scorer_crash", "shard_corrupt", "lease_skew",
})


class InjectedFault(RuntimeError):
    """Raised by injection points that simulate a hard kill mid-operation
    (distinct type so tests can assert it was THIS fault, and production
    except-clauses never swallow it by accident)."""


class _Arm:
    __slots__ = ("steps", "times", "param", "count")

    def __init__(self, steps, times, param):
        self.steps = steps      # None = any step; else a set of ints
        self.times = times      # None = unlimited; else max firings
        self.param = param      # free-form payload (e.g. hang seconds)
        self.count = 0          # firings so far


class FaultPlan:
    """A set of armed injection points. Thread-safe: fire() is called from
    producer threads, the serve batcher, and the train loop alike."""

    def __init__(self, spec: str = "") -> None:
        self._lock = threading.Lock()
        self._arms: Dict[str, _Arm] = {}
        self.fired: Dict[str, int] = {}
        if spec:
            self._parse(spec)

    def _parse(self, spec: str) -> None:
        for raw in spec.split(","):
            directive = raw.strip()
            if not directive:
                continue
            try:
                param = None
                if "#" in directive:
                    directive, pv = directive.rsplit("#", 1)
                    param = float(pv)
                times = None
                if "*" in directive:
                    directive, t = directive.rsplit("*", 1)
                    times = int(t)
                steps: Optional[Iterable[int]] = None
                if "@" in directive:
                    directive, s = directive.split("@", 1)
                    if "-" in s:
                        lo, hi = s.split("-", 1)
                        steps = range(int(lo), int(hi) + 1)
                    else:
                        steps = (int(s),)
            except ValueError:
                raise ValueError(
                    f"TPUIC_FAULTS: malformed directive {raw.strip()!r} "
                    "(expected point[@STEP|@LO-HI][*TIMES][#PARAM])"
                ) from None
            if directive not in REGISTERED_POINTS:
                raise ValueError(
                    f"TPUIC_FAULTS: unknown injection point {directive!r} "
                    f"(registered: {', '.join(sorted(REGISTERED_POINTS))}) "
                    "— refusing to run a chaos spec that would silently "
                    "never fire")
            self.arm(directive, steps=steps, times=times, param=param)

    def arm(self, point: str, *, steps: Union[int, Iterable[int], None] = None,
            times: Optional[int] = None, param=None) -> None:
        """Arm ``point``: fire at the given ``steps`` (int, iterable, or
        None = every call), at most ``times`` total firings."""
        if isinstance(steps, int):
            steps = (steps,)
        with self._lock:
            self._arms[point] = _Arm(
                None if steps is None else frozenset(int(s) for s in steps),
                times, param)

    def disarm(self, point: Optional[str] = None) -> None:
        with self._lock:
            if point is None:
                self._arms.clear()
            else:
                self._arms.pop(point, None)

    def reset(self) -> None:
        """Disarm everything and clear firing history (test isolation)."""
        with self._lock:
            self._arms.clear()
            self.fired.clear()

    def param(self, point: str):
        """The armed payload of ``point`` (None when unarmed or no payload)."""
        with self._lock:
            a = self._arms.get(point)
            return a.param if a is not None else None

    def fire(self, point: str, step: Optional[int] = None) -> bool:
        """True iff ``point`` is armed for this call — and records the
        firing. The injection SITE decides what a firing means."""
        with self._lock:
            a = self._arms.get(point)
            if a is None:
                return False
            if a.steps is not None and (step is None
                                        or int(step) not in a.steps):
                return False
            if a.times is not None and a.count >= a.times:
                return False
            a.count += 1
            self.fired[point] = self.fired.get(point, 0) + 1
            return True


# The process-global plan: sites call the module-level functions, tests and
# the TPUIC_FAULTS env var arm it.
plan = FaultPlan(os.environ.get("TPUIC_FAULTS", ""))


def arm(point: str, *, steps=None, times=None, param=None) -> None:
    plan.arm(point, steps=steps, times=times, param=param)


def disarm(point: Optional[str] = None) -> None:
    plan.disarm(point)


def reset() -> None:
    plan.reset()


def fire(point: str, step: Optional[int] = None) -> bool:
    return plan.fire(point, step)


def param(point: str):
    return plan.param(point)


def fired(point: str) -> int:
    return plan.fired.get(point, 0)


# -- at-rest corruption helpers (test-side tools) --------------------------
def truncate_file(path: str, keep: int = 8) -> None:
    """Truncate ``path`` to its first ``keep`` bytes — the classic
    interrupted-copy / interrupted-write artifact (truncated JPEG, half a
    checkpoint shard)."""
    with open(path, "r+b") as f:
        f.truncate(keep)


def corrupt_file(path: str, offset: int = 0, nbytes: int = 16) -> None:
    """Flip ``nbytes`` bytes of ``path`` starting at ``offset`` (XOR 0xFF)
    — silent bit-rot that keeps the file size, so only content checksums
    (the checkpoint manifest) can catch it."""
    size = os.path.getsize(path)
    offset = min(offset, max(0, size - 1))
    n = min(nbytes, size - offset)
    with open(path, "r+b") as f:
        f.seek(offset)
        data = f.read(n)
        f.seek(offset)
        f.write(bytes(b ^ 0xFF for b in data))
