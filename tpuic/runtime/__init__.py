"""Runtime: multi-host init, mesh construction, tunneled-backend guard.

Lazy re-exports (PEP 562): ``tpuic.runtime.axon_guard`` must stay importable
without pulling in jax (see tpuic/__init__.py).
"""

_LAZY = {
    "initialize": ("tpuic.runtime.distributed", "initialize"),
    "runtime_info": ("tpuic.runtime.distributed", "runtime_info"),
    "make_mesh": ("tpuic.runtime.mesh", "make_mesh"),
    "data_sharding": ("tpuic.runtime.mesh", "data_sharding"),
    "replicated_sharding": ("tpuic.runtime.mesh", "replicated_sharding"),
}


def __getattr__(name):
    if name in _LAZY:
        import importlib
        module, attr = _LAZY[name]
        return getattr(importlib.import_module(module), attr)
    raise AttributeError(f"module 'tpuic.runtime' has no attribute '{name}'")


def __dir__():
    return sorted(list(globals()) + list(_LAZY))
