from tpuic.runtime.distributed import initialize, runtime_info  # noqa: F401
from tpuic.runtime.mesh import make_mesh, data_sharding, replicated_sharding  # noqa: F401
