"""tpuic.compiled — the process-wide compiled-program registry
(docs/performance.md, "Compiled-program registry").

One executable cache for train, serve, and bench: ``ProgramKey`` keys
``(model, shapes, mesh, dtype, generation)``, ``registry`` owns
lowering/AOT compilation, cost-analysis capture, hit/miss/prewarm
accounting, generation-scoped GC, and the donation-safety policy
(:func:`donation_allowed`); ``manifest`` persists compiled keys so
restarts prewarm every known program up front.
"""

from tpuic.compiled.manifest import (MANIFEST_VERSION, ManifestError,
                                     load_manifest, save_manifest)
from tpuic.compiled.registry import (CompiledEntry, ProgramKey,
                                     ProgramRegistry, avals_crc,
                                     donation_allowed, registry, stable_crc,
                                     tree_avals)

__all__ = [
    "ProgramKey", "CompiledEntry", "ProgramRegistry", "registry",
    "donation_allowed", "tree_avals", "avals_crc", "stable_crc",
    "MANIFEST_VERSION", "ManifestError", "load_manifest", "save_manifest",
    "warm_engine",
]


def warm_engine(engine, manifest_path=None):
    """The shared serve warmup helper ``bench_serve.py`` / ``regress.py``
    deduplicate onto: AOT-compile every (variant, bucket) rung through
    the registry (``engine.warmup()`` routes there), optionally
    persisting the compiled keys to ``manifest_path`` so the next
    process prewarms from disk.  Returns ``engine.warmup()``'s timing
    dict unchanged (``{bucket: secs}`` or ``{variant: {bucket: secs}}``)."""
    timings = engine.warmup()
    if manifest_path:
        registry.write_manifest(manifest_path)
    return timings
