"""Process-wide compiled-program registry (docs/performance.md,
"Compiled-program registry").

The reference paper's pipeline compiles exactly one program; this repo
compiles dozens — per-bucket serve executables across a dtype ladder,
train/eval steps that re-jit on elastic reform, bench/regress warmup
programs.  Before this module each consumer grew its own cache (the
serve generation's ``(variant, bucket)`` dict, the trainer's re-jit on
``_build_steps``, warmup loops in ``bench.py``/``bench_serve.py``/
``regress.py``).  The registry is the one home for all of them:

- ``ProgramKey`` — ``(model, shapes, mesh, dtype, generation)``, the
  compatibility key.  Two call sites with equal keys may share one
  executable; anything that changes the compiled program (input avals,
  mesh extent, ladder dtype, a replaced forward) must change the key.
- ``get_or_compile(key, build_fn)`` — hit returns the cached entry
  (executable + captured ``cost_analysis``); miss runs ``build_fn``
  under the registry lock (racing compilers for the same key would
  otherwise both compile and the compiles-flat contract would report
  phantom recompiles) and records compile wall time + cost analysis.
- generation-scoped GC — ``retire(model, generation=g)`` drops a
  retired serve generation's entries; a pre-reform trainer step evicts
  its superseded keys the same way.  Entries never outlive the program
  identity that built them.
- the donation-safety policy — :func:`donation_allowed` is the single
  authoritative implementation of the cpu+cache+guard donation-disable
  rule (previously inlined in train/step.py; TPU201/202 in
  tpuic/analysis/rules.py codify the underlying backend bug).
- hit/miss/prewarm accounting — ``counters()`` feeds the
  ``compile_cache_{hits,misses,prewarmed,entries}`` rows both prom
  expositions render, and every compile/retire publishes a
  ``compile_cache`` event so restart downtime is attributable to
  compile vs everything else.

The prewarm manifest (tpuic/compiled/manifest.py) persists the keys a
process compiled so a restarted gang member, a hot-swap candidate, or a
cold replica compiles every known program up front — against the
persistent XLA cache that makes those compiles disk reads — instead of
paying them at first traffic.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional

__all__ = [
    "ProgramKey", "CompiledEntry", "ProgramRegistry", "registry",
    "donation_allowed", "tree_avals", "avals_crc", "stable_crc",
]


def tree_avals(variables) -> tuple:
    """Hashable (path, shape, dtype) signature of a pytree — the
    executable-compatibility signature: two trees with equal signatures
    can run through the same AOT executables (variables are *arguments*
    of the compiled program, not baked into it).  Moved here from
    serve/engine.py — the serve hot-swap reuse test and the trainer's
    aval-identical reform both key on it."""
    import jax
    return tuple(
        (jax.tree_util.keystr(path), tuple(getattr(leaf, "shape", ())),
         str(getattr(leaf, "dtype", type(leaf).__name__)))
        for path, leaf in jax.tree_util.tree_flatten_with_path(variables)[0])


def avals_crc(avals: tuple) -> str:
    """8-hex CRC of an aval signature — compact enough to live inside a
    ProgramKey (the full signature of a ResNet tree is hundreds of
    entries) while still discriminating shape/dtype/structure changes."""
    return f"{zlib.crc32(repr(avals).encode()) & 0xFFFFFFFF:08x}"


def stable_crc(obj) -> str:
    """8-hex CRC of any JSON-able object (sort_keys canonical form) —
    how consumers fold config blobs (optimizer, sharding flags, seeds)
    into a key without exploding its repr."""
    payload = json.dumps(obj, sort_keys=True, default=str)
    return f"{zlib.crc32(payload.encode()) & 0xFFFFFFFF:08x}"


def _tuplify(x):
    """Lists -> tuples, recursively: manifest JSON round-trips keys
    through lists, but ProgramKey fields must stay hashable."""
    if isinstance(x, (list, tuple)):
        return tuple(_tuplify(v) for v in x)
    return x


@dataclasses.dataclass(frozen=True)
class ProgramKey:
    """The registry key: ``(model, shapes, mesh, dtype, generation)``.

    ``model``      — program family tag ("serve:<tag>/fp32",
                     "train:resnet18-cifar:step", ...).  Consumers that
                     want cross-process manifest prewarm must use a tag
                     stable across restarts; anything else should make
                     it unique (a colliding tag with a different program
                     body would alias two incompatible executables).
    ``shapes``     — input geometry + whatever aval/config CRCs pin the
                     program body (nested tuples of primitives).
    ``mesh``       — ((axis, size), ...) of the SPMD mesh, () unsharded.
    ``dtype``      — compute/ladder dtype tag ("fp32", "bf16", ...).
    ``generation`` — program generation; bumps when the program body
                     changes under an unchanged geometry (a hot-swap
                     that replaced the forward fn), so retiring a
                     generation GCs exactly its entries.
    """

    model: str
    shapes: tuple = ()
    mesh: tuple = ()
    dtype: str = ""
    generation: int = 0

    def to_dict(self) -> dict:
        return {"model": self.model, "shapes": list(self.shapes),
                "mesh": list(self.mesh), "dtype": self.dtype,
                "generation": self.generation}

    @classmethod
    def from_dict(cls, d: dict) -> "ProgramKey":
        return cls(model=str(d["model"]), shapes=_tuplify(d.get("shapes", ())),
                   mesh=_tuplify(d.get("mesh", ())),
                   dtype=str(d.get("dtype", "")),
                   generation=int(d.get("generation", 0)))


@dataclasses.dataclass
class CompiledEntry:
    """One cached program: the executable (an AOT ``Compiled`` or a
    jitted callable), its captured cost analysis (best-effort; {} when
    the backend exposes none — lazy-jit entries cost-analyze at first
    lowering, not here), compile wall time, and per-entry accounting."""

    key: ProgramKey
    executable: object
    cost: dict
    compile_s: float
    hit_count: int = 0
    prewarmed: bool = False


def donation_allowed(*, guard_active: bool) -> bool:
    """THE cpu+cache+guard donation-disable rule — the registry is its
    single authoritative home (train/step.py and any future AOT
    consumer call this instead of re-deriving it).

    Buffer donation must be disabled exactly when all three hold:
    (a) the caller's program aliases donated inputs straight to outputs
    (the non-finite guard's skip path — ``guard_active``), (b) a
    persistent XLA compilation cache is configured, and (c) the backend
    is CPU.  Executables DESERIALIZED from the persistent cache
    mishandle input->output aliasing on this container's jax 0.4.37 CPU
    backend — measured as silent buffer corruption (NaN loss on finite
    data after a restore) and nondeterministic SIGSEGV in dispatch; any
    two of the three conditions are fine.  TPU201/TPU202
    (tpuic/analysis/rules.py) lint for the same hazard statically."""
    if not guard_active:
        return True
    import jax
    if not getattr(jax.config, "jax_compilation_cache_dir", None):
        return True
    return jax.default_backend() != "cpu"


def _publish(kind: str, **data) -> None:
    # Best-effort bus publish: the registry must work in processes that
    # never import telemetry (and before the bus exists in interpreter
    # teardown paths).
    try:
        from tpuic.telemetry.events import publish
        publish(kind, **data)
    except Exception:
        pass


class ProgramRegistry:
    """The process-wide executable cache.  Thread-safe: ``get_or_compile``
    holds one registry lock across the build (the same serialization the
    serve engine's compile lock provided — two threads racing the same
    key compile once), while ``peek`` is a lock-free dict read for the
    request path.  Hit counters are GIL-approximate under true
    multithreading (a lost increment, never a lost entry); every test
    that asserts exact counts is single-threaded."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._entries: Dict[ProgramKey, CompiledEntry] = {}
        self.hits = 0
        self.misses = 0
        self.prewarmed = 0
        self.compile_s = 0.0

    # -- core ----------------------------------------------------------
    def get_or_compile(self, key: ProgramKey,
                       build_fn: Callable[[], object], *,
                       prewarm: bool = False) -> CompiledEntry:
        """Return the cached entry for ``key``, compiling via
        ``build_fn`` on miss.  The freshly-built entry has
        ``hit_count == 0`` on exactly the call that built it — callers
        that keep their own compile stats (ServeStats) branch on that.
        ``prewarm=True`` marks a miss as manifest/startup prewarm work
        in the counters (it is still a real compile)."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                entry.hit_count += 1
                self.hits += 1
                return entry
            t0 = time.perf_counter()
            exe = build_fn()
            compile_s = time.perf_counter() - t0
            cost: dict = {}
            try:
                from tpuic.telemetry.goodput import cost_analysis_dict
                cost = dict(cost_analysis_dict(exe))
            except Exception:
                cost = {}
            entry = CompiledEntry(key=key, executable=exe, cost=cost,
                                  compile_s=compile_s, prewarmed=prewarm)
            self._entries[key] = entry
            self.misses += 1
            self.compile_s += compile_s
            if prewarm:
                self.prewarmed += 1
            _publish("compile_cache",
                     action="prewarm" if prewarm else "compile",
                     model=key.model, dtype=key.dtype,
                     generation=key.generation,
                     compile_ms=round(1000.0 * compile_s, 3),
                     entries=len(self._entries))
            return entry

    def peek(self, key: ProgramKey):
        """Lock-free executable lookup for the request path: the cached
        executable, or None.  Counts a hit on success (approximate under
        contention — see class docstring)."""
        entry = self._entries.get(key)
        if entry is None:
            return None
        entry.hit_count += 1
        self.hits += 1
        return entry.executable

    def lookup(self, key: ProgramKey) -> Optional[CompiledEntry]:
        """Non-counting introspection: the entry, or None."""
        return self._entries.get(key)

    def mark_prewarmed(self, key: ProgramKey) -> bool:
        """Flag an existing entry as prewarmed (a startup path executed
        it before first traffic) — counted once per entry."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None or entry.prewarmed:
                return False
            entry.prewarmed = True
            self.prewarmed += 1
            return True

    # -- generation-scoped GC ------------------------------------------
    def retire(self, model_prefix: str, *,
               generation: Optional[int] = None) -> int:
        """Drop every entry whose ``key.model`` starts with
        ``model_prefix`` (and, when given, whose ``key.generation``
        matches) — how a superseded serve generation or a pre-reform
        trainer step releases its executables.  Returns the count."""
        with self._lock:
            doomed = [k for k in self._entries
                      if k.model.startswith(model_prefix)
                      and (generation is None or k.generation == generation)]
            for k in doomed:
                del self._entries[k]
        if doomed:
            _publish("compile_cache", action="retire", model=model_prefix,
                     generation=generation, retired=len(doomed),
                     entries=len(self._entries))
        return len(doomed)

    def evict(self, key: ProgramKey) -> bool:
        """Drop one exact key (trainer reform GC of the superseded step)."""
        with self._lock:
            entry = self._entries.pop(key, None)
        if entry is not None:
            _publish("compile_cache", action="retire", model=key.model,
                     generation=key.generation, retired=1,
                     entries=len(self._entries))
        return entry is not None

    # -- introspection --------------------------------------------------
    def __len__(self) -> int:
        return len(self._entries)

    def keys(self) -> List[ProgramKey]:
        return list(self._entries)

    def counters(self) -> dict:
        return {"hits": self.hits, "misses": self.misses,
                "prewarmed": self.prewarmed, "entries": len(self._entries),
                "compile_s": round(self.compile_s, 4)}

    def manifest_entries(self, model_prefix: str = "") -> List[dict]:
        """JSON-able records of every (matching) compiled key — what the
        prewarm manifest persists."""
        with self._lock:
            return [{"key": e.key.to_dict(),
                     "compile_s": round(e.compile_s, 4)}
                    for e in self._entries.values()
                    if e.key.model.startswith(model_prefix)]

    def write_manifest(self, path: str, model_prefix: str = "") -> int:
        """Persist the compiled-key manifest atomically (tmp+rename with
        a payload CRC — tpuic/compiled/manifest.py).  Returns the entry
        count written."""
        from tpuic.compiled.manifest import save_manifest
        entries = self.manifest_entries(model_prefix)
        save_manifest(path, entries)
        return len(entries)

    def reset(self) -> None:
        """Tests only: drop every entry and zero the counters."""
        with self._lock:
            self._entries.clear()
            self.hits = self.misses = self.prewarmed = 0
            self.compile_s = 0.0


#: The process-wide registry every consumer shares (serve engine,
#: trainer, bench/regress warmup, the prom expositions' counter rows).
registry = ProgramRegistry()
