"""On-disk prewarm manifest (docs/performance.md, "Compiled-program
registry").

A tiny JSON sidecar recording the :class:`~tpuic.compiled.ProgramKey`\\ s
a process compiled, so the NEXT process — a restarted gang member, a
hot-swap candidate, a cold replica — compiles every known program up
front (against the persistent XLA compilation cache, where those
compiles are disk reads) instead of paying them at first traffic.

Write discipline matches the checkpoint manager's sidecars
(tpuic/checkpoint/manager.py): the payload is written to a tmp file and
``os.replace``\\ d into place so readers never see a half-written
manifest, and it carries a CRC32 of its canonical entries JSON.  A
reader that finds a CRC mismatch, an unknown version, or unparseable
JSON REFUSES the manifest (:class:`ManifestError`) — prewarming from a
torn file would compile garbage keys and report them as coverage.
"""

from __future__ import annotations

import json
import os
import tempfile
import zlib
from typing import List

__all__ = ["MANIFEST_VERSION", "ManifestError", "save_manifest",
           "load_manifest"]

MANIFEST_VERSION = 1


class ManifestError(ValueError):
    """A prewarm manifest that must not be trusted (torn write, CRC
    mismatch, unknown schema).  Callers skip prewarm loudly; they never
    prewarm from a manifest that failed this check."""


def _entries_crc(entries: List[dict]) -> str:
    payload = json.dumps(entries, sort_keys=True, separators=(",", ":"))
    return f"{zlib.crc32(payload.encode()) & 0xFFFFFFFF:08x}"


def save_manifest(path: str, entries: List[dict]) -> None:
    """Atomically write ``entries`` (``[{"key": ProgramKey.to_dict(),
    "compile_s": float}, ...]``) with a payload CRC."""
    doc = {"version": MANIFEST_VERSION, "crc": _entries_crc(entries),
           "entries": entries}
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".manifest-", suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=2)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def load_manifest(path: str) -> List[dict]:
    """Read + verify a prewarm manifest.  Returns the entries list.
    Raises :class:`ManifestError` on any integrity failure and
    ``FileNotFoundError`` when the file simply does not exist (a first
    boot — not an error)."""
    with open(path) as f:
        try:
            doc = json.load(f)
        except ValueError as e:
            raise ManifestError(f"prewarm manifest {path} is not valid "
                                f"JSON ({e}) — refusing to prewarm") from e
    if not isinstance(doc, dict) or doc.get("version") != MANIFEST_VERSION:
        raise ManifestError(
            f"prewarm manifest {path} has unknown version "
            f"{doc.get('version') if isinstance(doc, dict) else type(doc)} "
            f"(expected {MANIFEST_VERSION}) — refusing to prewarm")
    entries = doc.get("entries")
    if not isinstance(entries, list):
        raise ManifestError(f"prewarm manifest {path} carries no entries "
                            "list — refusing to prewarm")
    crc = _entries_crc(entries)
    if crc != doc.get("crc"):
        raise ManifestError(
            f"prewarm manifest {path} failed its CRC check "
            f"(recorded {doc.get('crc')!r}, computed {crc!r}) — torn or "
            "tampered; refusing to prewarm")
    return entries
