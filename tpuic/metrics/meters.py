"""Metrics primitives.

TPU-native re-design of the reference's ``utils.py``:

- ``AverageMeter`` keeps the exact running val/sum/count/avg semantics of
  reference utils.py:5-20 but on host floats (the reference feeds it 0-dim CUDA
  tensors, train.py:64, which silently keeps device sync in the logging path —
  here device values are fetched once per logging event, never per update).
- ``accuracy`` is the jit-friendly equivalent of reference utils.py:25-27
  (``argmax(dim=1) == label``), returning per-sample 0/1 so callers can reduce
  with ``psum`` instead of the reference's pickle-based ragged all_gather
  (ddp_utils.py:16-56).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class AverageMeter:
    """Running average with the reference's update semantics (utils.py:16-20)."""

    def __init__(self) -> None:
        self.reset()

    def reset(self) -> None:
        self.val = 0.0
        self.sum = 0.0
        self.count = 0
        self.avg = 0.0

    def update(self, val: float, n: int = 1) -> None:
        val = float(val)
        self.val = val
        self.sum += val * n
        self.count += n
        self.avg = self.sum / max(self.count, 1)


class LatencyMeter:
    """Latency percentile tracker over a bounded sliding window.

    ``update`` records one sample (seconds); ``percentiles`` reads
    p50/p95/p99 (milliseconds) over the last ``window`` samples, so a
    long-running server reports recent behavior rather than its whole
    lifetime.  count/total cover every sample ever recorded (for
    throughput math).  Not thread-safe by itself — callers that update
    from several threads hold their own lock (tpuic.serve.metrics does).
    """

    def __init__(self, window: int = 8192) -> None:
        from collections import deque
        self._win = deque(maxlen=max(1, int(window)))
        self.count = 0
        self.total = 0.0

    def reset(self) -> None:
        self._win.clear()
        self.count = 0
        self.total = 0.0

    def update(self, seconds: float) -> None:
        s = float(seconds)
        self._win.append(s)
        self.count += 1
        self.total += s

    def percentiles_ms(self, qs=(50, 95, 99)) -> dict:
        """{'p50': ms, ...} over the window; {} when no samples yet."""
        if not self._win:
            return {}
        import numpy as np
        arr = np.asarray(self._win, np.float64)
        vals = np.percentile(arr, qs)
        return {f"p{q}": round(1000.0 * float(v), 3)
                for q, v in zip(qs, vals)}

    @property
    def mean_ms(self) -> float:
        return 1000.0 * self.total / self.count if self.count else 0.0

    @property
    def std_ms(self) -> float:
        """Population std (ms) over the window — the spread companion to
        ``percentiles_ms`` (bench.py's per-step variance detail)."""
        if not self._win:
            return 0.0
        import numpy as np
        return round(1000.0 * float(np.std(np.asarray(self._win,
                                                      np.float64))), 3)


def accuracy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Per-sample 0/1 correctness; reference utils.py:25-27.

    logits: [B, C] float; labels: [B] int. Returns [B] float32 of 0.0/1.0.
    """
    return (jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32)


def topk_accuracy(logits: jnp.ndarray, labels: jnp.ndarray,
                  k: int = 5) -> jnp.ndarray:
    """Per-sample 0/1 top-k membership (the ImageNet convention the
    reference never reports; k is clamped to the class count).

    logits: [B, C] float; labels: [B] int. Returns [B] float32 of 0.0/1.0.
    """
    k = min(k, logits.shape[-1])
    _, idx = jax.lax.top_k(logits, k)  # [B, k]
    return jnp.any(idx == labels[:, None], axis=-1).astype(jnp.float32)
