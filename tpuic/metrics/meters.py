"""Metrics primitives.

TPU-native re-design of the reference's ``utils.py``:

- ``AverageMeter`` keeps the exact running val/sum/count/avg semantics of
  reference utils.py:5-20 but on host floats (the reference feeds it 0-dim CUDA
  tensors, train.py:64, which silently keeps device sync in the logging path —
  here device values are fetched once per logging event, never per update).
- ``accuracy`` is the jit-friendly equivalent of reference utils.py:25-27
  (``argmax(dim=1) == label``), returning per-sample 0/1 so callers can reduce
  with ``psum`` instead of the reference's pickle-based ragged all_gather
  (ddp_utils.py:16-56).
"""

from __future__ import annotations

import math
from typing import TYPE_CHECKING, Dict, Iterable, Sequence

if TYPE_CHECKING:  # pragma: no cover — annotations only
    import jax.numpy as jnp

# NOTE: no module-level jax import.  The stdlib-only serve tiers (the
# replica router and the canary rollout driver) import this module for
# the pinned quantile helpers — via tpuic/telemetry/slo.py — and must
# never pull the jax stack into a parent process that has to outlive a
# backend wedge.  accuracy()/topk_accuracy() import jax inside the
# function, where only jax-running callers (the train step) ever are.


# -- the one quantile implementation -----------------------------------------
# Pinned method: **nearest-rank** (R-1 / inverse-CDF) — the reported value
# is always an actually-observed sample, never an interpolation between
# two samples.  For latency SLOs that property matters: "p99 = 38 ms"
# means a real request took 38 ms, and a single outlier moves the tail
# quantiles by whole samples, not by interpolation fractions.  Every
# percentile this repo reports (serve stats, the telemetry StepTimer,
# request span ledgers, SLO attainment, the perf-regression gate) goes
# through these two helpers — there is deliberately no second copy.

def _rank(n: int, q: float) -> int:
    """THE nearest-rank formula: ceil(q/100 * n), clamped to [1, n].
    Both public readers index with this one expression — a change to
    the pinned method lands everywhere or nowhere."""
    return max(1, min(n, math.ceil(q / 100.0 * n)))


def quantile(samples: Sequence[float], q: float) -> float:
    """Nearest-rank quantile of ``samples`` (``q`` in percent, 0-100).

    Returns the rank-th smallest sample.  Raises on an empty sequence
    (callers that want "no data yet" semantics check first — a
    fabricated 0 would read as a perfect latency)."""
    s = sorted(samples)
    if not s:
        raise ValueError("quantile of empty sample set")
    return s[_rank(len(s), q) - 1]


def quantile_label(q: float) -> str:
    """Canonical metric key for a quantile: 50 -> 'p50', 99.9 -> 'p999'."""
    return "p" + format(float(q), "g").replace(".", "")


def quantiles(samples: Sequence[float],
              qs: Iterable[float]) -> Dict[str, float]:
    """{label: nearest-rank quantile} over one shared sort ({} if empty)."""
    s = sorted(samples)
    if not s:
        return {}
    return {quantile_label(q): s[_rank(len(s), q) - 1] for q in qs}


def process_rss_bytes():
    """Resident set size of THIS process, in bytes (None when unknowable).

    THE shared host-memory read: the overload soak's leak bound, the
    device-memory sampler's host companion row, and the
    ``process_rss_bytes`` gauge on both Prometheus expositions all call
    this one helper — there is deliberately no second ``/proc`` parser.
    Primary source is ``/proc/self/status`` (current RSS); the fallback
    is ``resource.getrusage`` whose ``ru_maxrss`` is the *peak* RSS
    (documented platform semantics — still the right alarm signal when
    ``/proc`` is unavailable)."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return float(line.split()[1]) * 1024.0
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource
        import sys
        peak = float(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss)
        # ru_maxrss units are platform-defined: macOS (the realistic
        # no-/proc platform for this fallback) reports bytes, linux KiB.
        return peak if sys.platform == "darwin" else peak * 1024.0
    except Exception:
        return None


class AverageMeter:
    """Running average with the reference's update semantics (utils.py:16-20)."""

    def __init__(self) -> None:
        self.reset()

    def reset(self) -> None:
        self.val = 0.0
        self.sum = 0.0
        self.count = 0
        self.avg = 0.0

    def update(self, val: float, n: int = 1) -> None:
        val = float(val)
        self.val = val
        self.sum += val * n
        self.count += n
        self.avg = self.sum / max(self.count, 1)


class LatencyMeter:
    """Latency percentile tracker over a bounded sliding window.

    ``update`` records one sample (seconds); ``percentiles_ms`` reads
    p50/p95/p99/p999 (milliseconds) over the last ``window`` samples, so
    a long-running server reports recent behavior rather than its whole
    lifetime.  count/total cover every sample ever recorded (for
    throughput math).  Not thread-safe by itself — callers that update
    from several threads hold their own lock (tpuic.serve.metrics does).

    Percentile method is the module-level nearest-rank :func:`quantile`
    (pinned and documented there): reported values are real observed
    samples, shared with the serve span ledger, SLO accounting, and the
    perf-regression gate — one implementation, one semantics.
    """

    def __init__(self, window: int = 8192) -> None:
        from collections import deque
        self._win = deque(maxlen=max(1, int(window)))
        self.count = 0
        self.total = 0.0

    def reset(self) -> None:
        self._win.clear()
        self.count = 0
        self.total = 0.0

    def update(self, seconds: float) -> None:
        s = float(seconds)
        self._win.append(s)
        self.count += 1
        self.total += s

    def quantile_s(self, q: float):
        """One nearest-rank quantile in SECONDS over the window (None
        when no samples yet — callers needing an estimate must not read
        a fabricated 0 as "instant")."""
        if not self._win:
            return None
        return quantile(self._win, q)

    def percentiles_ms(self, qs=(50, 95, 99, 99.9)) -> dict:
        """{'p50': ms, ..., 'p999': ms} over the window (nearest-rank,
        see :func:`quantile`); {} when no samples yet."""
        return {k: round(1000.0 * v, 3)
                for k, v in quantiles(self._win, qs).items()}

    @property
    def mean_ms(self) -> float:
        return 1000.0 * self.total / self.count if self.count else 0.0

    @property
    def std_ms(self) -> float:
        """Population std (ms) over the window — the spread companion to
        ``percentiles_ms`` (bench.py's per-step variance detail)."""
        if not self._win:
            return 0.0
        import numpy as np
        return round(1000.0 * float(np.std(np.asarray(self._win,
                                                      np.float64))), 3)


def accuracy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Per-sample 0/1 correctness; reference utils.py:25-27.

    logits: [B, C] float; labels: [B] int. Returns [B] float32 of 0.0/1.0.
    """
    import jax.numpy as jnp
    return (jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32)


def topk_accuracy(logits: jnp.ndarray, labels: jnp.ndarray,
                  k: int = 5) -> jnp.ndarray:
    """Per-sample 0/1 top-k membership (the ImageNet convention the
    reference never reports; k is clamped to the class count).

    logits: [B, C] float; labels: [B] int. Returns [B] float32 of 0.0/1.0.
    """
    import jax
    import jax.numpy as jnp
    k = min(k, logits.shape[-1])
    _, idx = jax.lax.top_k(logits, k)  # [B, k]
    return jnp.any(idx == labels[:, None], axis=-1).astype(jnp.float32)
