"""Host-0 logging + scalar metric writer.

The reference's observability is a rank-0-gated tqdm bar and ``print``
(train.py:39-42, 67-68, 94-95). Here: the same console UX plus a structured
JSONL scalar log (the reference has none — SURVEY.md §5 'Metrics/logging').
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

import jax


def is_host0() -> bool:
    return jax.process_index() == 0


def host0_print(*args, **kwargs) -> None:
    """print() on process 0 only — reference's ``if args.local_rank == 0`` gate."""
    if is_host0():
        print(*args, **kwargs, flush=True)


class MetricLogger:
    """Scalar writer, active on host 0 only: JSONL (machine-greppable) +
    TensorBoard events (metrics/tensorboard.py — no TF dependency), both
    under ``log_dir``."""

    def __init__(self, log_dir: Optional[str] = None) -> None:
        self._fh = None
        self._tb = None
        # The active log dir on host 0 (None when logging is off): callers
        # park non-scalar sidecars (confusion matrices, per-class detail)
        # beside metrics.jsonl through this.
        self.root = log_dir if (log_dir and is_host0()) else None
        if self.root is not None:
            os.makedirs(self.root, exist_ok=True)
            self._fh = open(os.path.join(self.root, "metrics.jsonl"), "a")
            from tpuic.metrics.tensorboard import TensorBoardWriter
            self._tb = TensorBoardWriter(self.root)

    @property
    def tb(self):
        """The active TensorBoardWriter (None when logging is off) — the
        telemetry TensorBoardSink bridges bus events through it."""
        return self._tb

    def write(self, step: int, **scalars) -> None:
        if self._fh is None:
            return
        vals = {k: float(v) for k, v in scalars.items()}
        rec = {"step": step, "time": time.time(), **vals}
        self._fh.write(json.dumps(rec) + "\n")
        self._fh.flush()
        if self._tb is not None:
            self._tb.scalars(step, **vals)

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        if self._tb is not None:
            self._tb.close()
            self._tb = None
