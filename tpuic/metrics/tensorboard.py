"""Dependency-free TensorBoard scalar event writer.

The reference has no metric export at all (SURVEY.md §5: tqdm + print);
tpuic writes JSONL (metrics/logging.py) and, with this module, standard
``events.out.tfevents.*`` files that TensorBoard's scalar dashboard reads
directly — next to the ``jax.profiler`` traces that already open there.

No TensorFlow / tensorboardX dependency: the format is hand-encoded.

- **TFRecord framing** (record_writer.cc): ``uint64 length | uint32
  masked_crc32c(length_bytes) | payload | uint32 masked_crc32c(payload)``
  with the masked Castagnoli CRC ``((crc >> 15 | crc << 17) + 0xa282ead8)``.
- **Event proto** (event.proto), fields hand-encoded in wire format:
  ``wall_time``(1, double) ``step``(2, int64) ``file_version``(3, string)
  ``summary``(5, message) — Summary.value(1) {tag(1, string),
  simple_value(2, float)}.

tests/test_tensorboard.py round-trips files through an independent reader
(also in this module) that verifies both CRCs and re-decodes the protos.
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time
from typing import Iterator, List, Optional, Tuple

# -- crc32c (Castagnoli, table-driven) ---------------------------------------

_CRC_TABLE: List[int] = []


def _crc_table() -> List[int]:
    global _CRC_TABLE
    if not _CRC_TABLE:
        poly = 0x82F63B78  # reversed Castagnoli
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            table.append(c)
        _CRC_TABLE = table
    return _CRC_TABLE


def crc32c(data: bytes) -> int:
    table = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# -- minimal protobuf wire encoding ------------------------------------------

def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _key(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _len_delim(field: int, payload: bytes) -> bytes:
    return _key(field, 2) + _varint(len(payload)) + payload


def _double(field: int, v: float) -> bytes:
    return _key(field, 1) + struct.pack("<d", v)


def _float32(field: int, v: float) -> bytes:
    return _key(field, 5) + struct.pack("<f", v)


def _event(step: int, scalars: Tuple[Tuple[str, float], ...] = (),
           file_version: Optional[str] = None,
           wall_time: Optional[float] = None) -> bytes:
    msg = _double(1, time.time() if wall_time is None else wall_time)
    msg += _key(2, 0) + _varint(step & 0xFFFFFFFFFFFFFFFF)
    if file_version is not None:
        msg += _len_delim(3, file_version.encode())
    if scalars:
        summary = b"".join(
            _len_delim(1, _len_delim(1, tag.encode()) + _float32(2, val))
            for tag, val in scalars)
        msg += _len_delim(5, summary)
    return msg


class TensorBoardWriter:
    """``events.out.tfevents.<ts>.<host>`` scalar writer."""

    def __init__(self, log_dir: str) -> None:
        os.makedirs(log_dir, exist_ok=True)
        name = f"events.out.tfevents.{int(time.time())}.{socket.gethostname()}"
        self._fh = open(os.path.join(log_dir, name), "ab")
        # Records interleave from more than one thread since the telemetry
        # bridge landed: the loop thread writes scalars while the
        # TensorBoardSink relays quarantine events from dataset producer
        # threads. A record is four sequential writes (header, CRC,
        # payload, CRC) — unserialized interleaving corrupts the CRC
        # framing and truncates the file for every reader.
        self._lock = threading.Lock()
        self._record(_event(0, file_version="brain.Event:2"))

    def _record(self, payload: bytes) -> None:
        header = struct.pack("<Q", len(payload))
        with self._lock:
            if self._fh is None:
                return
            self._fh.write(header)
            self._fh.write(struct.pack("<I", _masked_crc(header)))
            self._fh.write(payload)
            self._fh.write(struct.pack("<I", _masked_crc(payload)))
            self._fh.flush()

    def scalars(self, step: int, **values: float) -> None:
        if values:
            self._record(_event(step, tuple(
                (k, float(v)) for k, v in sorted(values.items()))))

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


# -- independent reader (tests + debugging) ----------------------------------

def read_events(path: str) -> Iterator[dict]:
    """Decode an events file, VERIFYING both CRCs per record. Yields
    {'step': int, 'wall_time': float, 'scalars': {tag: value}} (the
    file_version record yields scalars={})."""
    with open(path, "rb") as f:
        data = f.read()
    pos = 0
    while pos < len(data):
        if pos + 12 > len(data):
            raise ValueError(f"truncated record header at {pos}")
        (length,) = struct.unpack_from("<Q", data, pos)
        header = data[pos:pos + 8]
        (hcrc,) = struct.unpack_from("<I", data, pos + 8)
        if _masked_crc(header) != hcrc:
            raise ValueError(f"bad header crc at {pos}")
        if pos + 12 + length + 4 > len(data):
            raise ValueError(f"truncated record payload at {pos}")
        payload = data[pos + 12:pos + 12 + length]
        (pcrc,) = struct.unpack_from("<I", data, pos + 12 + length)
        if _masked_crc(payload) != pcrc:
            raise ValueError(f"bad payload crc at {pos}")
        pos += 12 + length + 4
        yield _decode_event(payload)


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    shift = n = 0
    while True:
        b = buf[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, pos
        shift += 7


def _decode_event(buf: bytes) -> dict:
    out = {"step": 0, "wall_time": 0.0, "scalars": {}}
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if field == 1 and wire == 1:
            (out["wall_time"],) = struct.unpack_from("<d", buf, pos)
            pos += 8
        elif field == 2 and wire == 0:
            out["step"], pos = _read_varint(buf, pos)
        elif field == 5 and wire == 2:
            ln, pos = _read_varint(buf, pos)
            out["scalars"].update(_decode_summary(buf[pos:pos + ln]))
            pos += ln
        elif wire == 2:  # skip unknown length-delimited (file_version etc.)
            ln, pos = _read_varint(buf, pos)
            pos += ln
        elif wire == 0:
            _, pos = _read_varint(buf, pos)
        elif wire == 1:
            pos += 8
        elif wire == 5:
            pos += 4
        else:
            raise ValueError(f"unknown wire type {wire}")
    return out


def _decode_summary(buf: bytes) -> dict:
    scalars = {}
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        if key >> 3 == 1 and key & 7 == 2:
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
            tag, v = None, None
            vpos = 0
            while vpos < len(val):
                vkey, vpos = _read_varint(val, vpos)
                if vkey >> 3 == 1 and vkey & 7 == 2:
                    vln, vpos = _read_varint(val, vpos)
                    tag = val[vpos:vpos + vln].decode()
                    vpos += vln
                elif vkey >> 3 == 2 and vkey & 7 == 5:
                    (v,) = struct.unpack_from("<f", val, vpos)
                    vpos += 4
                else:  # skip anything else
                    wire = vkey & 7
                    if wire == 0:
                        _, vpos = _read_varint(val, vpos)
                    elif wire == 2:
                        vln, vpos = _read_varint(val, vpos)
                        vpos += vln
                    elif wire == 1:
                        vpos += 8
                    elif wire == 5:
                        vpos += 4
            if tag is not None and v is not None:
                scalars[tag] = v
        else:
            break
    return scalars
