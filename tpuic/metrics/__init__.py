from tpuic.metrics.meters import (AverageMeter, accuracy,  # noqa: F401
                                  topk_accuracy)
from tpuic.metrics.logging import host0_print, MetricLogger  # noqa: F401
