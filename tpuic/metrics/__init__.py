from tpuic.metrics.meters import AverageMeter, accuracy  # noqa: F401
from tpuic.metrics.logging import host0_print, MetricLogger  # noqa: F401
