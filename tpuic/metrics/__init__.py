"""tpuic.metrics — meters, quantiles, host-0 logging.

Re-exports resolve lazily (PEP 562, the tpuic/__init__.py idiom):
``tpuic.metrics.meters`` is stdlib-importable (its jax-consuming
helpers import jax inside the function), and the stdlib-only serve
tiers — the replica router and the canary rollout driver
(tpuic/serve/rollout.py), which reuses telemetry/slo.py and therefore
the pinned ``meters.quantile`` — must be able to import it without
pulling the jax stack into a parent process that has to outlive a
backend wedge.  ``logging`` (host0_print / MetricLogger) stays
jax-backed and loads only when asked for.
"""

from __future__ import annotations

_LAZY = {
    "AverageMeter": ("tpuic.metrics.meters", "AverageMeter"),
    "LatencyMeter": ("tpuic.metrics.meters", "LatencyMeter"),
    "accuracy": ("tpuic.metrics.meters", "accuracy"),
    "quantile": ("tpuic.metrics.meters", "quantile"),
    "quantile_label": ("tpuic.metrics.meters", "quantile_label"),
    "quantiles": ("tpuic.metrics.meters", "quantiles"),
    "topk_accuracy": ("tpuic.metrics.meters", "topk_accuracy"),
    "host0_print": ("tpuic.metrics.logging", "host0_print"),
    "MetricLogger": ("tpuic.metrics.logging", "MetricLogger"),
}


def __getattr__(name):
    if name in _LAZY:
        import importlib
        module, attr = _LAZY[name]
        value = getattr(importlib.import_module(module), attr)
        globals()[name] = value  # cache: next access skips the import
        return value
    raise AttributeError(f"module 'tpuic.metrics' has no attribute '{name}'")


def __dir__():
    return sorted(set(list(globals()) + list(_LAZY)))
