from tpuic.metrics.meters import (AverageMeter, LatencyMeter,  # noqa: F401
                                  accuracy, quantile, quantile_label,
                                  quantiles, topk_accuracy)
from tpuic.metrics.logging import host0_print, MetricLogger  # noqa: F401
