from tpuic.train.loss import weighted_cross_entropy  # noqa: F401
from tpuic.train.schedule import multistep_schedule  # noqa: F401
from tpuic.train.optimizer import make_optimizer  # noqa: F401
from tpuic.train.state import TrainState, create_train_state  # noqa: F401
from tpuic.train.step import make_train_step, make_eval_step  # noqa: F401
from tpuic.train.loop import Trainer  # noqa: F401
