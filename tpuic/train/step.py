"""Compiled train / eval steps.

This is the TPU-native replacement for the reference's entire hot loop
(train.py:44-73) and validation pass (train.py:78-97). The whole per-batch
body — forward, loss (+0.4·aux for inception), backward, cross-replica
gradient averaging, BN stat sync, optimizer update, and metric reductions — is
ONE jitted XLA program over the device mesh:

- The batch is sharded over the ``data`` mesh axis; reductions over the batch
  dim (loss mean, BN statistics, accuracy counts) are *global* reductions, so
  GSPMD inserts the all-reduces that DDP (train.py:128), SyncBatchNorm
  (train.py:124), the logging all-reduce (train.py:61-63), and the pickle
  all_gather (ddp_utils.py:16-56) performed eagerly in the reference. XLA's
  latency-hiding scheduler overlaps the gradient reductions with the backward
  pass — the compiled analogue of DDP's bucket overlap.
- No separate no_grad logging collective: the loss metric IS the globally
  averaged loss, free.
- Validation returns exact global (weighted-correct, count) sums — the
  static-shape redesign of the reference's ragged per-sample gather; padded
  samples carry mask 0 and thus contribute to neither numerator nor
  denominator, which *fixes* the DistributedSampler padding-duplicate skew
  noted in SURVEY.md §7.
"""

from __future__ import annotations

import warnings
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpuic.config import ModelConfig, OptimConfig, resolve_compute_dtype
from tpuic.metrics.meters import accuracy, topk_accuracy
from tpuic.train.loss import classification_loss
from tpuic.train.state import TrainState


def _batch_shardings(mesh: Mesh):
    """Batch dict: every leaf sharded on dim 0 over the data axis."""
    return NamedSharding(mesh, P("data"))


def _moe_router_stats(intermediates) -> list:
    """All (probs, onehot) router tuples sown anywhere in the model
    (models/moe.py 'moe_router'); flax sow wraps each in an append-tuple,
    so pairs arrive as consecutive leaves under the same path."""
    by_path = {}
    for path, leaf in jax.tree_util.tree_leaves_with_path(intermediates):
        if any(getattr(k, "key", None) == "moe_router" for k in path):
            key = tuple(str(k) for k in path[:-1])
            by_path.setdefault(key, []).append(leaf)
    for key, v in by_path.items():
        # Fail fast on sow-structure drift: silently dropping groups here
        # would silently drop the load-balancing loss from training.
        if len(v) != 2:
            raise ValueError(f"'moe_router' sow at {key} has {len(v)} "
                             "leaves; expected (probs, onehot)")
    return [tuple(v) for v in by_path.values()]


def _replicated(mesh: Mesh):
    return NamedSharding(mesh, P())


def resolve_remat_policy(model_cfg: ModelConfig):
    """Step-level jax.checkpoint policy for the config, or None.

    'dots': wrap the whole forward, keeping only matmul/conv outputs
    without batch dims (i.e. nothing activation-sized); the backward
    recomputes activations instead of round-tripping them through HBM.

    'attention' returns None on purpose: the selective form lives in the
    MODEL (ViT ``remat_core`` — create_model_from_config sets it from the
    config), wrapping just the logits->softmax->probs@v core so only
    q/k/v survive as residuals. It is not expressible as a step-level
    names policy: softmax's backward wants its own internal output, so a
    save-anything-except-names policy still saves quadratic copies of it
    (verified with jax.ad_checkpoint.print_saved_residuals).
    """
    if not model_cfg.remat:
        return None
    if model_cfg.remat_policy == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    if model_cfg.remat_policy == "attention":
        # remat_core only exists in ViT's dense path; anywhere else this
        # combination applies NO remat at all — loud beats a silent OOM at
        # a batch size --remat (dots) would have fit.
        if "vit" not in model_cfg.name or model_cfg.attention != "dense":
            warnings.warn(
                f"remat_policy='attention' has no effect for model="
                f"'{model_cfg.name}' with attention="
                f"'{model_cfg.attention}': only the dense ViT attention "
                "core is rematerializable; NO remat is applied. Use "
                "remat_policy='dots' for whole-forward remat.",
                stacklevel=2)
        return None
    if model_cfg.remat_policy == "gelu":
        # Model-level, like 'attention': ViT ``remat_mlp`` runs each
        # block's Dense(mlp_up)+GELU under nn.remat (models/vit.py
        # MlpUpGelu), so the [B,N,4D] pre-activation is never a residual —
        # the mlp_up fusion writes ONE output instead of two (the
        # dual-output writes PERF_ANALYSIS §10f fingered) and the backward
        # recomputes W1·x per block. NOT expressible as a step-level names
        # policy: save-anything-except a checkpoint_name'd pre-activation
        # still saves its dtype-cast copies and the erf-vjp internals at
        # the same [B,N,4D] size (verified with print_saved_residuals).
        # In MoE ViTs the dense-MLP blocks still benefit; the routed
        # SwitchMoEMlp blocks are untouched.
        if "vit" not in model_cfg.name:
            warnings.warn(
                f"remat_policy='gelu' has no effect for model="
                f"'{model_cfg.name}': only the ViT encoder has the "
                "rematerializable mlp_up+GELU region; NO remat is "
                "applied. Use remat_policy='dots' for whole-forward "
                "remat.",
                stacklevel=2)
        return None
    if model_cfg.remat_policy == "blocks":
        # Per-encoder-block nn.remat lives in the model (ViT
        # ``remat_blocks``): residuals are the block inputs only, the
        # backward recomputes one block at a time. The long-context
        # memory mode — see ModelConfig.remat_policy.
        if "vit" not in model_cfg.name:
            warnings.warn(
                f"remat_policy='blocks' has no effect for model="
                f"'{model_cfg.name}': only the ViT encoder has "
                "per-block remat; NO remat is applied. Use "
                "remat_policy='dots' for whole-forward remat.",
                stacklevel=2)
        return None
    raise ValueError(f"unknown remat_policy '{model_cfg.remat_policy}'; "
                     f"available: ['dots', 'attention', 'blocks', 'gelu']")


def make_train_step(optim_cfg: OptimConfig, model_cfg: ModelConfig,
                    mesh: Optional[Mesh] = None,
                    lr_schedule: Optional[optax.Schedule] = None,
                    donate: bool = True, seed: int = 0,
                    state_sharding=None) -> Callable:
    """Returns jitted ``train_step(state, batch) -> (state, metrics)``.

    batch: {'image': [B,H,W,3] f32, 'label': [B] i32, 'mask': [B] f32}.
    B is the *global* batch size; under a mesh the caller provides globally
    sharded arrays (tpuic.data.pipeline handles this).

    state_sharding: optional NamedSharding prefix tree for the TrainState
    (tpuic.parallel.sharding.state_shardings) — TP/FSDP param+opt sharding.
    None => fully replicated state (reference DDP semantics).
    """
    class_weights = (jnp.asarray(optim_cfg.class_weights, jnp.float32)
                     if optim_cfg.class_weights else None)
    aux_w = model_cfg.aux_loss_weight
    smoothing = optim_cfg.label_smoothing
    remat_policy = resolve_remat_policy(model_cfg)
    # Mixed-precision policy (ModelConfig.compute_dtype): under 'bf16' the
    # batch is cast once at the step entry and the loss is computed on f32
    # logits; the Trainer has already forced the model's compute dtype.
    # The differentiated params stay f32 (param_dtype) — the in-module
    # casts' VJPs accumulate f32 grads — so master weights, moments, and
    # checkpoints never leave f32.
    compute_dtype = resolve_compute_dtype(model_cfg)
    cast_dtype = jnp.bfloat16 if compute_dtype == "bf16" else None
    loss_scale = float(optim_cfg.loss_scale or 1.0)
    # The cpu+cache+guard donation-disable rule lives in ONE place now:
    # tpuic.compiled.donation_allowed (docs/performance.md, "Compiled-
    # program registry").  The guard's skip path aliases donated inputs
    # straight to outputs (state passes through unchanged); executables
    # DESERIALIZED from the persistent compilation cache mishandle that
    # aliasing on this container's jax 0.4.37 CPU backend — silent
    # buffer corruption (NaN loss on finite data after a restore) and
    # nondeterministic SIGSEGV/SIGABRT in dispatch.  Cache+donate+guard
    # is the exact trigger; any two of the three are fine, so TPU runs
    # (and any run without a persistent cache — train.py configures
    # none) keep donation.
    from tpuic.compiled import donation_allowed
    if donate and not donation_allowed(
            guard_active=bool(optim_cfg.skip_nonfinite)):
        warnings.warn(
            "skip_nonfinite guard + persistent compilation cache: "
            "disabling train-state donation to avoid a known "
            "aliasing bug in cache-deserialized executables "
            "(independent of ModelConfig.compute_dtype / "
            "--compute-dtype: the bf16 tier's cast sites produce fresh "
            "arrays, never aliases of the donated state — set "
            "skip_nonfinite=False or drop the cache dir to keep "
            "donation)",
            stacklevel=2)
        donate = False

    def train_step(state: TrainState, batch):
        images, labels = batch["image"], batch["label"]
        mask = batch.get("mask")

        # Per-step dropout/drop-path randomness, deterministic in (seed, step).
        dropout_rng = jax.random.fold_in(jax.random.key(seed), state.step)

        # Mixup (Zhang et al., 2018) / CutMix (Yun et al., 2019), fully
        # on-device inside the jitted step: one lambda (and one box) per
        # step, pairs drawn by a global batch permutation (on a sharded
        # batch the gather is a GSPMD collective over ICI — one
        # batch-sized exchange per step). The loss becomes
        # lam*CE(y) + (1-lam)*CE(y_perm); accuracy is reported against
        # the ORIGINAL labels (standard practice). The Trainer's train
        # loader guarantees full batches (drop_last + the zero-steps
        # guard); for any other caller, rows whose pair involves a padded
        # sample fall back to SELF as the partner — self-mixing is the
        # exact identity, so partial batches degrade to plain CE per row
        # instead of training on padding garbage. With BOTH enabled, one
        # is chosen per step (50/50, the torchvision recipe) via
        # lax.cond, so only the chosen branch executes.
        labels_mix = None
        lam = None
        if optim_cfg.mixup_alpha > 0 or optim_cfg.cutmix_alpha > 0:
            mix_rng = jax.random.fold_in(dropout_rng, 0x6D69)
            perm = jax.random.permutation(jax.random.fold_in(mix_rng, 1),
                                          images.shape[0])
            partners = images[perm]
            labels_mix = labels[perm]
            if mask is not None:
                pair_ok = (mask * mask[perm]) > 0
                partners = jnp.where(pair_ok[:, None, None, None],
                                     partners, images)
                labels_mix = jnp.where(pair_ok, labels_mix, labels)

            def _mixup(imgs, partners):
                lam = jax.random.beta(mix_rng, optim_cfg.mixup_alpha,
                                      optim_cfg.mixup_alpha)
                out = (lam * imgs.astype(jnp.float32)
                       + (1.0 - lam) * partners.astype(jnp.float32))
                return out.astype(imgs.dtype), lam

            def _cutmix(imgs, partners):
                # Static-shape box: bounds are traced scalars compared
                # against iotas; the adjusted lambda is the EXACT kept
                # area (clipping at the borders changes it).
                h, w = imgs.shape[1], imgs.shape[2]
                lam0 = jax.random.beta(mix_rng, optim_cfg.cutmix_alpha,
                                       optim_cfg.cutmix_alpha)
                cut = jnp.sqrt(1.0 - lam0)
                cy, cx = jax.random.uniform(
                    jax.random.fold_in(mix_rng, 2), (2,))
                bh, bw = cut * h, cut * w
                y0 = jnp.clip(cy * h - bh / 2, 0, h)
                y1 = jnp.clip(cy * h + bh / 2, 0, h)
                x0 = jnp.clip(cx * w - bw / 2, 0, w)
                x1 = jnp.clip(cx * w + bw / 2, 0, w)
                ys = jnp.arange(h, dtype=jnp.float32)
                xs = jnp.arange(w, dtype=jnp.float32)
                box = ((ys[:, None] >= y0) & (ys[:, None] < y1)
                       & (xs[None, :] >= x0) & (xs[None, :] < x1))
                out = jnp.where(box[None, :, :, None], partners, imgs)
                lam = 1.0 - jnp.mean(box.astype(jnp.float32))
                return out, lam

            # Scope tag for the device-time waterfall (telemetry/
            # profile.py): mix ops roll up under 'augment', apart from
            # the model's own layers.
            with jax.named_scope("augment"):
                if optim_cfg.mixup_alpha > 0 and optim_cfg.cutmix_alpha > 0:
                    use_mix = jax.random.bernoulli(
                        jax.random.fold_in(mix_rng, 3))
                    # tpuic-ok: TPU202 cond operands are fresh mix
                    # tensors, never the donated pass-through state; the
                    # skip guard stays a jnp.where select (the PR-2
                    # bisect's actual fix)
                    images, lam = jax.lax.cond(  # tpuic-ok: TPU202
                        use_mix, _mixup, _cutmix, images, partners)
                elif optim_cfg.mixup_alpha > 0:
                    images, lam = _mixup(images, partners)
                else:
                    images, lam = _cutmix(images, partners)

        # Random erasing (Zhong et al., 2020), per SAMPLE: with prob p a
        # random box (area 2-33%, aspect 0.3-3.3) is zeroed — zero IS the
        # per-channel mean after the pipeline's normalization. Labels are
        # untouched, so it composes freely with mixup/cutmix above.
        if optim_cfg.random_erase > 0:
            with jax.named_scope("augment"):
                er_rng = jax.random.fold_in(dropout_rng, 0x6572)
                b, h, w = (images.shape[0], images.shape[1],
                           images.shape[2])
                ks = jax.random.split(er_rng, 5)
                area = jax.random.uniform(ks[0], (b,), minval=0.02,
                                          maxval=0.33)
                log_ar = jax.random.uniform(ks[1], (b,),
                                            minval=jnp.log(0.3),
                                            maxval=jnp.log(3.3))
                ar = jnp.exp(log_ar)
                bh = jnp.clip(jnp.sqrt(area * h * w * ar), 1, h)   # [B]
                bw = jnp.clip(jnp.sqrt(area * h * w / ar), 1, w)
                cy = jax.random.uniform(ks[2], (b,)) * h
                cx = jax.random.uniform(ks[3], (b,)) * w
                y0, y1 = (jnp.clip(cy - bh / 2, 0, h),
                          jnp.clip(cy + bh / 2, 0, h))
                x0, x1 = (jnp.clip(cx - bw / 2, 0, w),
                          jnp.clip(cx + bw / 2, 0, w))
                apply = jax.random.bernoulli(ks[4],
                                             optim_cfg.random_erase, (b,))
                ys = jnp.arange(h, dtype=jnp.float32)
                xs = jnp.arange(w, dtype=jnp.float32)
                box = ((ys[None, :, None] >= y0[:, None, None])
                       & (ys[None, :, None] < y1[:, None, None])
                       & (xs[None, None, :] >= x0[:, None, None])
                       & (xs[None, None, :] < x1[:, None, None])
                       & apply[:, None, None])                 # [B,H,W]
                images = jnp.where(box[..., None],
                                   jnp.zeros_like(images), images)

        if cast_dtype is not None:
            # bf16 compute tier: activations enter the network in bf16.
            # One cast of the batch — downstream params are cast inside
            # the flax modules (dtype=bfloat16) and its VJP accumulates
            # the gradient back in f32. After the augment block on
            # purpose: mixup/cutmix blend in f32 and random-erase masks
            # in the input dtype, identical to the f32 arm.
            with jax.named_scope("cast_bf16"):
                images = images.astype(cast_dtype)

        def forward(params, batch_stats, images, rng):
            variables = {"params": params, "batch_stats": batch_stats}
            # 'intermediates' carries sown MoE load-balancing losses
            # (models/moe.py); empty for dense models.
            return state.apply_fn(variables, images, train=True,
                                  mutable=["batch_stats", "intermediates"],
                                  rngs={"dropout": rng})

        if remat_policy is not None:
            forward = jax.checkpoint(forward, policy=remat_policy)

        def loss_fn(params):
            if optim_cfg.freeze_backbone and "backbone" in params:
                # stop_gradient lets XLA prune the whole backbone backward
                # pass (the optimizer-side set_to_zero alone would still
                # compute it, since the grad_norm metric keeps raw grads
                # live); grad_norm then reflects the head-only update.
                params = {**params,
                          "backbone": jax.lax.stop_gradient(
                              params["backbone"])}
            out, mutated = forward(params, state.batch_stats, images,
                                   dropout_rng)
            if cast_dtype is not None:
                # f32-loss guarantee of the bf16 tier: log-softmax over
                # bf16 logits costs ~3 decimal digits right where the
                # parity gate measures.
                out = jax.tree.map(lambda t: t.astype(jnp.float32), out)
            # 'loss' scope: CE (+aux) ops separate from the backbone's
            # layers in the device-time waterfall (telemetry/profile.py).
            with jax.named_scope("loss"):
                loss = classification_loss(
                    out, labels, class_weights=class_weights, mask=mask,
                    aux_weight=aux_w, label_smoothing=smoothing,
                    impl="fused" if optim_cfg.fused_loss
                    else "reference", mesh=mesh)
                if labels_mix is not None:
                    loss_b = classification_loss(
                        out, labels_mix, class_weights=class_weights,
                        mask=mask, aux_weight=aux_w,
                        label_smoothing=smoothing,
                        impl="fused" if optim_cfg.fused_loss
                        else "reference", mesh=mesh)
                    loss = lam * loss + (1.0 - lam) * loss_b
                routers = _moe_router_stats(mutated.get("intermediates",
                                                        {}))
                if routers and model_cfg.moe_aux_weight:
                    from tpuic.models.moe import switch_aux_loss
                    aux = sum(switch_aux_loss(p, o, mask)
                              for p, o in routers)
                    loss = loss + (model_cfg.moe_aux_weight * aux
                                   / len(routers))
            logits = out[0] if isinstance(out, tuple) else out
            return loss, (mutated.get("batch_stats", state.batch_stats), logits)

        if loss_scale != 1.0:
            # Static loss scaling (OptimConfig.loss_scale): backward runs
            # on the scaled loss, then both are unscaled — numerically a
            # no-op in exact arithmetic; in bf16 it lifts tiny cotangents
            # over underflow. Overflow => non-finite grads => the skip
            # guard below drops the step.
            def scaled_loss_fn(params):
                loss, aux = loss_fn(params)
                return loss * loss_scale, aux
            (loss, (new_stats, logits)), grads = jax.value_and_grad(
                scaled_loss_fn, has_aux=True)(state.params)
            inv = 1.0 / loss_scale
            loss = loss * inv
            grads = jax.tree.map(lambda g: g * inv, grads)
        else:
            (loss, (new_stats, logits)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(state.params)
        grad_norm = optax.global_norm(grads)

        @jax.named_scope("optimizer_update")
        def _apply_update(st: TrainState) -> TrainState:
            new_state = st.apply_gradients(grads=grads).replace(
                batch_stats=new_stats)
            from tpuic.runtime import faults as _faults
            if _faults.fire("bf16_master_truncate"):
                # Seeded mixed-precision bug (trace-time inject, baked
                # into the compiled step): master weights round-trip
                # through bf16 every update — exactly the no-f32-master
                # mistake the scripts/bf16_parity.py convergence gate
                # exists to catch. Never armed outside the gate's
                # --expect-fail arm.
                new_state = new_state.replace(params=jax.tree.map(
                    lambda p: p.astype(jnp.bfloat16).astype(p.dtype),
                    new_state.params))
            if optim_cfg.ema_decay > 0 and st.ema_params is not None:
                d = optim_cfg.ema_decay
                new_ema = jax.tree.map(lambda e, p: d * e + (1.0 - d) * p,
                                       st.ema_params, new_state.params)
                k = max(1, optim_cfg.grad_accum_steps)
                if k > 1:
                    # Under gradient accumulation params move only every
                    # K-th micro-step (optax.MultiSteps); advancing the EMA
                    # on the other K-1 would compound the decay to d^K per
                    # real update. Hold it between real updates instead.
                    is_update = ((st.step + 1) % k) == 0
                    new_ema = jax.tree.map(
                        lambda ne, e: jnp.where(is_update, ne, e),
                        new_ema, st.ema_params)
                new_state = new_state.replace(ema_params=new_ema)
            return new_state

        if optim_cfg.skip_nonfinite:
            # Non-finite step guard (docs/robustness.md): keep the update
            # only when loss AND global grad norm are finite; otherwise the
            # state passes through UNCHANGED (params, opt_state, BN stats,
            # EMA, step counter) — one poisoned batch costs one skipped
            # step, not the run. One compiled program either way, so a NaN
            # batch causes zero recompiles.
            #
            # Implemented as a per-leaf select, NOT lax.cond: a cond whose
            # skip branch passes donated inputs through to the outputs hits
            # a buffer-aliasing bug in executables deserialized from the
            # persistent compilation cache on this container's jax 0.4.37
            # CPU backend — after a checkpoint restore, steps through the
            # disk-cached executable read corrupted buffers (NaN loss on
            # finite data; reproduced and bisected: cache+donate+cond is
            # the exact trigger, any two of the three are fine). The select
            # computes the update unconditionally and discards it on skip —
            # a few elementwise ops on the update path, negligible next to
            # fwd/bwd. The select's own pass-through aliasing still upsets
            # cache-deserialized CPU executables intermittently, so the
            # donate gate above also applies (cpu + cache + guard =>
            # donate=False); TPU and cache-less runs are untouched.
            finite = jnp.isfinite(loss) & jnp.isfinite(grad_norm)
            updated = _apply_update(state)
            new_state = jax.tree.map(
                lambda new, old: jnp.where(finite, new, old), updated, state)
            if state.skip_count is not None:
                # Consecutive-skip streak, in-graph (train/state.py): the
                # Trainer reads it via the deferred metrics drain and rolls
                # back past RunConfig.skip_threshold.
                new_state = new_state.replace(skip_count=jnp.where(
                    finite, 0, state.skip_count + 1).astype(jnp.int32))
        else:
            new_state = _apply_update(state)
        with jax.named_scope("step_metrics"):
            acc = accuracy(logits, labels)
            if mask is not None:
                m = mask.astype(jnp.float32)
                acc_mean = jnp.sum(acc * m) / jnp.maximum(jnp.sum(m), 1.0)
            else:
                acc_mean = jnp.mean(acc)
        metrics = {"loss": loss, "accuracy": acc_mean,
                   "grad_norm": grad_norm}
        if optim_cfg.skip_nonfinite:
            metrics["skipped"] = 1.0 - finite.astype(jnp.float32)
            if new_state.skip_count is not None:
                metrics["skip_count"] = new_state.skip_count
        if lr_schedule is not None:
            metrics["lr"] = lr_schedule(state.step)
        return new_state, metrics

    if mesh is None:
        return jax.jit(train_step, donate_argnums=(0,) if donate else ())
    repl, data = _replicated(mesh), _batch_shardings(mesh)
    st = state_sharding if state_sharding is not None else repl
    return jax.jit(
        train_step,
        in_shardings=(st, data),
        out_shardings=(st, repl),
        donate_argnums=(0,) if donate else (),
    )


def make_eval_step(optim_cfg: OptimConfig, model_cfg: ModelConfig,
                   mesh: Optional[Mesh] = None, state_sharding=None,
                   per_sample: bool = False,
                   per_class: bool = False) -> Callable:
    """Returns jitted ``eval_step(state, batch) -> metrics``.

    metrics: {'correct': Σ 0/1 over valid, 'count': Σ mask,
    'loss_num': Σ w·nll, 'loss_den': Σ w}. Summing each across batches and
    dividing on host gives the exact global val accuracy (reference
    train.py:92, minus the pickle gather and the sampler-padding
    double-count) and the exact global weighted CE (numerator and
    denominator accumulated separately so batch composition can't skew the
    weighted mean).

    per_sample=True adds ``wrong``: the [global_batch] 0/1
    misclassification vector. Its output sharding is replicated, so under a
    mesh GSPMD materializes it with an all-gather over the ``data`` axis —
    the fixed-shape, ICI-ridden redesign of the reference's pickle-based
    ragged all_gather of per-sample results (ddp_utils.py:16-56): every
    host ends up with the full global vector and can map positions back to
    image ids through the (host-replicated) epoch order
    (tpuic.data.Loader attaches ``batch.indices``).

    per_class=True adds ``confusion``: the [C, C] count matrix
    (rows = true class, cols = predicted), computed as a one-hot
    contraction over the batch dim — a fixed-shape matmul GSPMD reduces
    over the ``data`` axis like every other eval sum (no ragged
    per-class gathers). Summed across batches it yields exact global
    per-class accuracy (diagonal / row sums).
    """
    class_weights = (jnp.asarray(optim_cfg.class_weights, jnp.float32)
                     if optim_cfg.class_weights else None)

    def eval_step(state: TrainState, batch):
        images, labels = batch["image"], batch["label"]
        mask = batch.get("mask")
        m = (mask.astype(jnp.float32) if mask is not None
             else jnp.ones(labels.shape, jnp.float32))
        # Validation (and thus 'best' checkpoint selection) uses the EMA
        # weights when the recipe maintains them (state.inference_params).
        variables = {"params": state.inference_params,
                     "batch_stats": state.batch_stats}
        logits = state.apply_fn(variables, images, train=False)
        with jax.named_scope("eval_metrics"):
            acc = accuracy(logits, labels)
            loss = classification_loss(logits, labels,
                                       class_weights=class_weights, mask=m)
        if class_weights is not None:
            w = jnp.sum(jax.nn.one_hot(labels, logits.shape[-1],
                                       dtype=jnp.float32)
                        * class_weights[None, :], axis=-1) * m
        else:
            w = m
        loss_den = jnp.sum(w)
        out = {"correct": jnp.sum(acc * m), "count": jnp.sum(m),
               "loss_num": loss * loss_den, "loss_den": loss_den}
        if logits.shape[-1] > 5:
            # Top-5 (the ImageNet convention; meaningless below 6 classes).
            out["correct5"] = jnp.sum(topk_accuracy(logits, labels, 5) * m)
        if per_sample:
            out["wrong"] = (1.0 - acc) * m
        if per_class:
            n_cls = logits.shape[-1]
            oh_true = jax.nn.one_hot(labels, n_cls,
                                     dtype=jnp.float32) * m[:, None]
            oh_pred = jax.nn.one_hot(jnp.argmax(logits, axis=-1), n_cls,
                                     dtype=jnp.float32)
            out["confusion"] = jnp.einsum("bt,bp->tp", oh_true, oh_pred)
        return out

    if mesh is None:
        return jax.jit(eval_step)
    repl, data = _replicated(mesh), _batch_shardings(mesh)
    st = state_sharding if state_sharding is not None else repl
    return jax.jit(eval_step, in_shardings=(st, data), out_shardings=repl)
