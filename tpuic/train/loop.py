"""Epoch driver — the re-design of reference train.py:99-188.

Maps 1:1 onto the reference's flow with the TPU-shaped replacements:

| reference                                  | here                              |
|--------------------------------------------|-----------------------------------|
| init_process_group('nccl') (train.py:102)  | runtime.initialize() + make_mesh  |
| DataLoader + DistributedSampler (112-118)  | tpuic.data.Loader (sharded)       |
| Classifier + SyncBN + DDP (122-128)        | create_model + sharded jit step   |
| checkpoint probe/partial load (131-153)    | CheckpointManager.restore_into    |
| MultiStepLR + weighted CE (156-158)        | optax schedule + loss config      |
| for epoch in range(100) (161)              | fit() — resumes at saved epoch    |
| train_epoch / val_epoch (36-97)            | train_epoch / val_epoch           |
| best/latest saves (173-188)                | save_best / maybe_save_latest     |

Progress UX matches the reference: host-0 tqdm bar with description
``Epoch: {e}; Loss {val:.4f}|({avg:.4f})`` (train.py:67-68) and val print
(train.py:94-95). The displayed loss is already the global mean — the step
computes it over the global batch, so no extra logging collective exists.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import time
from typing import Optional

import jax
import numpy as np
from tqdm import tqdm

from tpuic.runtime import faults as _faults
from tpuic.telemetry.events import publish as _tm_publish

from tpuic.checkpoint.manager import CheckpointManager
from tpuic.config import Config
from tpuic.data.folder import ImageFolderDataset
from tpuic.data.pipeline import Loader
from tpuic.metrics.logging import MetricLogger, host0_print, is_host0
from tpuic.metrics.meters import AverageMeter
from tpuic.models import create_model_from_config
from tpuic.runtime.mesh import make_mesh
from tpuic.train.optimizer import make_optimizer, make_schedule
from tpuic.train.state import create_train_state
from tpuic.train.step import make_eval_step, make_train_step


def _async_copy(tree) -> None:
    """Start device->host transfers for every array in a metrics dict so the
    later (deferred) device_get returns from the transfer cache instead of
    paying a tunnel RTT. Tolerates plain floats (tests with stub steps)."""
    for h in jax.tree_util.tree_leaves(tree):
        if hasattr(h, "copy_to_host_async"):
            h.copy_to_host_async()


class Trainer:
    def __init__(self, cfg: Config, mesh=None, log_dir: Optional[str] = None):
        self.cfg = cfg
        self.mesh = mesh if mesh is not None else make_mesh(cfg.mesh)
        # On a single device the mesh adds nothing — and on the tunneled
        # single-chip dev platform, SPMD-annotated executables take a ~100x
        # slower dispatch path — so sharding machinery engages only when
        # there is something to shard over.
        step_mesh = self.mesh if self.mesh.size > 1 else None
        d = cfg.data
        self.train_ds = ImageFolderDataset(d.data_dir, "train", d.resize_size, d)
        self.val_ds = ImageFolderDataset(d.data_dir, "val", d.resize_size, d,
                                         class_to_idx=self.train_ds.class_to_idx)
        if d.pack:
            # Decode-once packed cache + device-side augmentation: the only
            # way a 1-core host feeds the chip (tpuic/data/pack.py docstring).
            from tpuic.data.pack import pack_dataset
            cache = d.cache_dir or os.path.join(d.data_dir, ".tpuic_pack")
            self.train_ds = pack_dataset(self.train_ds, cache,
                                         verbose=is_host0())
            self.val_ds = pack_dataset(self.val_ds, cache, verbose=is_host0())
        global_batch = self._build_loaders()
        num_classes = cfg.model.num_classes or self.train_ds.num_classes
        mcfg = cfg.model
        if num_classes != mcfg.num_classes:
            mcfg = dataclasses.replace(mcfg, num_classes=num_classes)
        # Mixed-precision policy (docs/performance.md "Mixed-precision
        # training"): compute_dtype is the one knob — it forces the flax
        # forward dtype, the train step's batch cast and f32-loss guard
        # (train/step.py), and the dtype-aware MFU roofline below. Master
        # weights, optimizer moments, and checkpoints stay f32 regardless
        # (param_dtype is untouched), so lifecycle/swap/elastic machinery
        # never sees a bf16 artifact.
        from tpuic.config import resolve_compute_dtype
        compute_dtype = resolve_compute_dtype(mcfg)
        if compute_dtype:
            mcfg = dataclasses.replace(
                mcfg, dtype=("bfloat16" if compute_dtype == "bf16"
                             else "float32"))
        if cfg.optim.auto_class_weights:
            # Inverse-frequency CE weights from the train fold (what the
            # reference's hand-tuned [3,3,10,1,4,4,5] approximated for its
            # own dataset): w_c = N / (K_present * n_c), mean ~1 over the
            # classes that actually occur. Sized by the RESOLVED head width
            # so an explicit --num-classes larger than the fold's class
            # count pads with weight 1.0 instead of tracing a shape error.
            counts = self.train_ds.class_counts()
            if len(counts) > num_classes:
                raise ValueError(
                    f"auto class weights: train fold has {len(counts)} "
                    f"classes but the model head is {num_classes} wide")
            counts = np.concatenate(
                [counts, np.zeros(num_classes - len(counts), np.int64)])
            w = np.ones(num_classes, np.float64)
            present = counts > 0
            w[present] = counts.sum() / (present.sum() * counts[present])
            cfg = dataclasses.replace(cfg, optim=dataclasses.replace(
                cfg.optim,
                class_weights=tuple(round(float(x), 6) for x in w)))
            self.cfg = cfg
            host0_print("[weights] auto class weights: "
                        + ", ".join(f"{c}={x:.3f}" for c, x in
                                    zip(self.train_ds.classes,
                                        cfg.optim.class_weights)))
        self.mcfg = mcfg  # resolved model config (inferred num_classes)
        self.model = create_model_from_config(mcfg, mesh=self.mesh)
        steps = max(1, self.train_loader.steps_per_epoch())
        self.schedule = make_schedule(cfg.optim, steps, cfg.run.epochs,
                                      global_batch=global_batch)
        tx = make_optimizer(cfg.optim, steps, cfg.run.epochs,
                            global_batch=global_batch)
        shape = (global_batch, d.resize_size, d.resize_size, 3)
        with self.mesh:
            self.state = create_train_state(
                self.model, tx, jax.random.key(cfg.run.seed), shape,
                ema=cfg.optim.ema_decay > 0)
        from tpuic.utils import tree_bytes, tree_size
        host0_print(f"[model] {mcfg.name}: "
                    f"{tree_size(self.state.params) / 1e6:.1f}M params "
                    f"({tree_bytes(self.state.params) / (1 << 20):.1f} MB), "
                    f"{num_classes} classes, global batch {global_batch}")
        # TP/FSDP state sharding (replicated when neither is requested —
        # reference DDP semantics).
        self.state_sharding = None
        if step_mesh is not None and (cfg.mesh.fsdp or cfg.mesh.zero1 or (
                cfg.mesh.tensor_parallel and self.mesh.shape["model"] > 1)):
            from tpuic.parallel.sharding import shard_state, state_shardings
            self.state_sharding = state_shardings(
                self.state, self.mesh, tp=cfg.mesh.tensor_parallel,
                fsdp=cfg.mesh.fsdp, zero1=cfg.mesh.zero1)
            self.state = shard_state(self.state, self.state_sharding)
        self._build_steps()
        self.last_misclassified: list = []
        self.ckpt = CheckpointManager(cfg.run.ckpt_dir, mcfg.name,
                                      cfg.run.save_period,
                                      async_commit=cfg.run.async_checkpoint)
        if is_host0():
            # Reproducibility sidecar: the resolved config (incl. inferred
            # num_classes / derived class weights) next to the checkpoint
            # tracks. tpuic.predict reads it to auto-resolve the model.
            resolved = dataclasses.replace(cfg, model=mcfg)
            with open(os.path.join(self.ckpt.root, "config.json"), "w") as f:
                json.dump(dataclasses.asdict(resolved), f, indent=2,
                          default=str)
            # Class-name sidecar: online serving (tpuic.serve) has no fold
            # tree to derive display names from at request time.
            with open(os.path.join(self.ckpt.root,
                                   "class_to_idx.json"), "w") as f:
                json.dump(self.train_ds.class_to_idx, f, indent=2)
        # SIGTERM (pod preemption / scheduler eviction) -> finish the
        # current step, flush a 'latest' checkpoint, return cleanly
        # (runtime/preemption.py). The handler is installed for the span of
        # fit() only; polling is a flag read per step, with a cross-host
        # agreement at fixed boundaries on multi-host pods.
        from tpuic.runtime.preemption import PreemptionGuard
        self.preemption = PreemptionGuard()
        # Elastic fleet membership (runtime/membership.py, docs/
        # parallelism.md "Elastic data parallelism"): when the elastic
        # gang supervisor injected TPUIC_MEMBERSHIP_FILE, the loop polls
        # it at step boundaries (one os.stat when unchanged) and a
        # 'degrade' transition re-forms THIS process in place — restore
        # from the fleet-agreed step through the capped integrity
        # ladder, recompile if the local mesh shrank — with no process
        # restart. None (the common case) costs nothing.
        from tpuic.runtime.membership import MembershipWatcher
        self.membership = MembershipWatcher.from_env()
        self._reform_pending = None
        self.reforms = 0
        self.logger = MetricLogger(log_dir)
        self.start_epoch = 0
        # Step offset into start_epoch (step-exact resume from a mid-epoch
        # preemption flush); 0 for normal end-of-epoch checkpoints.
        self.start_step = 0
        self.best_score = 0.0
        if cfg.run.init_from:
            self._init_from_torch(cfg.run.init_from)
        if cfg.run.resume:
            # Newest of latest/best — a crash after the last val improvement
            # resumes at the last periodic save instead of replaying epochs.
            self.state, self.start_epoch, self.best_score = \
                self.ckpt.restore_into(self.state)
            self.start_step = self._validated_start_step()
            if self.state_sharding is not None:
                from tpuic.parallel.sharding import shard_state
                self.state = shard_state(self.state, self.state_sharding)
        # Telemetry (docs/observability.md): step-time breakdown, goodput
        # accounting, optional JSONL event sink / trace trigger /
        # TensorBoard bridge — all host-side subscribers on the global
        # event bus (zero device syncs, zero compiles; test-asserted).
        from tpuic import telemetry as _telemetry
        self.telemetry = _telemetry.TrainTelemetry(
            cfg.run, model_name=mcfg.name, image_size=d.resize_size,
            global_batch=global_batch, n_devices=self.mesh.size,
            device=jax.devices()[0], tb=self.logger.tb,
            compute_dtype=(compute_dtype or (
                "bf16" if mcfg.dtype == "bfloat16" else "f32")))
        if self.telemetry.profile is not None:
            # Device-time attribution (telemetry/profile.py): hand the
            # analyzer the REAL train step's AOT view. Called lazily
            # (once, cached) from the capture/finalize hooks — never on
            # the hot path.
            self.telemetry.profile.hlo_provider = self._train_step_hlo
        # Non-finite rollback bookkeeping (docs/robustness.md): the jitted
        # step skips poisoned updates in-graph (train/step.py guard) and
        # counts the consecutive-skip streak in state.skip_count; the
        # deferred log drain watches the streak and, past
        # run.skip_threshold, flags a rollback — fit() then restores the
        # last good checkpoint through the integrity ladder and continues.
        self._rollback_pending = False
        self.rollbacks = 0
        self._quarantine_seen = 0
        self._last_skip_streak = 0
        self._steps_exhausted = False

    def _train_step_hlo(self):
        """(optimized HLO text, cost_analysis dict) of THE train step —
        the device-time analyzer's model source (docs/observability.md,
        "Device-time attribution").

        Lowered against the batch geometry this run trains with (image
        as float32, the decode-path contract; the packed uint8 path
        differs only in the cast/augment prologue, which class-level
        attribution absorbs into elementwise).  The compile is off the
        hot path by construction — analysis hooks only — and hits the
        persistent compilation cache when one is configured."""
        from tpuic.telemetry.goodput import cost_analysis_dict
        d = self.cfg.data
        gb = self.train_loader.global_batch
        sds = jax.ShapeDtypeStruct
        batch = {"image": sds((gb, d.resize_size, d.resize_size, 3),
                              np.float32),
                 "label": sds((gb,), np.int32),
                 "mask": sds((gb,), np.float32)}
        compiled = self.train_step.lower(self.state, batch).compile()
        try:
            cost = cost_analysis_dict(compiled)
        except Exception:
            cost = {}
        return compiled.as_text(), cost

    def _init_from_torch(self, path: str) -> None:
        """Pretrained-weight initialization from a torch checkpoint.

        The reference starts every backbone pretrained (nn/classifier.py:9-21);
        the conversion + lenient merge (and the *-s2d stem re-indexing) is
        shared with tpuic.predict in checkpoint.torch_convert."""
        from tpuic.checkpoint.torch_convert import init_state_from_torch

        self.state = init_state_from_torch(self.state, path,
                                           self.cfg.model.name,
                                           log=host0_print)
        if self.state_sharding is not None:
            from tpuic.parallel.sharding import shard_state
            self.state = shard_state(self.state, self.state_sharding)

    def _build_loaders(self) -> int:
        """Train/val Loaders for the CURRENT ``self.mesh`` — ONE
        construction site shared by ``__init__`` and the elastic re-form
        (``_rebuild_for_replicas``), so the two paths cannot drift: the
        global batch is per-device batch x data extent, the device-cache
        HBM budget is a per-process TOTAL (train claims first, val gets
        the remainder — never 2x the configured budget), and a fold
        smaller than one global batch fails loudly. Returns the global
        batch."""
        d = self.cfg.data
        step_mesh = self.mesh if self.mesh.size > 1 else None
        n_data = self.mesh.shape["data"]
        global_batch = d.batch_size * n_data
        cache_total = int(d.device_cache_mb) << 20
        self.train_loader = Loader(self.train_ds, global_batch, step_mesh,
                                   seed=d.shuffle_seed,
                                   num_workers=d.num_workers,
                                   prefetch=d.prefetch, drop_last=True,
                                   device_cache_bytes=cache_total,
                                   augment=None if d.augment else False)
        if self.train_loader.steps_per_epoch() == 0:
            # drop_last with a fold smaller than ONE global batch would
            # otherwise train zero steps per epoch while still writing
            # checkpoints and reporting val numbers — a silent no-op run.
            raise ValueError(
                f"train fold has {len(self.train_ds)} images but the "
                f"global batch is {global_batch} "
                f"({d.batch_size}/chip x {n_data} data-parallel devices): "
                "every epoch would train ZERO steps (the trailing partial "
                "batch is dropped). Reduce --batchsize or the device "
                "count, or add data.")
        self.val_loader = Loader(self.val_ds,
                                 d.resolved_val_batch_size() * n_data,
                                 step_mesh, shuffle=False,
                                 num_workers=d.num_workers,
                                 prefetch=d.prefetch,
                                 device_cache_bytes=max(
                                     0, cache_total
                                     - self.train_loader.resident_bytes))
        return global_batch

    def _step_program_keys(self):
        """Registry keys of THE train/eval step programs for the current
        geometry (tpuic.compiled, docs/performance.md "Compiled-program
        registry").  The key pins everything the built step closes over
        — optimizer config (schedule params, guard, loss scale, class
        weights, ema), seed, eval flags, sharding flags, the donation
        policy verdict — plus the loader geometry the schedule was
        derived from, the mesh signature, and the batch avals.  An
        elastic reform back to a previously-seen extent therefore HITS
        (aval-identical executables reused instead of re-jitting); any
        geometry/config change misses and the superseded key is evicted."""
        import dataclasses as _dc

        from tpuic.compiled import ProgramKey, donation_allowed, stable_crc
        cfg = self.cfg
        d = cfg.data
        steps = max(1, self.train_loader.steps_per_epoch())
        global_batch = self.train_loader.global_batch
        mesh_sig = (tuple((str(a), int(n)) for a, n in
                          self.mesh.shape.items())
                    if self.mesh.size > 1 else ())
        cfg_crc = stable_crc({
            "optim": _dc.asdict(cfg.optim), "model": _dc.asdict(self.mcfg),
            "mesh_cfg": _dc.asdict(cfg.mesh), "seed": cfg.run.seed,
            "epochs": cfg.run.epochs, "steps_per_epoch": steps,
            "collect": cfg.run.collect_misclassified,
            "per_class": cfg.run.per_class_metrics,
            "donate": donation_allowed(
                guard_active=bool(cfg.optim.skip_nonfinite)),
        })
        shapes = ((global_batch, d.resize_size, d.resize_size, 3), cfg_crc)
        return tuple(
            ProgramKey(model=f"train:{self.mcfg.name}:{kind}",
                       shapes=shapes, mesh=mesh_sig, dtype=self.mcfg.dtype)
            for kind in ("step", "eval"))

    def _build_steps(self) -> None:
        """(Re-)build the train/eval steps for the CURRENT mesh,
        schedule, and state sharding — shared by ``__init__`` and the
        elastic re-form — through the compiled-program registry
        (tpuic/compiled): a reform whose geometry matches an existing
        key reuses the aval-identical jitted step (and its warm XLA
        cache) instead of re-jitting, a changed geometry builds fresh
        and evicts the pre-reform entries."""
        from tpuic.compiled import registry as _registry
        cfg = self.cfg
        step_mesh = self.mesh if self.mesh.size > 1 else None
        train_key, eval_key = self._step_program_keys()
        self.train_step = _registry.get_or_compile(
            train_key,
            lambda: make_train_step(cfg.optim, self.mcfg, step_mesh,
                                    lr_schedule=self.schedule,
                                    seed=cfg.run.seed,
                                    state_sharding=self.state_sharding),
        ).executable
        self.eval_step = _registry.get_or_compile(
            eval_key,
            lambda: make_eval_step(
                cfg.optim, self.mcfg, step_mesh,
                state_sharding=self.state_sharding,
                per_sample=cfg.run.collect_misclassified,
                per_class=cfg.run.per_class_metrics),
        ).executable
        # Pre-reform GC: a superseded geometry's step entries can never
        # run again in this process.
        for old in getattr(self, "_step_keys", ()):
            if old not in (train_key, eval_key):
                _registry.evict(old)
        self._step_keys = (train_key, eval_key)
        # Prewarm manifest (docs/performance.md): when the supervisor —
        # or any caller — exported TPUIC_COMPILE_MANIFEST, persist the
        # keys this process compiled so the NEXT process (a restarted
        # gang member) prewarms them up front.  ``_manifest_preexisting``
        # (first call only) records whether a previous life already left
        # a manifest behind — that is what gates the restart-side
        # prewarm in fit().
        mpath = os.environ.get("TPUIC_COMPILE_MANIFEST", "")
        if mpath:
            if not hasattr(self, "_manifest_preexisting"):
                self._manifest_preexisting = os.path.exists(mpath)
            try:
                _registry.write_manifest(mpath)
            except OSError as e:
                host0_print(f"[compiled] could not write prewarm "
                            f"manifest {mpath}: {e}")

    def prewarm(self, manifest_path: Optional[str] = None) -> dict:
        """Compile-and-execute every program this run's steady state
        needs BEFORE the first training step (docs/performance.md,
        "Compiled-program registry") — the restart path that turns
        first-step compile stalls into up-front prewarm time, measured
        in perf/resume_cache_proof.json and checker-asserted (zero
        steady-state compiles after prewarm) in the CI prewarm smoke.

        One real batch is pulled from each loader (the same batch fit()
        will see first — the epoch permutation and augment streams are
        position-keyed and stateless, so nothing is consumed or
        perturbed) and run through the train step against a THROWAWAY
        copy of the state (the step is functional and the copy absorbs
        donation) and through the eval step directly.  Executing — not
        just lowering — is what populates the jit caches and forces the
        backend compiles (disk reads when the persistent XLA cache is
        warm), so the subsequent fit dispatches with zero compiles.

        ``manifest_path`` names a prewarm manifest to cross-check: a
        corrupt manifest raises :class:`tpuic.compiled.ManifestError`
        (refusal — never prewarm from a torn file); a manifest that
        does not list this run's keys is reported but does not block
        (the geometry is local knowledge; the manifest is the fleet's
        memory of it)."""
        from tpuic.compiled import ProgramKey, load_manifest
        from tpuic.compiled import registry as _registry
        t0 = time.perf_counter()
        listed = None
        if manifest_path:
            listed = {ProgramKey.from_dict(e["key"])
                      for e in load_manifest(manifest_path)}
        keys = getattr(self, "_step_keys", ())
        covered = (None if listed is None
                   else sum(1 for k in keys if k in listed))
        it = self.train_loader.epoch(self.start_epoch,
                                     start_step=self.start_step)
        try:
            batch = next(it)
        finally:
            it.close()
        fbatch = {k: batch[k] for k in ("image", "label", "mask")}
        # Donation-safe copy: the guard-off path donates the state
        # argument, so the real self.state must never be passed here.
        # The copy must be SIGNATURE-FAITHFUL leaf by leaf — a restored
        # state mixes numpy leaves with committed/uncommitted jax
        # Arrays, and coercing a numpy leaf to a jax Array changes the
        # pjit call signature: fit's first step would then backend-
        # compile a second executable (no retrace, so invisible to
        # trace counters) and the prewarm would not be compile-flat.
        # jnp.copy preserves sharding and committed-ness for jax
        # Arrays; numpy stays numpy; host scalars are immutable.
        import jax.numpy as jnp

        def _leaf_copy(x):
            if isinstance(x, jax.Array):
                return jnp.copy(x)
            if isinstance(x, np.ndarray):
                return np.copy(x)
            return x

        state_copy = jax.tree_util.tree_map(_leaf_copy, self.state)
        # TWO train-step executions, because a resumed run dispatches
        # under TWO distinct program signatures and both must be warm:
        #  1. the RESTORED signature — a checkpoint-restored state mixes
        #     numpy and uncommitted-jax scalar leaves (step, skip_count),
        #     which pjit resolves to unspecified input shardings; fit's
        #     first step runs under this signature, and
        #  2. the STEADY-STATE signature — every later step passes the
        #     previous step's output, whose leaves are all committed jax
        #     Arrays, so the same avals resolve to concrete shardings: a
        #     different lowering key and a different executable.
        # Warming only (1) leaves fit's SECOND step to backend-compile
        # (the stall moves one step later instead of disappearing).
        # Feeding call 1's output state into call 2 reproduces (2)
        # exactly; both calls run against throwaway state (donation-safe).
        out_state, m = self.train_step(state_copy, fbatch)
        jax.block_until_ready(m["loss"])
        out2_state, m2 = self.train_step(out_state, fbatch)
        jax.block_until_ready(m2["loss"])
        del out_state, state_copy
        vit = self.val_loader.epoch(0)
        try:
            vbatch = next(vit)
        finally:
            vit.close()
        vfbatch = {k: vbatch[k] for k in ("image", "label", "mask")}
        # Same two-signature rule for eval: fit's epoch-end eval sees the
        # post-step (all-committed) state; an eval before any step (a
        # resume landing exactly on an epoch boundary) sees the restored
        # one. keep_unused DCE usually collapses the two eval signatures
        # into one, but that is a jaxpr property, not a contract.
        em = self.eval_step(out2_state, vfbatch)
        jax.block_until_ready(em["count"])
        em2 = self.eval_step(self.state, vfbatch)
        jax.block_until_ready(em2["count"])
        del out2_state
        for k in keys:
            _registry.mark_prewarmed(k)
        prewarm_s = time.perf_counter() - t0
        out = {"prewarm_s": round(prewarm_s, 3), "programs": len(keys),
               "manifest_listed": covered}
        if covered is not None and covered < len(keys):
            host0_print(f"[compiled] prewarm manifest lists {covered}/"
                        f"{len(keys)} of this run's step programs "
                        f"(geometry changed since it was written)")
        host0_print(f"[compiled] prewarmed {len(keys)} step programs in "
                    f"{prewarm_s:.1f}s")
        _tm_publish("compile_cache", action="prewarm_done",
                    programs=len(keys), manifest_listed=covered,
                    duration_s=round(prewarm_s, 3))
        return out

    def _loader_geometry(self):
        """(global_batch, seed, n_samples) — everything the epoch
        permutation and its step slicing depend on; recorded at a
        mid-epoch flush and required to match before a resume reuses the
        step offset."""
        ld = self.train_loader
        return (ld.global_batch, ld.seed, len(ld.dataset))

    def _validated_start_step(self) -> int:
        """Step offset of the checkpoint the manager just restored, IF its
        recorded loader geometry matches this run (shared by __init__
        resume and the non-finite rollback — both must refuse an offset
        that would skip the wrong samples)."""
        start_step = self.ckpt.last_restore_step_in_epoch or 0
        if not start_step:
            return 0
        saved = self.ckpt.last_restore_geometry
        live = self._loader_geometry()
        epoch = (self.ckpt.last_restore_meta or (0, 0))[0]
        if saved is not None and any(
                a not in (-1, b) for a, b in zip(saved, live)):
            # The epoch permutation is keyed by (seed, n_samples)
            # and sliced by global_batch — a mismatch in any means
            # the offset points at different samples.
            host0_print(
                f"[ckpt] mid-epoch checkpoint was flushed under "
                f"loader geometry (global_batch, seed, n_samples)="
                f"{saved} but this run has {live} — the step "
                f"offset would skip the wrong samples; replaying "
                f"epoch {epoch} from its start instead")
            return 0
        if start_step > len(self.train_loader):
            host0_print(
                f"[ckpt] mid-epoch step {start_step} exceeds "
                f"this run's {len(self.train_loader)} steps/epoch "
                f"(dataset changed?) — replaying epoch "
                f"{epoch} from its start instead")
            return 0
        return start_step

    # -- epochs -------------------------------------------------------------
    def train_epoch(self, epoch: int, start_step: int = 0) -> float:
        """Reference train_epoch (train.py:36-73).

        ``start_step`` continues a partially-trained epoch at that step
        (step-exact resume; the loader serves the identical remainder).
        ``self.last_epoch_steps`` records how many steps of this epoch are
        complete when the method returns — = steps_per_epoch normally,
        less if preemption broke the loop — for the mid-epoch flush."""
        losses = AverageMeter()
        remaining = len(self.train_loader) - start_step
        self.last_epoch_steps = start_step
        # Step-time breakdown (telemetry/steptime.py): the wrapped
        # iterator times loader waits (data-wait), dispatch is timed
        # around the step call below, and the residual is device time —
        # pure perf_counter arithmetic, no host syncs added.
        steptime = self.telemetry.steptime
        steptime.epoch_start()
        it = steptime.wrap_epoch(
            self.train_loader.epoch(epoch, start_step=start_step))
        bar = tqdm(it, total=remaining, disable=not is_host0())
        metrics = None
        log_every = max(1, self.cfg.run.log_every_steps)
        global_batch = self.train_loader.global_batch
        # One readback per EPOCH for the optimizer step counter: the in-loop
        # step number is step0 + host steps, so logging never touches
        # state.step on the hot path (each device_get is a full tunnel RTT).
        step0 = int(jax.device_get(self.state.step))  # tpuic-ok: TPU101 one read per EPOCH, off the steady-state path
        # Deferred logging: at log point N we SCHEDULE an async device->host
        # copy of the interval's metrics and DRAIN log point N-1, whose
        # values the device finished an interval ago — so the drain returns
        # from the transfer cache instead of stalling dispatch. The loop
        # still cannot run away from the device: draining point N-1 throttles
        # the host to at most one interval of run-ahead, which keeps the
        # measured images/sec honest. (Round-4 chip finding: four blocking
        # scalar reads per log point cost ~4 RTTs and held Trainer.fit at
        # 59% of the const-batch bench over the tunneled link.)
        pending = None  # (host step number, images/sec, metric handles)
        t_log = time.perf_counter()
        from tpuic.runtime.preemption import agree
        preempt_on = self.cfg.run.handle_preemption
        multi = jax.process_count() > 1
        # Multi-host: a locally-latched SIGTERM may only be acted on at a
        # boundary every host reaches together (agree() is a collective);
        # 16 steps of latency is well inside any grace window. With
        # handle_preemption off, no polling (and no allgather) happens.
        preempt_sync = 16
        for step, batch in enumerate(bar):
            # Fault-injection sites (runtime/faults.py; inert when unarmed):
            # 'sigterm' drives the REAL preemption path — the latch, the
            # boundary agreement, the mid-epoch flush — deterministically.
            if preempt_on and _faults.fire("sigterm", step=step0 + step):
                os.kill(os.getpid(), signal.SIGTERM)
            trig = preempt_on and self.preemption.triggered
            if preempt_on and multi:
                if step % preempt_sync == 0:
                    trig = agree(trig)
                    if trig:
                        self.preemption.trigger()  # latch the agreement
                else:
                    trig = False  # never act unilaterally between boundaries
            if trig:
                bar.close()
                break
            if self.membership is not None:
                m = self.membership.poll()
                if m is not None:
                    if m.reason == "degrade" or (
                            self.membership.skipped
                            and m.resume_step is not None):
                        # A peer died: re-form from the fleet-agreed
                        # step (fit() runs the restore) instead of
                        # training ahead of the membership the fleet
                        # just agreed on. The second arm is the
                        # coalesced case — the file holds only the
                        # latest view, so a degrade overwritten by its
                        # rejoin before this rank polled (a long val
                        # pass) surfaces as a rejoin with skipped
                        # versions and the cap aboard; restoring to the
                        # cap is a deterministic replay either way.
                        self._reform_pending = m
                        bar.close()
                        break
                    # rejoin/restart transitions need no restore here —
                    # note them so the stream shows the fleet view.
                    _tm_publish("reform", reason=m.reason,
                                version=m.version, active=list(m.active),
                                resume_step=m.resume_step, acted=False)
            fbatch = {k: batch[k] for k in ("image", "label", "mask")}
            if _faults.fire("nan_batch", step=step0 + step):
                # Poison this step's images host-side: same shapes/dtypes,
                # so the guard's zero-recompile contract is what's tested.
                fbatch["image"] = fbatch["image"] * np.float32("nan")
            if _faults.fire("slow_step", step=step0 + step):
                # Injected host-side stall (runtime/faults.py): a
                # deterministic step-time regression, for trace-trigger
                # tests — the step's work is untouched.
                slow_s = _faults.param("slow_step")
                # Explicit None check: '#0' must mean a 0 s stall (a
                # severity-sweep control run), not the default.
                time.sleep(
                    0.05 if slow_s is None else float(slow_s))  # tpuic-ok: TPU101 fault param is a host float
            if _faults.fire("hard_crash", step=step0 + step):
                # Abrupt process death: SIGKILL to self — no flush, no
                # atexit, no Python teardown. The supervisor
                # (runtime/supervisor.py) must classify this as a
                # retryable crash and restart with resume.
                os.kill(os.getpid(), signal.SIGKILL)
            if _faults.fire("hang_step", step=step0 + step):
                # Wedge: stop making progress while staying alive — the
                # shape of a stuck device call or a data-pipeline
                # deadlock. Only the supervisor's watchdog escalation
                # (SIGQUIT dump -> SIGTERM -> SIGKILL) ends it; the
                # cooperative SIGTERM latch is useless here by design
                # (the loop never reaches its next poll).
                hang_s = _faults.param("hang_step")
                deadline = (None if hang_s is None
                            else time.monotonic() + float(hang_s))  # tpuic-ok: TPU101 fault param is a host float
                while deadline is None or time.monotonic() < deadline:
                    time.sleep(0.5)
            if _faults.fire("rank_crash", step=step0 + step):
                # Rank-targeted SIGKILL (#PARAM names the victim rank,
                # default 0): one member of a gang dies abruptly while
                # its peers keep running — the partial failure the gang
                # supervisor (runtime/gang.py) must answer with a
                # coordinated teardown + restart. Every rank evaluates
                # the armed directive; only the named one dies.
                target = _faults.param("rank_crash")
                if int(self.telemetry.rank) == int(target or 0):
                    os.kill(os.getpid(), signal.SIGKILL)
            if _faults.fire("rank_hang", step=step0 + step):
                # Rank-targeted wedge (forever; #PARAM names the rank,
                # default 0): the partial-hang twin — only the gang's
                # per-rank watchdog escalation ends it.
                target = _faults.param("rank_hang")
                if int(self.telemetry.rank) == int(target or 0):
                    while True:
                        time.sleep(0.5)
            steptime.dispatch_start()
            self.state, metrics = self.train_step(self.state, fbatch)
            steptime.dispatch_end()
            self.last_epoch_steps = start_step + step + 1
            if (step + 1) % log_every == 0:
                handles = {"loss": metrics["loss"],
                           "accuracy": metrics["accuracy"]}
                if "lr" in metrics:
                    handles["lr"] = metrics["lr"]
                if "skip_count" in metrics:
                    # The in-graph consecutive-skip streak rides the SAME
                    # deferred drain as the other metrics — rollback
                    # detection costs zero extra host syncs.
                    handles["skip_count"] = metrics["skip_count"]
                _async_copy(handles)
                now = time.perf_counter()
                imgs_per_sec = log_every * global_batch / max(now - t_log,
                                                              1e-9)
                t_log = now
                if pending is not None:
                    self._drain_train_log(pending, losses, bar, epoch)
                pending = (step0 + step + 1, imgs_per_sec, handles)
                if step + 1 == remaining:
                    # Last step of the epoch: drain NOW, while the bar is
                    # still open (set_description on a closed bar is a
                    # no-op), so the final interval's loss is shown. The
                    # blocking read sits on the epoch boundary, off the
                    # steady-state path.
                    self._drain_train_log(pending, losses, bar, epoch)
                    pending = None
                if self._rollback_pending:
                    # Grinding out the rest of the epoch on (guarded but
                    # unprogressing) steps is pointless — hand back to
                    # fit() for the restore now.
                    bar.close()
                    break
            # Close the step's telemetry span (publishes the 'step'
            # event with the data/dispatch/device breakdown). Sits after
            # the deferred drain so blocking readbacks are charged to
            # the step that performed them.
            steptime.step_end(step0 + step + 1)
            if (self.cfg.run.max_steps
                    and step0 + step + 1 >= self.cfg.run.max_steps):
                # --steps budget (smoke runs / CI telemetry gate): stop
                # mid-epoch; fit() skips the epoch's val and exits.
                self._steps_exhausted = True
                bar.close()
                break
        if pending is not None:
            # Post-loop drain (break paths: budget/rollback/preemption —
            # the in-loop last-step branch covers normal epoch ends): the
            # blocking readback here is the final dispatched step still
            # executing, i.e. device time AFTER its step event closed.
            # Published as a 'drain' span so the goodput ledger books it
            # as productive instead of losing it to 'other'.
            t_drain = time.perf_counter()
            self._drain_train_log(pending, losses, bar, epoch)
            _tm_publish("drain",
                        duration_s=round(time.perf_counter() - t_drain, 3))
        # Epoch-mean loss over all steps, one sync, off the hot path: the
        # running meter only sees logged points (display semantics identical
        # to the reference bar, train.py:67-68).
        if metrics is not None and losses.count == 0:
            losses.update(
                float(metrics["loss"]), 1)  # tpuic-ok: TPU101 post-loop epoch boundary, one sync
        # Quarantine surfacing (docs/robustness.md): decode failures the
        # data layer absorbed this epoch, one console line + JSONL record
        # per epoch with events — a corrupt file is visible without being
        # fatal.
        q = self.train_loader.quarantine_count
        if q > self._quarantine_seen:
            delta = q - self._quarantine_seen
            self._quarantine_seen = q
            host0_print(f"[quarantine] epoch {epoch}: {delta} sample "
                        f"load(s) served a replacement (total {q})")
            self.logger.write(step0 + self.last_epoch_steps - start_step,
                              quarantined=delta, quarantined_total=q)
        _tm_publish("epoch", epoch=epoch,
                    steps=self.last_epoch_steps - start_step,
                    loss=round(losses.avg, 6))
        return losses.avg

    def _drain_train_log(self, pending, losses: AverageMeter, bar,  # tpuic-ok: TPU101 THE deferred drain site
                         epoch: int) -> None:
        """Read one deferred log interval (a single batched device_get) and
        emit the bar description + JSONL record for it. Also the rollback
        watchdog: the drained skip_count is the in-graph consecutive
        non-finite streak; past run.skip_threshold it flags a rollback
        (detection latency <= ~2 log intervals — the price of keeping the
        hot path free of per-step host syncs)."""
        step_num, imgs_per_sec, handles = pending
        vals = jax.device_get(handles)
        loss = float(vals["loss"])
        losses.update(loss, 1)
        bar.set_description(
            f"Epoch: {epoch}; Loss {losses.val:.4f}|({losses.avg:.4f})")
        extra = {}
        streak = int(vals.get("skip_count", 0))
        if streak:
            extra["skipped_streak"] = streak
            # 'skip' event (docs/observability.md): the streak at this
            # drain plus the delta since the last one — the goodput
            # tracker charges that many steps to the skip bucket. At
            # log_every_steps=1 the delta is exact; at coarser cadences
            # it undercounts streaks that reset inside an interval
            # (documented estimate, same latency as rollback detection).
            last = getattr(self, "_last_skip_streak", 0)
            delta = streak - last if streak > last else streak
            _tm_publish("skip", step=step_num, streak=streak, delta=delta)
        self._last_skip_streak = streak
        self.logger.write(step_num, loss=loss,
                          accuracy=float(vals["accuracy"]),
                          lr=float(vals.get("lr", 0.0)),
                          images_per_sec=round(imgs_per_sec, 1), **extra)
        thr = self.cfg.run.skip_threshold
        if (thr > 0 and streak >= thr and self.cfg.run.rollback
                and not self._rollback_pending):
            host0_print(
                f"[rollback] {streak} consecutive non-finite steps "
                f"(threshold {thr}) at step {step_num} — state is still "
                f"finite (guard skipped the updates); restoring the last "
                f"good checkpoint instead of grinding forward")
            self._rollback_pending = True

    def val_epoch(self, epoch: int) -> float:
        """Reference val_epoch (train.py:78-97): exact global accuracy ×100,
        plus the exact global weighted val CE (num/den accumulated
        separately)."""
        t_eval0 = time.perf_counter()
        correct = correct5 = count = loss_num = loss_den = 0.0
        have_top5 = False
        collect = self.cfg.run.collect_misclassified
        per_class = self.cfg.run.per_class_metrics
        confusion = None
        misclassified: list = []
        # Deferred accumulation: per-batch float() reads would serialize
        # every eval step against the tunnel RTT (the same stall the train
        # loop's deferred logging avoids), so metric handles are drained a
        # WINDOW behind dispatch. The window bound matters on the streaming
        # (non-resident) val path: each not-yet-executed step pins its uint8
        # batch upload in HBM, so unbounded run-ahead over a long val fold
        # would stack hundreds of ~20 MB buffers; draining handle i-W after
        # dispatching i throttles the host to at most W batches in flight.
        window = max(2, int(self.cfg.data.prefetch))
        pending: list = []

        def drain(m, indices) -> None:  # tpuic-ok: TPU101 deferred eval drain (window W behind dispatch)
            nonlocal correct, correct5, count, loss_num, loss_den, have_top5
            nonlocal confusion
            m = jax.device_get(m)
            correct += float(m["correct"])
            count += float(m["count"])
            loss_num += float(m["loss_num"])
            loss_den += float(m["loss_den"])
            if "correct5" in m:
                have_top5 = True
                correct5 += float(m["correct5"])
            if collect:
                # 'wrong' is the GLOBAL per-sample vector (replicated out of
                # the sharded step = all-gather over ICI); batch.indices is
                # the host-replicated global order — so every host can name
                # every misclassified sample, reference val_epoch's
                # all_gather capability (train.py:92) without the pickle.
                wrong = np.asarray(m["wrong"])
                ds = self.val_loader.dataset
                misclassified.extend(
                    ds.image_id(int(indices[pos]))
                    for pos in np.nonzero(wrong > 0.5)[0])
            if per_class:
                c = np.asarray(m["confusion"], np.float64)
                confusion = c if confusion is None else confusion + c
        for batch in self.val_loader.epoch(epoch):
            m = self.eval_step(self.state,
                               {k: batch[k] for k in ("image", "label", "mask")})
            _async_copy(m)
            pending.append((m, batch.indices if collect else None))
            if len(pending) > window:
                drain(*pending.pop(0))
        for item in pending:
            drain(*item)
        if collect:
            self.last_misclassified = misclassified
        score = 100.0 * correct / max(count, 1.0)
        val_loss = loss_num / max(loss_den, 1e-12)
        extra = {"n_misclassified": len(misclassified)} if collect else {}
        top5_msg = ""
        if have_top5:
            extra["val_top5"] = 100.0 * correct5 / max(count, 1.0)
            top5_msg = f"; Top-5 {extra['val_top5']:.4f}"
        if per_class and confusion is not None:
            # Exact global per-class accuracy: diagonal / true-class counts.
            # Scalars (balanced = mean per-class recall, and the worst
            # class) ride the normal logger; the full vector + confusion
            # matrix are non-scalar, so they go to sidecar files beside
            # metrics.jsonl.
            support = confusion.sum(axis=1)
            cls_acc = np.divide(np.diag(confusion), support,
                                out=np.zeros_like(support),
                                where=support > 0)
            present = support > 0
            if present.any():
                extra["val_balanced_acc"] = 100.0 * cls_acc[present].mean()
                extra["val_worst_class_acc"] = 100.0 * cls_acc[present].min()
            if self.logger.root is not None:
                # Per-epoch file: the off-diagonal structure at (say) the
                # best-checkpoint epoch must survive later epochs.
                np.save(os.path.join(self.logger.root,
                                     f"confusion_e{epoch}.npy"), confusion)
                with open(os.path.join(self.logger.root,
                                       "per_class.jsonl"), "a") as f:
                    f.write(json.dumps({
                        "epoch": epoch,
                        "acc": [round(100.0 * a, 2) for a in cls_acc],
                        "support": [int(s) for s in support]}) + "\n")
        host0_print(f"Epoch: {epoch}; Val Accuracy {score:.4f}{top5_msg}; "
                    f"Val Loss {val_loss:.4f}")
        self.logger.write(int(jax.device_get(self.state.step)),  # tpuic-ok: TPU101 epoch boundary
                          val_accuracy=score, val_loss=val_loss, **extra)
        _tm_publish("eval", epoch=epoch, accuracy=round(score, 4),
                    duration_s=round(time.perf_counter() - t_eval0, 3))
        return score

    # -- driver -------------------------------------------------------------
    def _do_rollback(self) -> int:
        """Restore the last good checkpoint after a non-finite streak
        (docs/robustness.md); returns the epoch to continue from.

        The restore goes through the integrity ladder, the skip streak is
        reset, and with run.rollback_rewarm_steps the LR re-enters its
        schedule on a linear ramp (a new optimizer transform — one retrace
        of the train step, the only recompile on any rollback path)."""
        self._rollback_pending = False
        self.rollbacks += 1
        t_rb0 = time.perf_counter()
        run = self.cfg.run
        if self.rollbacks > run.max_rollbacks:
            # NonRetryable: a supervisor restart would resume, diverge,
            # and land right back here — the poison half of the
            # exit-code contract (runtime/supervisor.py).
            from tpuic.runtime.supervisor import NonRetryableError
            raise NonRetryableError(
                f"non-finite rollback #{self.rollbacks} exceeds "
                f"run.max_rollbacks={run.max_rollbacks}: the run keeps "
                "diverging after restore — fix the data/LR instead of "
                "looping restore->diverge forever")
        # Commit any staged save FIRST: the most recent epoch's checkpoint
        # normally still sits in '{track}.new' (its commit rides the next
        # wait()), and probing newest_track() before committing would
        # spuriously report "nothing to roll back to".
        self.ckpt.wait()
        if self.ckpt.newest_track() is None:
            from tpuic.runtime.supervisor import NonRetryableError
            raise NonRetryableError(
                f"{run.skip_threshold} consecutive non-finite steps before "
                "any checkpoint existed — nothing to roll back to (the "
                "guard kept the state finite; lower the LR or check the "
                "data)")
        import jax.numpy as jnp
        self.state, epoch, restored_best = self.ckpt.restore_into(self.state)
        # 'best' on disk still holds its score; never let a rollback
        # resurrect a worse-looking history.
        self.best_score = max(self.best_score, restored_best)
        self.state = self.state.replace(skip_count=jnp.zeros((), jnp.int32))
        if run.rollback_rewarm_steps > 0:
            from tpuic.train.optimizer import make_optimizer, rewarm_scale
            steps = max(1, self.train_loader.steps_per_epoch())
            base_step = int(np.asarray(jax.device_get(self.state.step)))  # tpuic-ok: TPU101 rollback path, not steady state
            scale = rewarm_scale(base_step, run.rollback_rewarm_steps)
            self.state = self.state.replace(tx=make_optimizer(
                self.cfg.optim, steps, run.epochs, lr_scale=scale,
                global_batch=self.train_loader.global_batch))
            # The logged 'lr' metric must report what the optimizer now
            # APPLIES: fold the ramp into the metric schedule and rebuild
            # the step around it (one retrace — the same one the new tx
            # forces anyway). Composed onto the PRISTINE base schedule —
            # the optimizer rebuild above applies only the newest scale,
            # so stacking onto an already-scaled self.schedule (rollback
            # #2 inside rollback #1's ramp) would under-report the LR.
            from tpuic.train.optimizer import make_schedule
            base_sched = make_schedule(
                self.cfg.optim, steps, run.epochs,
                global_batch=self.train_loader.global_batch)
            self.schedule = lambda t: base_sched(t) * scale(t)
            self.train_step = make_train_step(
                self.cfg.optim, self.mcfg,
                self.mesh if self.mesh.size > 1 else None,
                lr_schedule=self.schedule, seed=self.cfg.run.seed,
                state_sharding=self.state_sharding)
            host0_print(f"[rollback] LR re-warming over "
                        f"{run.rollback_rewarm_steps} steps from step "
                        f"{base_step}")
        if self.state_sharding is not None:
            from tpuic.parallel.sharding import shard_state
            self.state = shard_state(self.state, self.state_sharding)
        self.start_epoch = epoch
        self.start_step = self._validated_start_step()
        host0_print(f"[rollback] restored '{self.ckpt.last_restore_rung}' — "
                    f"continuing at epoch {epoch} step {self.start_step} "
                    f"(rollback {self.rollbacks}/{run.max_rollbacks})")
        self._last_skip_streak = 0
        _tm_publish("rollback", epoch=epoch, rollback=self.rollbacks,
                    rung=self.ckpt.last_restore_rung,
                    duration_s=round(time.perf_counter() - t_rb0, 3))
        return epoch

    def _rebuild_for_replicas(self, replicas: int) -> None:
        """Re-form the in-process compute plane at a new data-parallel
        extent — the "recompile, don't respawn" half of elastic
        membership (docs/parallelism.md): a fresh mesh over the first
        ``replicas`` replica slots (runtime/mesh.py ``replica_mesh``),
        loaders re-sliced to the new global batch, schedule/optimizer
        rebuilt in the new step time (the batch-scaled LR rule sees the
        new global batch), state resharded onto the new mesh, and the
        step functions re-jitted. Only reached when this process owns a
        multi-replica mesh; an independent-rank fleet (mesh.size == 1)
        has no local mesh to shrink and re-forms state only."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        from tpuic.runtime.mesh import replica_mesh
        cfg = self.cfg
        self.mesh = replica_mesh(replicas, cfg.mesh)
        step_mesh = self.mesh if self.mesh.size > 1 else None
        global_batch = self._build_loaders()
        steps = max(1, self.train_loader.steps_per_epoch())
        self.schedule = make_schedule(cfg.optim, steps, cfg.run.epochs,
                                      global_batch=global_batch)
        self.state = self.state.replace(
            tx=make_optimizer(cfg.optim, steps, cfg.run.epochs,
                              global_batch=global_batch))
        self.state_sharding = None
        if step_mesh is not None and (cfg.mesh.fsdp or cfg.mesh.zero1 or (
                cfg.mesh.tensor_parallel and self.mesh.shape["model"] > 1)):
            from tpuic.parallel.sharding import shard_state, state_shardings
            self.state_sharding = state_shardings(
                self.state, self.mesh, tp=cfg.mesh.tensor_parallel,
                fsdp=cfg.mesh.fsdp, zero1=cfg.mesh.zero1)
            self.state = shard_state(self.state, self.state_sharding)
        else:
            # Replicated state must MOVE onto the shrunken mesh before
            # the re-jitted step sees it: a leaf still laid out over the
            # old R-device mesh fails the new program's device
            # assignment instead of resharding silently.
            repl = NamedSharding(self.mesh, P())
            self.state = jax.tree.map(
                lambda x: jax.device_put(x, repl), self.state)
        self._build_steps()

    def _do_reform(self, m) -> int:
        """Act on a 'degrade' membership transition (docs/parallelism.md
        "Elastic data parallelism"): shrink the local mesh if this
        process owns one, then restore the fleet-agreed step through the
        capped integrity ladder — all in-process (the pid is the proof;
        the elastic soak pins it). Returns the epoch to continue from."""
        self._reform_pending = None
        self.reforms += 1
        t0 = time.perf_counter()
        # Commit any staged save first (the rollback discipline): the
        # capped ladder must see every rung that exists.
        self.ckpt.wait()
        # Shrink the LOCAL mesh only when it IS the fleet (one process
        # hosting all m.world replicas — rank ids map 1:1 onto replica
        # slots, so "R' survivors" means "R' local replicas"). A
        # multi-host rank whose local mesh spans several replicas can't
        # equate fleet rank count with local extent (and which slots
        # survived isn't local knowledge): there the membership/restore
        # half applies and the mesh change rides the collective
        # re-initialization (docs/parallelism.md, CPU-fleet caveat).
        if (self.mesh.shape["data"] > 1
                and m.world == self.mesh.shape["data"]
                and 0 < len(m.active) < self.mesh.shape["data"]):
            self._rebuild_for_replicas(len(m.active))
        self.state, epoch, restored_best = self.ckpt.restore_into(
            self.state, resume_cap=m.resume_step)
        self.best_score = max(self.best_score, restored_best)
        if self.state.skip_count is not None:
            import jax.numpy as jnp
            self.state = self.state.replace(
                skip_count=jnp.zeros((), jnp.int32))
        if self.state_sharding is not None:
            from tpuic.parallel.sharding import shard_state
            self.state = shard_state(self.state, self.state_sharding)
        self.start_epoch = epoch
        self.start_step = self._validated_start_step()
        self._last_skip_streak = 0
        what = (f"fleet degraded to {len(m.active)}/{m.world} "
                f"(rank {m.rank} lost)" if m.reason == "degrade"
                else f"coalesced '{m.reason}' transition (a degrade came "
                     f"and went between polls; fleet at "
                     f"{len(m.active)}/{m.world})")
        host0_print(
            f"[elastic] membership v{m.version}: {what} — re-formed "
            f"in place from fleet-agreed step {m.resume_step} (rung "
            f"'{self.ckpt.last_restore_rung}'); continuing at epoch "
            f"{epoch} step {self.start_step}, no process restart")
        _tm_publish("reform", reason=m.reason, version=m.version,
                    active=list(m.active), resume_step=m.resume_step,
                    acted=True, epoch=epoch, rung=self.ckpt.last_restore_rung,
                    duration_s=round(time.perf_counter() - t0, 3))
        return epoch

    def fit(self, epochs: Optional[int] = None) -> float:
        from tpuic.runtime.preemption import agree
        epochs = epochs if epochs is not None else self.cfg.run.epochs
        best = self.best_score
        profiled = False
        if self.cfg.run.handle_preemption:
            self.preemption.install()
        goodput = self.telemetry.goodput
        goodput.start()
        # Supervised restart (runtime/supervisor.py): announce it as a
        # typed event. The downtime — previous child's death through
        # backoff, respawn, re-init, and checkpoint restore to here — is
        # charged to the goodput 'restart' bucket, so post-restart wall
        # time is classified instead of vanishing into 'other'.
        from tpuic.runtime.supervisor import restart_info
        rinfo = restart_info()
        if rinfo is not None:
            count, downtime_s = rinfo
            host0_print(f"[supervise] restart #{count}: resumed at epoch "
                        f"{self.start_epoch} step {self.start_step} after "
                        f"{downtime_s:.1f}s downtime")
            _tm_publish("restart", restart=count,
                        downtime_s=round(downtime_s, 3),
                        epoch=self.start_epoch, step_in_epoch=self.start_step)
        # Manifest-driven restart prewarm (docs/performance.md,
        # "Compiled-program registry"): when TPUIC_COMPILE_MANIFEST
        # names a manifest a PREVIOUS life left behind, compile-and-run
        # every step program now — against the persistent XLA cache —
        # so the steady state below dispatches with zero compiles.  A
        # corrupt manifest is refused loudly and training proceeds
        # unwarmed (correctness never depended on the prewarm).
        mpath = os.environ.get("TPUIC_COMPILE_MANIFEST", "")
        if mpath and getattr(self, "_manifest_preexisting", False):
            from tpuic.compiled import ManifestError
            try:
                self.prewarm(mpath)
            except ManifestError as e:
                host0_print(f"[compiled] refusing prewarm manifest: {e}")
            except FileNotFoundError:
                pass
        self._steps_exhausted = False
        try:
            epoch = self.start_epoch
            while epoch < epochs:
                if (self.cfg.run.profile_dir and not profiled
                        and epoch == self.start_epoch):
                    jax.profiler.start_trace(self.cfg.run.profile_dir)
                    profiled = True
                t0 = time.time()
                self.train_epoch(
                    epoch,
                    self.start_step if epoch == self.start_epoch else 0)
                if self._rollback_pending:
                    # Non-finite streak past skip_threshold: restore the
                    # last good checkpoint and continue from ITS epoch.
                    if profiled:
                        jax.profiler.stop_trace()
                        profiled = False
                    epoch = self._do_rollback()
                    best = self.best_score
                    continue
                if self._reform_pending is not None:
                    # Elastic degrade: a peer died; re-form in place from
                    # the fleet-agreed step and continue from ITS epoch.
                    if profiled:
                        jax.profiler.stop_trace()
                        profiled = False
                    epoch = self._do_reform(self._reform_pending)
                    best = self.best_score
                    continue
                if self._steps_exhausted:
                    # --steps budget reached mid-epoch: a smoke run's
                    # contract is N train steps + a goodput report, not
                    # a val pass over an unfinished epoch.
                    host0_print(f"[tpuic] step budget "
                                f"({self.cfg.run.max_steps}) reached in "
                                f"epoch {epoch}; stopping")
                    break
                # Epoch end is a common boundary: agree so a host whose
                # local SIGTERM missed the last in-epoch sync point doesn't
                # diverge from the others (val vs flush).
                if (self.cfg.run.handle_preemption
                        and agree(self.preemption.triggered)):
                    self.preemption.trigger()
                    if profiled:
                        jax.profiler.stop_trace()
                        profiled = False
                    # Grace windows are short: skip val and flush 'latest'.
                    # The save carries the completed step count so resume
                    # continues the epoch exactly where it stopped (no
                    # replayed prefix, no skipped tail). A boundary flush
                    # (done == total) records the full count: resume then
                    # trains ZERO remaining steps and runs the epoch's
                    # still-pending validation — so val/save_best are never
                    # lost to a signal landing between train and val.
                    done = self.last_epoch_steps
                    total = len(self.train_loader)
                    host0_print(f"[preempt] signal received during epoch "
                                f"{epoch} (step {done}/{total}); flushing "
                                f"latest and exiting")
                    gb, seed, n = self._loader_geometry()
                    self.ckpt.save_latest(
                        self.state, epoch, best, step_in_epoch=done,
                        global_batch=gb, data_seed=seed, data_len=n)
                    break
                score = self.val_epoch(epoch)
                host0_print(f"Epoch {epoch} took {time.time() - t0:.1f}s")
                if profiled:
                    jax.profiler.stop_trace()
                    profiled = False
                if score > best:
                    best = score
                    self.ckpt.save_best(self.state, epoch, best)
                self.ckpt.maybe_save_latest(self.state, epoch, best)
                # Epoch-cadence goodput: one console line plus a
                # 'goodput' event (TensorBoard fractions via the bus
                # sink, JSONL via --metrics-jsonl).
                host0_print(f"[goodput] {goodput.summary_line()}")
                _tm_publish("goodput", step=self.telemetry.steptime.last_step,
                            **goodput.report())
                epoch += 1
        finally:
            self.preemption.uninstall()
            # Commit any staged save on EVERY exit path: an exception
            # during epoch N+1 must not strand epoch N's fully-written
            # checkpoint in '{track}.new' (the restore ladder only reads
            # committed tracks).
            self.ckpt.wait()
            if self.telemetry.tracer is not None:
                self.telemetry.tracer.finish()
            if self.telemetry.profile is not None:
                # Final device-time analysis BEFORE the final goodput
                # event: the goodput publish drives the --prom-dump
                # refresh, which must see the finished waterfall
                # (finalize is idempotent; flush() backstops it).
                self.telemetry.profile.finalize()
            # Final goodput report — the run's wall-time ledger
            # (productive/input/compile/checkpoint/skip/rollback/eval;
            # CI asserts the buckets sum to ~100% of wall).
            host0_print(f"[goodput] {goodput.summary_line()}")
            _tm_publish("goodput", final=True,
                        step=self.telemetry.steptime.last_step,
                        **goodput.report())
            self.telemetry.flush()
        self.best_score = best
        return best
