"""Train state: params + batch_stats + optimizer state + step.

The reference's mutable training state is spread across the DDP module's
parameters, BN running stats buried in module buffers, replicated Adam state,
and Python-side ``start_epoch``/``best_score`` (train.py:127-150). Here it is
one immutable pytree, which is what makes sharding, donation, and
checkpointing uniform.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from flax import struct
from flax.core import unfreeze


class TrainState(struct.PyTreeNode):
    step: jnp.ndarray
    params: Any
    batch_stats: Any
    opt_state: optax.OptState
    apply_fn: Callable = struct.field(pytree_node=False)
    tx: optax.GradientTransformation = struct.field(pytree_node=False)
    # Exponential moving average of params (OptimConfig.ema_decay > 0);
    # None disables — an empty pytree subtree, so shardings, donation, and
    # checkpoints are unaffected when off.
    ema_params: Any = None
    # Consecutive non-finite (skipped) steps, maintained IN-GRAPH by the
    # train step's guard (OptimConfig.skip_nonfinite): 0 after every
    # applied update, +1 per skip. Living in the state keeps the streak
    # exact with zero extra host syncs — the Trainer reads it through the
    # same deferred metrics drain as loss, and rolls back past
    # RunConfig.skip_threshold. None on states built by older callers;
    # the guard then still skips, it just can't count streaks.
    skip_count: Any = None

    @property
    def inference_params(self):
        """The weights evaluation/inference should score: the EMA when the
        recipe maintains one, else the raw params. The single source of
        truth for eval_step, predict, and best-checkpoint selection."""
        return self.ema_params if self.ema_params is not None else self.params

    def apply_gradients(self, grads) -> "TrainState":
        updates, new_opt_state = self.tx.update(grads, self.opt_state, self.params)
        new_params = optax.apply_updates(self.params, updates)
        return self.replace(step=self.step + 1, params=new_params,
                            opt_state=new_opt_state)


def opt_state_bytes(state: TrainState) -> int:
    """GLOBAL byte size of the optimizer state (every array leaf's full
    logical extent) — the denominator of the ZeRO 1/R memory claim."""
    return sum(int(leaf.nbytes)
               for leaf in jax.tree_util.tree_leaves(state.opt_state)
               if hasattr(leaf, "nbytes"))


def opt_state_device_bytes(state: TrainState,
                           device: jax.Device) -> int:
    """Bytes of optimizer state RESIDENT on ``device`` — per-shard, not
    logical: a leaf sharded over the ``data`` axis (ZeRO-1,
    tpuic/parallel/sharding.py) charges ``nbytes / R`` here while a
    replicated leaf charges its full size. The measured quantity behind
    perf/elastic_zero.json (optimizer memory per replica ~ 1/R)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(state.opt_state):
        if not isinstance(leaf, jax.Array):
            continue
        for shard in leaf.addressable_shards:
            if shard.device == device:
                total += int(shard.data.nbytes)
    return total


def create_train_state(model, tx: optax.GradientTransformation, rng: jax.Array,
                       input_shape, train: bool = True,
                       ema: bool = False) -> TrainState:
    """Initialize params/batch_stats with a dummy batch of ``input_shape``.

    The batch dim is forced to 1: param shapes don't depend on it, and a
    global-batch-sized unsharded dummy would OOM device 0 at pod scale.
    ``ema=True`` seeds ema_params = params (no debias term needed).
    """
    dummy = jnp.zeros((1,) + tuple(input_shape[1:]), jnp.float32)
    # Init in train mode so branches that only exist then (inception aux head,
    # drop-path) create their params too; eval-only applies just ignore them.
    params_rng, dropout_rng = jax.random.split(rng)
    variables = model.init({"params": params_rng, "dropout": dropout_rng},
                           dummy, train=True)
    # Plain dicts throughout: model.apply(mutable=...) returns plain dicts in
    # current flax, and jit out_shardings prefix trees must match container
    # types exactly.
    params = unfreeze(variables.get("params", {}))
    batch_stats = unfreeze(variables.get("batch_stats", {}))
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats=batch_stats,
        opt_state=tx.init(params),
        apply_fn=model.apply,
        tx=tx,
        # A REAL copy: sharing params' buffers would double-donate them
        # under the jitted step's donate_argnums and wedge the executable.
        ema_params=(jax.tree.map(lambda x: jnp.array(x, copy=True), params)
                    if ema else None),
        skip_count=jnp.zeros((), jnp.int32),
    )
