"""Loss functions.

Reproduces torch ``nn.CrossEntropyLoss(weight=...)`` semantics exactly, since
the reference's loss is a weighted CE with a hard-coded 7-class imbalance
vector [3,3,10,1,4,4,5] (train.py:157-158): per-sample NLL scaled by the label
class weight, normalized by the *sum of the applied weights* (not the sample
count). The inception path adds ``loss1 + 0.4 * loss2`` over main and aux
logits (train.py:48-52).

A validity mask supports SPMD's static shapes: padded samples contribute zero
weight, so global loss over a padded final batch is exact.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def weighted_cross_entropy(logits: jnp.ndarray, labels: jnp.ndarray,
                           class_weights: Optional[jnp.ndarray] = None,
                           mask: Optional[jnp.ndarray] = None,
                           label_smoothing: float = 0.0) -> jnp.ndarray:
    """Mean weighted CE over valid samples; torch-compatible normalization.

    logits [B, C] (any float dtype; upcast to f32), labels [B] int,
    class_weights [C] or None, mask [B] (1=valid) or None.
    """
    logits = logits.astype(jnp.float32)
    num_classes = logits.shape[-1]
    logp = jax.nn.log_softmax(logits, axis=-1)
    # one_hot (iota comparison) rather than eye()[labels]: a gather indexed by
    # the batch-sharded label array would force sharding-unfriendly lowering;
    # the comparison form stays elementwise and fuses.
    onehot = jax.nn.one_hot(labels, num_classes, dtype=jnp.float32)
    if label_smoothing > 0.0:
        onehot = onehot * (1.0 - label_smoothing) + label_smoothing / num_classes
    nll = -jnp.sum(onehot * logp, axis=-1)  # [B]
    if class_weights is not None:
        cw = jnp.asarray(class_weights, jnp.float32)
        w = jnp.sum(jax.nn.one_hot(labels, num_classes, dtype=jnp.float32)
                    * cw[None, :], axis=-1)
    else:
        w = jnp.ones_like(nll)
    if mask is not None:
        w = w * mask.astype(jnp.float32)
    # torch weighted-CE normalizer: sum of applied weights.
    return jnp.sum(w * nll) / jnp.maximum(jnp.sum(w), 1e-12)


LOSS_IMPLS = ("reference", "fused")


def classification_loss(outputs, labels, *, class_weights=None, mask=None,
                        aux_weight: float = 0.4,
                        label_smoothing: float = 0.0,
                        impl: str = "reference", mesh=None) -> jnp.ndarray:
    """Main loss, plus the inception aux term when outputs is a tuple.

    Reference train.py:48-56: ``loss = loss_fn(out1,l) + 0.4*loss_fn(out2,l)``
    in train mode, plain CE otherwise. ``impl='fused'`` routes through the
    Pallas kernel (tpuic/kernels/cross_entropy.py), same numerics; pass
    ``mesh`` so the kernel stays batch-parallel under a sharded jit.
    """
    if impl not in LOSS_IMPLS:
        raise ValueError(f"unknown loss impl '{impl}'; available: {LOSS_IMPLS}")
    if impl == "fused":
        from tpuic.kernels import fused_weighted_cross_entropy

        def ce(logits):
            return fused_weighted_cross_entropy(logits, labels, class_weights,
                                                mask, label_smoothing, 128,
                                                None, mesh)
    else:
        def ce(logits):
            return weighted_cross_entropy(logits, labels, class_weights, mask,
                                          label_smoothing)

    if isinstance(outputs, tuple):
        logits, aux_logits = outputs
        return ce(logits) + aux_weight * ce(aux_logits)
    return ce(outputs)
