"""Learning-rate schedules.

``multistep_schedule`` reproduces torch ``MultiStepLR(milestones=[50,80],
gamma=0.5)`` stepped once per epoch (reference train.py:156, 166), expressed as
a per-step optax schedule (the jitted step owns the LR, so the schedule is a
pure function of the global step — no Python-side ``scheduler.step()``).
Warmup + cosine covers the large-batch LARS config (BASELINE.md config 5,
Goyal-style linear warmup).
"""

from __future__ import annotations

from typing import Sequence

import optax


def multistep_schedule(base_lr: float, milestones: Sequence[int],
                       gamma: float, steps_per_epoch: int) -> optax.Schedule:
    """lr * gamma^(number of milestone epochs passed)."""
    boundaries = {int(m) * steps_per_epoch: gamma for m in milestones}
    return optax.piecewise_constant_schedule(base_lr, boundaries)


def warmup_cosine_schedule(base_lr: float, warmup_epochs: int, total_epochs: int,
                           steps_per_epoch: int, end_lr: float = 0.0) -> optax.Schedule:
    warmup_steps = warmup_epochs * steps_per_epoch
    total_steps = max(total_epochs * steps_per_epoch, warmup_steps + 1)
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=base_lr, warmup_steps=max(warmup_steps, 1),
        decay_steps=total_steps, end_value=end_lr)


def constant_schedule(base_lr: float) -> optax.Schedule:
    return optax.constant_schedule(base_lr)
