"""Learning-rate schedules.

``multistep_schedule`` reproduces torch ``MultiStepLR(milestones=[50,80],
gamma=0.5)`` stepped once per epoch (reference train.py:156, 166), expressed as
a per-step optax schedule (the jitted step owns the LR, so the schedule is a
pure function of the global step — no Python-side ``scheduler.step()``).
Warmup + cosine covers the large-batch LARS config (BASELINE.md config 5,
Goyal-style linear warmup).
"""

from __future__ import annotations

from typing import Sequence

import optax


def multistep_schedule(base_lr: float, milestones: Sequence[int],
                       gamma: float, steps_per_epoch: int) -> optax.Schedule:
    """lr * gamma^(number of milestone epochs passed)."""
    boundaries = {int(m) * steps_per_epoch: gamma for m in milestones}
    return optax.piecewise_constant_schedule(base_lr, boundaries)


def warmup_cosine_schedule(base_lr: float, warmup_epochs: int, total_epochs: int,
                           steps_per_epoch: int, end_lr: float = 0.0) -> optax.Schedule:
    warmup_steps = warmup_epochs * steps_per_epoch
    total_steps = max(total_epochs * steps_per_epoch, warmup_steps + 1)
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=base_lr, warmup_steps=max(warmup_steps, 1),
        decay_steps=total_steps, end_value=end_lr)


def constant_schedule(base_lr: float) -> optax.Schedule:
    return optax.constant_schedule(base_lr)


def batch_scaled_warmup_schedule(base_lr: float, global_batch: int,
                                 base_batch: int, warmup_epochs: int,
                                 steps_per_epoch: int,
                                 main: optax.Schedule) -> optax.Schedule:
    """Goyal linear-scaling warmup (arXiv:1706.02677; the ingredient every
    15-minute-ImageNet recipe shares, arXiv:1711.04325): when the global
    batch is k× the reference batch the stable peak LR is k×base_lr —
    but STARTING there diverges, so the first ``warmup_epochs`` ramp
    linearly from ``base_lr`` (the small-batch LR, a known-safe point)
    up to the scaled peak. After the ramp, ``main`` — the recipe's
    normal schedule built at the scaled peak — takes over.

    Pure function of the optimizer step (traced into the jitted step
    like every schedule here); ``main`` is also evaluated during warmup
    (jnp.where selects), so it must be finite there."""
    import jax.numpy as jnp

    scale = float(global_batch) / float(base_batch)
    peak = base_lr * scale
    warmup_steps = max(1, int(warmup_epochs) * int(steps_per_epoch))

    def schedule(t):
        frac = jnp.clip(t / warmup_steps, 0.0, 1.0)
        ramp = base_lr + (peak - base_lr) * frac
        return jnp.where(t < warmup_steps, ramp, main(t))

    return schedule
