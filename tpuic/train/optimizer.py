"""Optimizers.

The reference uses replicated Adam(lr=0.5e-5) on every rank (train.py:127);
here the optimizer is an optax transform applied inside the sharded jitted
step (the update math itself is compiled and, under TP/FSDP-style param
sharding, computed shard-locally — no redundant full-replica update).
LARS covers BASELINE.md config 5 (large-batch ResNet-50).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

from tpuic.config import OptimConfig
from tpuic.train import schedule as sched


def make_schedule(cfg: OptimConfig, steps_per_epoch: int, total_epochs: int,
                  global_batch: int = 0) -> optax.Schedule:
    """The config's LR schedule in optimizer-step time.

    ``global_batch`` + ``cfg.base_batch_size`` engage the Goyal
    linear-scaling rule (train/schedule.py
    ``batch_scaled_warmup_schedule``): peak LR scaled by
    global_batch/base_batch, reached by a linear ramp from the unscaled
    base LR over ``warmup_epochs``, with the config's normal schedule
    (milestones / cosine / constant) built at the scaled peak taking
    over after the ramp. With base_batch_size unset (the default) the
    behavior is bitwise the old one."""
    if cfg.base_batch_size and global_batch:
        peak = cfg.learning_rate * global_batch / cfg.base_batch_size
        if cfg.milestones and not cfg.warmup_epochs:
            main = sched.multistep_schedule(peak, cfg.milestones,
                                            cfg.gamma, steps_per_epoch)
        elif cfg.warmup_epochs > 0:
            main = sched.warmup_cosine_schedule(peak, cfg.warmup_epochs,
                                                total_epochs,
                                                steps_per_epoch)
        else:
            main = sched.constant_schedule(peak)
        return sched.batch_scaled_warmup_schedule(
            cfg.learning_rate, global_batch, cfg.base_batch_size,
            max(1, cfg.warmup_epochs), steps_per_epoch, main)
    if cfg.warmup_epochs > 0:
        return sched.warmup_cosine_schedule(cfg.learning_rate, cfg.warmup_epochs,
                                            total_epochs, steps_per_epoch)
    if cfg.milestones:
        return sched.multistep_schedule(cfg.learning_rate, cfg.milestones,
                                        cfg.gamma, steps_per_epoch)
    return sched.constant_schedule(cfg.learning_rate)


def rewarm_scale(start_step: int, rewarm_steps: int):
    """LR scale factor ramping linearly 1/N -> 1 over ``rewarm_steps``
    optimizer steps starting at ``start_step``, then 1 forever.

    The Trainer multiplies this into the schedule after a non-finite
    rollback (RunConfig.rollback_rewarm_steps): the run re-enters its
    schedule gently instead of slamming the restored weights with the full
    LR that just produced the divergence (loss-spike hygiene from the
    large-batch literature, arXiv:1711.04325)."""
    n = max(1, int(rewarm_steps))
    s0 = int(start_step)

    def scale(t):
        import jax.numpy as jnp
        return jnp.clip((t - s0 + 1) / n, 1.0 / n, 1.0)

    return scale


class FusedLarsState(NamedTuple):
    """count: updates applied (the schedule clock); trace: momentum."""
    count: jnp.ndarray
    trace: Any


class FusedLambState(NamedTuple):
    """count: updates applied (schedule + Adam debias clock); mu/nu: the
    f32 Adam moments."""
    count: jnp.ndarray
    mu: Any
    nu: Any


def _lr_at(learning_rate, count):
    return (learning_rate(count) if callable(learning_rate)
            else learning_rate)


def fused_lars(learning_rate, weight_decay: float = 0.0,
               trust_coefficient: float = 0.001, momentum: float = 0.9,
               impl: Optional[str] = None) -> optax.GradientTransformation:
    """optax.lars semantics as ONE fused pass per leaf
    (tpuic/kernels/optimizer_update.py): update order wd -> trust -> -lr
    -> momentum trace, trajectory-pinned against optax.lars in
    tests/test_fused_optimizer.py. A real optax.GradientTransformation,
    so grad-clip / freeze / MultiSteps wrappers compose unchanged."""
    from tpuic.kernels.optimizer_update import lars_leaf_update

    def init_fn(params):
        return FusedLarsState(count=jnp.zeros([], jnp.int32),
                              trace=jax.tree.map(jnp.zeros_like, params))

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError("fused_lars needs params (trust ratio + wd)")
        lr = _lr_at(learning_rate, state.count)
        with jax.named_scope("fused_lars"):
            # The new trace IS the update (optax.trace applies momentum
            # after lr scaling), so one tree pass yields both.
            new_trace = jax.tree.map(
                lambda g, w, m: lars_leaf_update(
                    w, g, m, lr=lr, weight_decay=weight_decay,
                    trust_coefficient=trust_coefficient,
                    momentum=momentum, impl=impl),
                updates, params, state.trace)
        return new_trace, FusedLarsState(count=state.count + 1,
                                         trace=new_trace)

    return optax.GradientTransformation(init_fn, update_fn)


def fused_lamb(learning_rate, b1: float = 0.9, b2: float = 0.999,
               eps: float = 1e-6, weight_decay: float = 0.0,
               impl: Optional[str] = None) -> optax.GradientTransformation:
    """optax.lamb semantics with the Adam-moment + decayed-direction pass
    fused per leaf (tpuic/kernels/optimizer_update.py); the trust-ratio
    norms and the -lr rescale are scalar epilogues XLA folds into the
    apply-updates add. Trajectory-pinned against optax.lamb."""
    from tpuic.kernels.optimizer_update import lamb_leaf_update

    def init_fn(params):
        zeros = lambda: jax.tree.map(  # noqa: E731
            lambda p: jnp.zeros_like(p, jnp.float32), params)
        return FusedLambState(count=jnp.zeros([], jnp.int32),
                              mu=zeros(), nu=zeros())

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError("fused_lamb needs params (trust ratio + wd)")
        lr = _lr_at(learning_rate, state.count)
        gs = jax.tree.leaves(updates)
        treedef = jax.tree.structure(updates)
        ws = jax.tree.leaves(params)
        ms = jax.tree.leaves(state.mu)
        vs = jax.tree.leaves(state.nu)
        with jax.named_scope("fused_lamb"):
            outs = [lamb_leaf_update(w, g, m, v, state.count, lr=lr, b1=b1,
                                     b2=b2, eps=eps,
                                     weight_decay=weight_decay, impl=impl)
                    for g, w, m, v in zip(gs, ws, ms, vs)]
        upd = jax.tree.unflatten(treedef, [o[0] for o in outs])
        mu = jax.tree.unflatten(treedef, [o[1] for o in outs])
        nu = jax.tree.unflatten(treedef, [o[2] for o in outs])
        return upd, FusedLambState(count=state.count + 1, mu=mu, nu=nu)

    return optax.GradientTransformation(init_fn, update_fn)


def make_optimizer(cfg: OptimConfig, steps_per_epoch: int = 1,
                   total_epochs: int = 100,
                   lr_scale=None,
                   global_batch: int = 0) -> optax.GradientTransformation:
    # Under gradient accumulation the inner transform's schedule counter
    # advances once per REAL update (1 in K micro-steps), so map it back to
    # micro-step time: lr(t_real) = micro_schedule(t_real * K). Exact for
    # any K/steps_per_epoch combination (dividing steps_per_epoch by K
    # would floor-drift milestones on small datasets), and identical to
    # the Trainer's micro-step logging schedule in data time.
    k = max(1, cfg.grad_accum_steps)
    base = make_schedule(cfg, steps_per_epoch, total_epochs,
                         global_batch=global_batch)
    if lr_scale is not None:
        # Multiplicative override in MICRO-step time (state.step), e.g.
        # rewarm_scale after a rollback; composed before the accumulation
        # remap so both see the same clock.
        micro = lambda t, b=base: b(t) * lr_scale(t)  # noqa: E731
    else:
        micro = base
    lr = micro if k == 1 else (lambda t: micro(t * k))
    name = cfg.optimizer.lower()
    if name == "adam":
        tx = optax.adam(lr)
        if cfg.weight_decay:
            tx = optax.adamw(lr, weight_decay=cfg.weight_decay)
    elif name == "lars":
        # Layer-wise Adaptive Rate Scaling (You et al., arXiv:1708.03888;
        # the BASELINE.md config-5 / 15-minute-ImageNet optimizer): each
        # layer's update is rescaled by the trust ratio
        # eta * ||w|| / (||g|| + wd * ||w||), so layers whose gradients
        # are large relative to their weights can't blow up at
        # large-batch LRs. Golden-value-pinned against an independent
        # numpy reference in tests/test_optimizer.py.
        if cfg.fused_optimizer:
            tx = fused_lars(lr, weight_decay=cfg.weight_decay,
                            trust_coefficient=cfg.lars_trust_coefficient,
                            momentum=cfg.lars_momentum)
        else:
            tx = optax.lars(lr, weight_decay=cfg.weight_decay,
                            trust_coefficient=cfg.lars_trust_coefficient,
                            momentum=cfg.lars_momentum)
    elif name == "lamb":
        # LAMB (You et al., arXiv:1904.00962): the Adam-flavored sibling
        # — Adam moments first, then the per-layer trust ratio
        # ||w|| / ||adam_update + wd * w|| rescales each layer's step.
        # The large-batch recipe for attention models (ViT) where plain
        # LARS underperforms; golden-pinned next to LARS.
        if cfg.fused_optimizer:
            tx = fused_lamb(lr, b1=cfg.lamb_b1, b2=cfg.lamb_b2,
                            eps=cfg.lamb_eps, weight_decay=cfg.weight_decay)
        else:
            tx = optax.lamb(lr, b1=cfg.lamb_b1, b2=cfg.lamb_b2,
                            eps=cfg.lamb_eps, weight_decay=cfg.weight_decay)
    elif name == "sgd":
        tx = optax.sgd(lr, momentum=0.9)
        if cfg.weight_decay:
            tx = optax.chain(optax.add_decayed_weights(cfg.weight_decay), tx)
    else:
        raise ValueError(f"unknown optimizer '{cfg.optimizer}'")
    if cfg.grad_clip_norm:
        tx = optax.chain(optax.clip_by_global_norm(cfg.grad_clip_norm), tx)
    if cfg.freeze_backbone:
        # Head-only fine-tuning (companion to --init-from): backbone
        # params receive zero updates via set_to_zero; only the MLP head
        # (and any other non-backbone scope) trains. NOT optax.masked —
        # masked leaves updates outside the mask UNTOUCHED (raw grads
        # would flow into apply_updates). Note BN running statistics
        # still update in train mode — freeze covers gradients, not
        # stats (torch requires_grad_(False) semantics).
        def _labels(params):
            return {k: jax.tree.map(
                        lambda _, lab=("freeze" if k == "backbone"
                                       else "train"): lab, v)
                    for k, v in params.items()}
        tx = optax.multi_transform(
            {"train": tx, "freeze": optax.set_to_zero()}, _labels)
    if cfg.grad_accum_steps > 1:
        # Gradient accumulation: K micro-steps average their grads before
        # one real update — the K-x-larger effective batch when it doesn't
        # fit in HBM (the reference can only shrink its per-GPU batch,
        # train.py:30). optax.MultiSteps keeps the accumulator inside
        # opt_state, so it shards/checkpoints with everything else.
        tx = optax.MultiSteps(tx, every_k_schedule=cfg.grad_accum_steps)
    return tx
