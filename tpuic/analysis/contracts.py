"""CTR — cross-artifact contract checks (docs/analysis.md).

Code, registry, and docs drift apart silently: a new event kind that
never gets a schema row, a prom row a dashboard can't look up, an exit
code the supervisor honors but the runbook doesn't mention.  These
rules re-derive each contract from the AST on every run:

- **CTR101 event-kind-contract**: every ``*.publish("kind", ...)`` call
  site's kind is registered in ``EVENT_KINDS``
  (tpuic/telemetry/events.py), and every registered kind has a schema
  row (``| `kind` | ... |``) in docs/observability.md.  Wrapper
  resolution: a call whose callee resolves to a project def forwards
  its first argument as a kind only when that parameter is literally
  named ``kind`` (``Router._publish(self, kind, ...)``); a wrapper with
  its own vocabulary (``RolloutDriver._publish(self, action, ...)``)
  is not a kind site — the fixed kind its body publishes is.
- **CTR102 prom-row-contract**: every metric row name emitted by
  tpuic/telemetry/prom.py appears in docs/observability.md.  Row names
  are extracted structurally — a row is a 5-tuple whose TYPE element is
  ``"gauge"``/``"counter"``; f-string and loop-variable names are
  expanded from the literal tuples they iterate (a name the extractor
  cannot resolve statically is itself a finding: emitted names must
  stay statically enumerable).
- **CTR103 exit-code-contract**: the supervisor exit-code constants
  (``EXIT_* = <int>`` in runtime/supervisor.py) are pairwise distinct,
  never shadowed with a different value in runtime/gang.py, never used
  as raw integer literals in ``sys.exit()``/``SystemExit`` in either
  module, and each nonzero code's number and constant name both appear
  in docs/robustness.md (the supervision contract table / prose).

The pass anchors on the canonical artifacts by path suffix; a scan tree
without them (a test fixture dir) simply runs the subset it can see.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from tpuic.analysis.callgraph import FuncInfo, ModuleInfo, Project, dotted
from tpuic.analysis.core import Finding, Severity

_EVENTS_SUFFIX = "tpuic/telemetry/events.py"
_PROM_SUFFIX = "tpuic/telemetry/prom.py"
_SUP_SUFFIX = "tpuic/runtime/supervisor.py"
_GANG_SUFFIX = "tpuic/runtime/gang.py"


def _docs_dir(anchor_path: str) -> str:
    """<repo>/docs for an anchor like <repo>/tpuic/telemetry/events.py."""
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(anchor_path))))
    return os.path.join(root, "docs")


def _read(path: str) -> Optional[str]:
    try:
        with open(path, encoding="utf-8") as fh:
            return fh.read()
    except OSError:
        return None


# -- CTR101 -------------------------------------------------------------
def _event_kinds(mod: ModuleInfo) -> Optional[List[Tuple[str, int]]]:
    """(kind, lineno) for every entry of the EVENT_KINDS tuple."""
    if mod.tree is None:
        return None
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "EVENT_KINDS"
                for t in node.targets) \
                and isinstance(node.value, (ast.Tuple, ast.List)):
            return [(e.value, e.lineno) for e in node.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)]
    return None


def _publish_kind_sites(project: Project
                        ) -> List[Tuple[str, int, str, FuncInfo]]:
    """(kind, lineno, path, publisher) for every statically-known
    publish kind in the project."""
    out: List[Tuple[str, int, str, FuncInfo]] = []
    for fi in project.funcs():
        for call in fi.calls:
            d = dotted(call.func)
            if d is None or not d.split(".")[-1].endswith("publish"):
                continue
            # Resolve through wrappers: a project def forwards a kind
            # only when its first non-self parameter is named 'kind'.
            resolved = project.resolve_call(fi, call)
            if not resolved and isinstance(call.func, ast.Attribute) \
                    and isinstance(call.func.value, ast.Name) \
                    and call.func.value.id == "self" and fi.cls:
                meth = fi.module.classes.get(fi.cls, {}).get(
                    call.func.attr)
                resolved = [meth] if meth is not None else []
            if resolved:
                params = resolved[0].params()
                if params and params[0] == "self":
                    params = params[1:]
                if not params or params[0] != "kind":
                    continue  # wrapper with its own vocabulary
            kind_expr: Optional[ast.AST] = None
            if call.args:
                kind_expr = call.args[0]
            else:
                for kw in call.keywords:
                    if kw.arg == "kind":
                        kind_expr = kw.value
            if isinstance(kind_expr, ast.Constant) \
                    and isinstance(kind_expr.value, str):
                out.append((kind_expr.value, call.lineno,
                            fi.module.path, fi))
    return out


def _ctr101(project: Project) -> List[Finding]:
    events = project.module_ending(_EVENTS_SUFFIX)
    if events is None:
        return []
    kinds = _event_kinds(events)
    if kinds is None:
        return [Finding("CTR101", Severity.ERROR, events.path, 1,
                        "EVENT_KINDS tuple not found (or not a literal "
                        "tuple of strings) — the event-kind contract "
                        "cannot be checked")]
    registered = {k for k, _ in kinds}
    findings: List[Finding] = []
    for kind, line, path, fi in _publish_kind_sites(project):
        if kind not in registered and not fi.allowlisted("CTR101"):
            findings.append(Finding(
                "CTR101", Severity.ERROR, path, line,
                f"published event kind '{kind}' is not registered in "
                f"EVENT_KINDS ({events.path}) — register it and add "
                f"its schema row to docs/observability.md"))
    doc = _read(os.path.join(_docs_dir(events.path), "observability.md"))
    if doc is not None:
        for kind, line in kinds:
            if not re.search(rf"^\|\s*`{re.escape(kind)}`\s*\|", doc,
                             re.MULTILINE):
                findings.append(Finding(
                    "CTR101", Severity.ERROR, events.path, line,
                    f"event kind '{kind}' has no schema row in "
                    f"docs/observability.md (expected a table row "
                    f"'| `{kind}` | ... |')",
                    fkey=f"ctr101:doc:{kind}"))
    return findings


# -- CTR102 -------------------------------------------------------------
def _loop_expansions(fn_node: ast.AST) -> Dict[int, List[str]]:
    """id(loop-variable Name binding) -> the literal strings it ranges
    over: for a ``for field, ... in (("a", ...), ("b", ...)):`` loop,
    the target element's position indexes each literal tuple."""
    out: Dict[int, List[str]] = {}
    for node in ast.walk(fn_node):
        if not isinstance(node, ast.For) \
                or not isinstance(node.iter, (ast.Tuple, ast.List)):
            continue
        targets: List[ast.Name] = []
        if isinstance(node.target, ast.Name):
            targets = [node.target]
        elif isinstance(node.target, ast.Tuple):
            targets = [e for e in node.target.elts
                       if isinstance(e, ast.Name)]
        positions = {t.id: i for i, t in enumerate(
            node.target.elts if isinstance(node.target, ast.Tuple)
            else [node.target]) if isinstance(t, ast.Name)}
        for name, pos in positions.items():
            vals: List[str] = []
            ok = True
            for elt in node.iter.elts:
                item = elt
                if isinstance(elt, (ast.Tuple, ast.List)):
                    item = (elt.elts[pos] if pos < len(elt.elts)
                            else None)
                elif pos != 0:
                    ok = False
                    break
                if isinstance(item, ast.Constant) \
                        and isinstance(item.value, str):
                    vals.append(item.value)
                else:
                    ok = False
                    break
            if ok and vals:
                out[hash((id(node), name))] = vals
                out.setdefault(name, vals)  # by-name fallback
    return out


def _row_names(mod: ModuleInfo) -> Tuple[Set[str], List[Tuple[int, str]]]:
    """(statically-known row names, unresolvable sites) over prom.py.

    A row is any 5-element tuple whose third element is the literal
    metric type ``"gauge"``/``"counter"`` — the shape every
    ``rows.append((name, value, type, help, labels))`` site shares."""
    names: Set[str] = set()
    bad: List[Tuple[int, str]] = []
    if mod.tree is None:
        return names, bad
    for fn in ast.walk(mod.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        expand = _loop_expansions(fn)
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Tuple)
                    and len(node.elts) == 5
                    and isinstance(node.elts[2], ast.Constant)
                    and node.elts[2].value in ("gauge", "counter")):
                continue
            head = node.elts[0]
            if isinstance(head, ast.Constant) \
                    and isinstance(head.value, str):
                names.add(head.value)
            elif isinstance(head, ast.Name) and head.id in expand:
                names.update(expand[head.id])
            elif isinstance(head, ast.JoinedStr):
                parts: List[List[str]] = []
                ok = True
                for v in head.values:
                    if isinstance(v, ast.Constant):
                        parts.append([str(v.value)])
                    elif isinstance(v, ast.FormattedValue) \
                            and isinstance(v.value, ast.Name) \
                            and v.value.id in expand:
                        parts.append(expand[v.value.id])
                    else:
                        ok = False
                        break
                if ok:
                    combos = [""]
                    for p in parts:
                        combos = [c + s for c in combos for s in p]
                    names.update(combos)
                else:
                    bad.append((node.lineno, ast.unparse(head)
                                if hasattr(ast, "unparse")
                                else "<f-string>"))
            else:
                bad.append((node.lineno,
                            ast.unparse(head) if hasattr(ast, "unparse")
                            else "<expr>"))
    return names, bad


def _ctr102(project: Project) -> List[Finding]:
    prom = project.module_ending(_PROM_SUFFIX)
    if prom is None:
        return []
    names, bad = _row_names(prom)
    findings: List[Finding] = []
    for line, expr in bad:
        findings.append(Finding(
            "CTR102", Severity.WARNING, prom.path, line,
            f"metric row name {expr!r} is not statically enumerable — "
            f"the docs contract can only be checked for literal (or "
            f"literal-loop-expanded) names"))
    doc = _read(os.path.join(_docs_dir(prom.path), "observability.md"))
    if doc is None:
        return findings
    for name in sorted(names):
        if name not in doc:
            findings.append(Finding(
                "CTR102", Severity.WARNING, prom.path, 1,
                f"prom row '{name}' is emitted but never mentioned in "
                f"docs/observability.md — add it to the metric "
                f"reference",
                fkey=f"ctr102:{name}"))
    return findings


# -- CTR103 -------------------------------------------------------------
def _exit_constants(mod: ModuleInfo) -> Dict[str, Tuple[int, int]]:
    """name -> (value, lineno) for module-level ``EXIT_* = <int>``."""
    out: Dict[str, Tuple[int, int]] = {}
    if mod.tree is None:
        return out
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) \
                and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id.startswith("EXIT_") \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, int):
            out[node.targets[0].id] = (node.value.value, node.lineno)
    return out


def _raw_exit_literals(mod: ModuleInfo,
                       values: Set[int]) -> List[Tuple[int, int]]:
    """(lineno, value) of sys.exit(<raw int>)/SystemExit(<raw int>)
    calls using a contract value as a bare literal."""
    out: List[Tuple[int, int]] = []
    if mod.tree is None:
        return out
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) \
                and dotted(node.func) in ("sys.exit", "exit",
                                          "SystemExit") \
                and node.args \
                and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, int) \
                and node.args[0].value in values \
                and node.args[0].value != 0:
            out.append((node.lineno, node.args[0].value))
    return out


def _ctr103(project: Project) -> List[Finding]:
    sup = project.module_ending(_SUP_SUFFIX)
    if sup is None:
        return []
    consts = _exit_constants(sup)
    findings: List[Finding] = []
    if not consts:
        return [Finding("CTR103", Severity.ERROR, sup.path, 1,
                        "no EXIT_* integer constants found in the "
                        "supervisor — the exit-code contract cannot "
                        "be checked")]
    by_value: Dict[int, List[str]] = {}
    for name, (val, _line) in consts.items():
        by_value.setdefault(val, []).append(name)
    for val, names in sorted(by_value.items()):
        if len(names) > 1:
            line = consts[names[0]][1]
            findings.append(Finding(
                "CTR103", Severity.ERROR, sup.path, line,
                f"exit-code constants {', '.join(sorted(names))} share "
                f"the value {val} — the supervisor cannot classify the "
                f"child's death"))
    gang = project.module_ending(_GANG_SUFFIX)
    if gang is not None:
        for name, (val, line) in _exit_constants(gang).items():
            if name in consts and consts[name][0] != val:
                findings.append(Finding(
                    "CTR103", Severity.ERROR, gang.path, line,
                    f"{name} redefined as {val} here but "
                    f"{consts[name][0]} in the supervisor — one "
                    f"contract, one definition: import it"))
    values = {v for v, _ in consts.values()}
    for mod in (sup, gang):
        if mod is None:
            continue
        for line, val in _raw_exit_literals(mod, values):
            names = "/".join(sorted(by_value[val]))
            findings.append(Finding(
                "CTR103", Severity.ERROR, mod.path, line,
                f"raw exit literal {val} — use the {names} constant so "
                f"the contract has one definition"))
    doc = _read(os.path.join(_docs_dir(sup.path), "robustness.md"))
    if doc is not None:
        for name, (val, line) in sorted(consts.items()):
            if val == 0:
                continue
            if not re.search(rf"\b{val}\b", doc):
                findings.append(Finding(
                    "CTR103", Severity.ERROR, sup.path, line,
                    f"exit code {val} ({name}) does not appear in "
                    f"docs/robustness.md — the supervision contract "
                    f"table must cover it",
                    fkey=f"ctr103:value:{val}"))
            elif name not in doc:
                findings.append(Finding(
                    "CTR103", Severity.ERROR, sup.path, line,
                    f"constant {name} (= {val}) is never named in "
                    f"docs/robustness.md — name it where the code is "
                    f"documented so grep finds the contract",
                    fkey=f"ctr103:name:{name}"))
    return findings


def run_ctr(project: Project) -> List[Finding]:
    return _ctr101(project) + _ctr102(project) + _ctr103(project)
