"""Linter core: findings, suppression parsing, file walking, the driver.

A finding is (rule, severity, path, line, message) plus the stripped
source line it anchors to — the anchor text (not the line *number*) is
what the baseline fingerprints, so unrelated edits above a legacy
finding don't churn the baseline.

Suppression syntax (docs/analysis.md): an inline comment on the
offending line

    jax.device_get(handles)  # tpuic-ok: TPU101 deferred drain site

silences the named rule(s) for that line; multiple IDs separate with
commas (``# tpuic-ok: TPU101, TPU501 reason...``).  A bare
``# tpuic-ok:`` with no rule ID silences every rule on the line (use
sparingly — reviewers grep for these).  Suppressions are the
*allowlist* mechanism the host-sync rule's "deferred-drain sites" refer
to: the sync is intentional, the comment says why, and the linter keeps
every other line honest.
"""

from __future__ import annotations

import dataclasses
import enum
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple


class Severity(enum.IntEnum):
    INFO = 0
    WARNING = 1
    ERROR = 2

    def __str__(self) -> str:  # 'error', not 'Severity.ERROR'
        return self.name.lower()


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str            # e.g. 'TPU101' / 'CONC101'
    severity: Severity
    path: str            # as given to the linter (relative in CI)
    line: int            # 1-based
    message: str
    anchor: str = ""     # stripped source text of the offending line
    # Project-level findings (a lock cycle spans files) set ``fkey`` to
    # a stable structural key (e.g. the sorted edge set); the baseline
    # fingerprints on it instead of path|anchor so unrelated edits
    # don't churn the entry.
    fkey: str = ""

    @property
    def family(self) -> str:
        """'lint' for TPU rules, else the lowercased rule prefix —
        matches the ``--passes`` vocabulary."""
        prefix = self.rule.rstrip("0123456789")
        return "lint" if prefix == "TPU" else prefix.lower()

    def render(self) -> str:
        return (f"{self.path}:{self.line}: {self.rule} "
                f"[{self.severity}] {self.message}")


_SUPPRESS_RE = re.compile(r"#\s*tpuic-ok:\s*(.*)")
_RULE_ID_RE = re.compile(r"(?:TPU|CONC|SPMD|CTR)\d+")


def suppressions(source: str) -> Dict[int, Optional[Set[str]]]:
    """{line: set of suppressed rule IDs, or None for 'all rules'}.

    Parsed from real COMMENT tokens, so a ``tpuic-ok`` inside a string
    literal doesn't silence anything.  Any rule ID (``TPU###`` /
    ``CONC###`` / ``SPMD###`` / ``CTR###``) anywhere after the colon
    names a suppressed rule (so rationale text before the ID still
    suppresses only that rule, never everything); a comment with no ID
    at all is the deliberate suppress-all form.
    """
    out: Dict[int, Optional[Set[str]]] = {}
    try:
        lines = iter(source.splitlines(keepends=True))
        tokens = tokenize.generate_tokens(lambda: next(lines))
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            ids = set(_RULE_ID_RE.findall(m.group(1)))
            out[tok.start[0]] = ids or None
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass
    return out


def is_suppressed(finding: Finding,
                  supp: Dict[int, Optional[Set[str]]]) -> bool:
    ids = supp.get(finding.line, "absent")
    if ids == "absent":
        return False
    return ids is None or finding.rule in ids


def collect_files(paths: Sequence[str],
                  exclude: Sequence[str] = ()) -> List[str]:
    """Every .py file under the given files/directories, sorted; paths in
    ``exclude`` (substring match on the relative path) are dropped."""
    out: Set[str] = set()
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            out.add(p)
        elif os.path.isdir(p):
            for dirpath, dirnames, files in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git",
                                            ".jax_cache")]
                for f in files:
                    if f.endswith(".py"):
                        out.add(os.path.join(dirpath, f))
    return sorted(f for f in out
                  if not any(e and e in f for e in exclude))


def lint_source(source: str, path: str,
                select: Optional[Iterable[str]] = None) -> List[Finding]:
    """Lint one module's source; returns unsuppressed findings sorted by
    (line, rule).  ``select`` restricts to those rule IDs."""
    import ast

    from tpuic.analysis.rules import run_rules

    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding("TPU000", Severity.ERROR, path, e.lineno or 1,
                        f"syntax error: {e.msg}")]
    src_lines = source.splitlines()

    def anchored(f: Finding) -> Finding:
        text = (src_lines[f.line - 1].strip()
                if 0 < f.line <= len(src_lines) else "")
        return dataclasses.replace(f, anchor=text)

    supp = suppressions(source)
    findings = [anchored(f)
                for f in run_rules(tree, path, source, supp=supp)]
    if select is not None:
        chosen = set(select)
        findings = [f for f in findings if f.rule in chosen]
    findings = [f for f in findings if not is_suppressed(f, supp)]
    return sorted(findings, key=lambda f: (f.line, f.rule))


def lint_paths(paths: Sequence[str], exclude: Sequence[str] = (),
               select: Optional[Iterable[str]] = None
               ) -> Tuple[List[Finding], List[str]]:
    """Lint every file under ``paths``; returns (findings, files).
    The per-file pass only — :func:`analyze_paths` runs the project
    passes too."""
    files = collect_files(paths, exclude)
    findings: List[Finding] = []
    for f in files:
        with open(f, encoding="utf-8") as fh:
            findings.extend(lint_source(fh.read(), f, select=select))
    return findings, files


PASSES = ("lint", "conc", "spmd", "ctr")


def analyze_paths(paths: Sequence[str], exclude: Sequence[str] = (),
                  select: Optional[Iterable[str]] = None,
                  passes: Sequence[str] = PASSES
                  ) -> Tuple[List[Finding], List[str]]:
    """The multi-pass driver: the per-file lint pass plus the
    project-wide passes (conc/spmd/ctr) over one shared parse.

    Every pass rides the same machinery: ``select`` restricts rule IDs,
    inline ``# tpuic-ok: RULE why`` comments on the anchored line (or
    the enclosing ``def`` line, for the project rules) suppress, and
    the returned findings carry anchors for baseline fingerprinting.
    Returns (findings, files).
    """
    files = collect_files(paths, exclude)
    sources: Dict[str, str] = {}
    for f in files:
        with open(f, encoding="utf-8") as fh:
            sources[f] = fh.read()
    findings: List[Finding] = []
    if "lint" in passes:
        for f in files:
            findings.extend(lint_source(sources[f], f, select=select))
    if any(p in passes for p in ("conc", "spmd", "ctr")):
        from tpuic.analysis.callgraph import Project
        project = Project(files, sources)
        raw: List[Finding] = []
        if "conc" in passes:
            from tpuic.analysis.conc import run_conc
            raw.extend(run_conc(project))
        if "spmd" in passes:
            from tpuic.analysis.spmd import run_spmd
            raw.extend(run_spmd(project))
        if "ctr" in passes:
            from tpuic.analysis.contracts import run_ctr
            raw.extend(run_ctr(project))
        chosen = set(select) if select is not None else None
        for f in raw:
            if chosen is not None and f.rule not in chosen:
                continue
            mod = project.modules.get(f.path.replace("\\", "/"))
            if mod is not None:
                if is_suppressed(f, mod.supp):
                    continue
                text = (mod.lines[f.line - 1].strip()
                        if 0 < f.line <= len(mod.lines) else "")
                f = dataclasses.replace(f, anchor=text)
            findings.append(f)
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule)), files
