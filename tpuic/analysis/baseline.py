"""Committed-baseline workflow: new findings fail, legacy ones are
visible debt.

The baseline (``analysis_baseline.json`` at the repo root) is a list of
fingerprinted findings the tree is *allowed* to contain.  A fingerprint
is ``sha1(rule | normalized-path | stripped-source-line)`` — anchored to
the offending line's *text*, not its number, so edits elsewhere in the
file don't churn it.  Identical lines in one file (rare) are handled by
count: the baseline stores how many of each fingerprint it tolerates,
and the gate fails only when the live tree exceeds that count.

Workflow (docs/analysis.md):

- fix a legacy finding        -> the stale entry is reported (and
                                 ``--write-baseline`` prunes it)
- introduce a new finding     -> CI fails with the finding rendered
- genuinely intended          -> suppress inline (``# tpuic-ok: RULE
                                 why``) — preferred, the reason lives
                                 next to the code — or re-baseline
"""

from __future__ import annotations

import collections
import hashlib
import json
import os
from typing import Dict, List, Sequence, Tuple

from tpuic.analysis.core import Finding


_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _norm_path(path: str) -> str:
    """Repo-root-relative when the file lives under the repo, else the
    absolute path.  Critical for the fingerprint: the CI invocation
    (``tpuic/``, relative) and the CLI default (absolute) must hash a
    file identically, on any checkout location."""
    p = os.path.normpath(os.path.abspath(path))
    try:
        rel = os.path.relpath(p, _ROOT)
    except ValueError:  # Windows: different drive
        rel = ".."
    if not rel.startswith(".."):
        p = rel
    return p.replace("\\", "/")


def fingerprint(f: Finding) -> str:
    """Project-level findings (``f.fkey`` set — e.g. a lock cycle that
    spans files) key on their structural identity, not a line: the
    cycle's sorted edge set survives any edit that doesn't change the
    lock graph itself."""
    if f.fkey:
        key = f"{f.rule}|{f.fkey}"
    else:
        key = f"{f.rule}|{_norm_path(f.path)}|{f.anchor}"
    return hashlib.sha1(key.encode()).hexdigest()[:16]


def load_baseline(path: str) -> Dict[str, int]:
    """{fingerprint: tolerated count}; {} when the file doesn't exist."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    counts: Dict[str, int] = collections.Counter()
    for entry in data.get("findings", []):
        counts[entry["fingerprint"]] += int(entry.get("count", 1))
    return dict(counts)


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    """Write the current findings as the new tolerated baseline, grouped
    and human-diffable (sorted by path/rule, one entry per fingerprint)."""
    grouped: Dict[str, List[Finding]] = collections.defaultdict(list)
    for f in findings:
        grouped[fingerprint(f)].append(f)
    entries = []
    for fp, group in grouped.items():
        f = group[0]
        entry = {
            "fingerprint": fp,
            "rule": f.rule,
            "path": _norm_path(f.path),
            "line": f.line,        # informational; not part of the key
            "anchor": f.anchor,
            "message": f.message,
            "count": len(group),
        }
        if f.fkey:
            entry["fkey"] = f.fkey  # the structural key that was hashed
        entries.append(entry)
    entries.sort(key=lambda e: (e["path"], e["line"], e["rule"]))
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": 1, "findings": entries}, fh, indent=2)
        fh.write("\n")


def new_findings(findings: Sequence[Finding], baseline: Dict[str, int]
                 ) -> Tuple[List[Finding], int]:
    """(findings beyond what the baseline tolerates, stale entry count).

    Stale = baseline entries the live tree no longer produces; reported
    so fixed debt gets pruned instead of silently shielding a future
    regression on the same line text.
    """
    remaining = dict(baseline)
    fresh: List[Finding] = []
    for f in findings:
        fp = fingerprint(f)
        if remaining.get(fp, 0) > 0:
            remaining[fp] -= 1
        else:
            fresh.append(f)
    stale = sum(1 for v in remaining.values() if v > 0)
    return fresh, stale
