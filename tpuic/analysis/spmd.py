"""SPMD — collective-consistency analysis (docs/analysis.md).

In SPMD code every process must execute the same collectives in the
same order; a collective one rank skips (or reorders) hangs the fleet
at the next synchronization point, with no traceback anywhere.

- **SPMD101 rank-divergent-collective**: a collective
  (``psum``/``pmean``/``all_gather``/``ppermute``/``all_to_all``/...)
  reachable under Python control flow conditioned on a rank-dependent
  value: ``jax.process_index()``, the ``TPUIC_FLEET_RANK`` env var, a
  name/attribute whose identifier is literally ``rank`` (``ranks`` — a
  world *size*, identical everywhere — deliberately does not taint), or
  a call to a function that derives such a value (``is_main_process``).
  Both forms are caught: a collective lexically inside the tainted
  branch (or one resolved call away), and a tainted early ``return``
  lexically above a collective later in the same function.
- **SPMD102 collective-order-divergence**: two functions that execute
  the same pair of distinct collectives in opposite orders — two call
  paths through them give two ranks opposite acquisition orders on the
  fleet's synchronization points, the collective flavor of CONC101.
  Project-level finding, fingerprinted on the sorted pair.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from tpuic.analysis.callgraph import FuncInfo, Project, dotted
from tpuic.analysis.core import Finding, Severity

COLLECTIVES = frozenset({
    "psum", "pmean", "pmax", "pmin", "all_gather", "ppermute",
    "all_to_all", "psum_scatter", "pshuffle", "axis_index_groups_sum",
})

# Identifier segments that mark a value as rank-dependent.  The word
# boundary matters: 'rank'/'fleet_rank'/'rank_id' taint, 'ranks' (world
# size) does not.
_RANK_WORD = re.compile(r"(?:^|_)rank(?:$|_)")
_RANK_ENV = re.compile(r"RANK", re.IGNORECASE)


def _is_rank_name(name: str) -> bool:
    return bool(_RANK_WORD.search(name)) or "process_index" in name


def _rank_source_funcs(project: Project) -> Set[int]:
    """id(FuncInfo) of functions whose body derives a rank-dependent
    value (``jax.process_index()`` or a *_RANK env read) — a call to one
    of these taints the expression around it."""
    out: Set[int] = set()
    for fi in project.funcs():
        for call in fi.calls:
            d = dotted(call.func)
            if d is None:
                continue
            tail = d.split(".")[-1]
            if tail == "process_index":
                out.add(id(fi))
            elif tail in ("getenv", "get") and call.args \
                    and isinstance(call.args[0], ast.Constant) \
                    and isinstance(call.args[0].value, str) \
                    and _RANK_ENV.search(call.args[0].value):
                out.add(id(fi))
    return out


def _expr_rank_tainted(project: Project, fi: FuncInfo, expr: ast.AST,
                       rank_funcs: Set[int]) -> bool:
    for n in ast.walk(expr):
        if isinstance(n, ast.Name) and _is_rank_name(n.id):
            return True
        if isinstance(n, ast.Attribute) and _is_rank_name(n.attr):
            return True
        if isinstance(n, ast.Call):
            d = dotted(n.func)
            tail = (d or "").split(".")[-1]
            if tail == "process_index":
                return True
            if tail in ("getenv", "get") and n.args \
                    and isinstance(n.args[0], ast.Constant) \
                    and isinstance(n.args[0].value, str) \
                    and _RANK_ENV.search(n.args[0].value):
                return True
            for callee in project.resolve_call(fi, n):
                if id(callee) in rank_funcs:
                    return True
    return False


def _collective_id(call: ast.Call) -> Optional[str]:
    """'psum' / 'ppermute@x' (axis_name folded in when constant)."""
    d = dotted(call.func)
    if d is None:
        return None
    tail = d.split(".")[-1]
    if tail not in COLLECTIVES:
        return None
    for kw in call.keywords:
        if kw.arg == "axis_name" and isinstance(kw.value, ast.Constant):
            return f"{tail}@{kw.value.value}"
    return tail


def _own_nodes(node: ast.AST) -> List[ast.AST]:
    out: List[ast.AST] = []

    def rec(n: ast.AST) -> None:
        out.append(n)
        for c in ast.iter_child_nodes(n):
            if isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
                continue
            rec(c)
    rec(node)
    return out


def _direct_collectives(fi: FuncInfo) -> List[Tuple[str, int]]:
    out: List[Tuple[str, int]] = []
    for stmt in fi.node.body:
        for n in _own_nodes(stmt):
            if isinstance(n, ast.Call):
                cid = _collective_id(n)
                if cid is not None:
                    out.append((cid, n.lineno))
    return out


def _terminates(body: List[ast.stmt]) -> bool:
    return bool(body) and isinstance(
        body[-1], (ast.Return, ast.Raise, ast.Break, ast.Continue))


def run_spmd(project: Project) -> List[Finding]:
    rank_funcs = _rank_source_funcs(project)
    # One resolved call level: functions with direct collectives, so a
    # rank-gated call to `ring_step()` is as divergent as a rank-gated
    # psum.
    has_direct: Dict[int, List[Tuple[str, int]]] = {
        id(f): _direct_collectives(f) for f in project.funcs()}
    findings: List[Finding] = []

    for fi in project.funcs():
        if fi.allowlisted("SPMD101"):
            continue
        mod = fi.module
        directs = has_direct[id(fi)]
        for stmt in fi.node.body:
            for n in _own_nodes(stmt):
                if not isinstance(n, (ast.If, ast.While)):
                    continue
                if not _expr_rank_tainted(project, fi, n.test,
                                          rank_funcs):
                    continue
                # Form 1: collective inside the tainted branch (or one
                # resolved call away).
                branch_nodes: List[ast.AST] = []
                for sub in n.body + n.orelse:
                    branch_nodes.extend(_own_nodes(sub))
                hit = False
                for b in branch_nodes:
                    if not isinstance(b, ast.Call):
                        continue
                    cid = _collective_id(b)
                    if cid is not None:
                        findings.append(Finding(
                            "SPMD101", Severity.ERROR, mod.path,
                            b.lineno,
                            f"collective '{cid}' under rank-dependent "
                            f"control flow (condition at line "
                            f"{n.lineno}) — ranks that skip it hang "
                            f"the fleet at the next sync point"))
                        hit = True
                        continue
                    for callee in project.resolve_call(fi, b):
                        inner = has_direct.get(id(callee), [])
                        if inner:
                            findings.append(Finding(
                                "SPMD101", Severity.ERROR, mod.path,
                                b.lineno,
                                f"call to {callee.qualname}() "
                                f"(contains collective "
                                f"'{inner[0][0]}') under "
                                f"rank-dependent control flow "
                                f"(condition at line {n.lineno})"))
                            hit = True
                            break
                if hit:
                    continue
                # Form 2: tainted early exit above a later collective.
                if isinstance(n, ast.If) and _terminates(n.body) \
                        and not n.orelse:
                    end = getattr(n, "end_lineno", n.lineno) or n.lineno
                    later = [(cid, ln) for cid, ln in directs
                             if ln > end]
                    if later:
                        cid, ln = later[0]
                        findings.append(Finding(
                            "SPMD101", Severity.ERROR, mod.path,
                            n.lineno,
                            f"rank-dependent early exit above "
                            f"collective '{cid}' (line {ln}) — "
                            f"exiting ranks never reach it; the rest "
                            f"hang"))

    # SPMD102: opposite-order collective pairs across functions.
    seqs: List[Tuple[FuncInfo, List[Tuple[str, int]]]] = []
    for fi in project.funcs():
        if fi.allowlisted("SPMD102"):
            continue
        seq = has_direct[id(fi)]
        if len({c for c, _ in seq}) >= 2:
            seqs.append((fi, seq))
    reported: Set[Tuple[str, str]] = set()
    for i, (fa, sa) in enumerate(seqs):
        for fb, sb in seqs[i + 1:]:
            for a_idx, (ca, la) in enumerate(sa):
                for cb, lb in sa[a_idx + 1:]:
                    if ca == cb:
                        continue
                    # fa runs ca before cb; does fb run cb before ca?
                    pos_b = {c: k for k, (c, _) in
                             reversed(list(enumerate(sb)))}
                    if cb in pos_b and ca in pos_b \
                            and pos_b[cb] < pos_b[ca]:
                        pair = tuple(sorted((ca, cb)))
                        if pair in reported:
                            continue
                        reported.add(pair)
                        findings.append(Finding(
                            "SPMD102", Severity.WARNING,
                            fa.module.path, la,
                            f"collectives '{ca}' and '{cb}' run in "
                            f"opposite orders: {fa.qualname}() (line "
                            f"{la}) vs {fb.qualname}() "
                            f"({fb.module.path}:{lb}) — two ranks on "
                            f"the two paths deadlock at the sync "
                            f"point",
                            fkey=f"spmd102:{pair[0]}|{pair[1]}"))
    return findings
