"""Pytest plugin for the runtime contract checkers (docs/analysis.md).

Registered from tests/conftest.py via ``pytest_plugins``; provides the
checkers as fixtures plus a ``compiles_flat`` marker that wraps a whole
test in the steady-state assertion:

    @pytest.mark.compiles_flat(max_new=4)   # warmup allowance
    def test_my_stream(...): ...

    def test_drain_budget(device_gets):
        ...
        assert device_gets.count <= 2
"""

from __future__ import annotations

import pytest

from tpuic.analysis import runtime


def pytest_configure(config) -> None:
    config.addinivalue_line(
        "markers",
        "compiles_flat(max_new=0): assert at most max_new new XLA "
        "executables are built during the test "
        "(tpuic.analysis.runtime.assert_compiles_flat)")


@pytest.fixture(autouse=True)
def _compiles_flat_marker(request):
    """Honors ``@pytest.mark.compiles_flat`` — no-op without the mark."""
    m = request.node.get_closest_marker("compiles_flat")
    if m is None:
        yield
        return
    max_new = m.kwargs.get("max_new", m.args[0] if m.args else 0)
    with runtime.assert_compiles_flat(max_new=max_new,
                                      what=request.node.name):
        yield


@pytest.fixture
def compile_watch():
    """Observe compile/trace deltas over the test (no assertion)."""
    with runtime.watch_compiles() as w:
        yield w


@pytest.fixture
def device_gets():
    """Count jax.device_get calls over the test (no assertion)."""
    with runtime.count_device_gets() as c:
        yield c


@pytest.fixture
def lock_order_watch():
    """Record the actual lock-acquisition order over the test (locks
    *created inside* the test are watched); fails the test if an
    observed edge closes a cycle.  Cross-check against the static graph
    with ``watch.check(runtime.static_lock_edges([...]))``."""
    with runtime.lock_order_watch() as w:
        yield w
