"""The JAX/TPU footgun rules (docs/analysis.md has the catalog).

Every rule is born from a debugging session PRs 1-3 actually paid for:

- TPU1xx — host/device boundary: silent syncs in hot-path modules, the
  recompile hazards (Python branching on tracers, f-strings on traced
  values, jit args that should be static).
- TPU2xx — donation misuse: donated buffers read after the call, and the
  codified PR-2 bisect: ``lax.cond`` inside a donated jit is one
  persistent-compile-cache away from silent buffer corruption.
- TPU3xx — dtype discipline: accidental float64 promotion and
  per-trace ``jnp.array`` construction inside jitted code.
- TPU4xx — PRNG hygiene: key reuse / missing key threading.
- TPU5xx — generic hygiene: unused imports, unreachable code.

The analysis is a single AST pass per module with a *jit context*: a
function counts as jitted when it is decorated with ``jax.jit`` (bare,
called, or via ``partial``) or when any ``jax.jit(<its name>, ...)``
call appears in the module (the ``make_train_step`` idiom — the def and
the wrap are far apart).  Nested defs inherit the context: everything
inside a jitted function traces.

These are heuristics, deliberately precision-biased: a rule that cries
wolf gets suppressed wholesale and protects nothing.  Shape/ndim/dtype
attribute accesses are recognized as static and never flagged.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tpuic.analysis.core import Finding, Severity

# Modules whose per-step loops are latency-critical: a blocking host sync
# here costs a tunnel RTT per step (PERF_ANALYSIS round-4 finding — four
# scalar reads per log point held fit() at 59% of the bench).  Matched by
# path suffix; ``.item()`` / ``jax.device_get`` are flagged anywhere in
# these modules.  The deferred-drain sites inside them carry explicit
# ``# tpuic-ok: TPU101`` suppressions with their rationale — put the
# comment on the ``def`` line to allowlist a whole drain function.
HOT_PATH_SUFFIXES = (
    "tpuic/train/loop.py",
    "tpuic/train/step.py",
    "tpuic/serve/engine.py",
    "tpuic/data/pipeline.py",
    "tpuic/data/device_prep.py",
)

# The per-step loop functions themselves: here even ``float(...)`` /
# ``np.asarray`` are flagged (each is a blocking readback when handed a
# device value).  Nested defs inherit — a drain closure inside
# ``val_epoch`` is still the hot loop.
HOT_LOOP_FUNCS = {
    "tpuic/train/loop.py": {"train_epoch", "_drain_train_log",
                            "val_epoch"},
    "tpuic/serve/engine.py": {"submit", "predict", "_gather", "_dispatch",
                              "_resolve", "_run"},
}

_SYNC_CALLS = {
    "jax.device_get": "blocking device->host transfer",
    "np.asarray": "materializes device arrays on host",
    "np.array": "materializes device arrays on host",
    "numpy.asarray": "materializes device arrays on host",
    "numpy.array": "materializes device arrays on host",
}
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}
_KEY_MAKERS = {"key", "PRNGKey", "split", "fold_in", "wrap_key_data",
               "clone"}


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    name: str
    severity: Severity
    doc: str


RULES: Dict[str, Rule] = {r.id: r for r in (
    Rule("TPU101", "host-sync-in-hot-path", Severity.ERROR,
         "Host-sync call (.item(), float(), np.asarray, jax.device_get) "
         "in a hot-path module outside an allowlisted deferred-drain "
         "site, or inside jitted code where it breaks tracing."),
    Rule("TPU102", "traced-python-branch", Severity.WARNING,
         "Python control flow (if/while/range) on a traced argument "
         "inside a jitted function: every distinct value retraces — use "
         "lax.cond/lax.select or mark the arg static_argnums."),
    Rule("TPU103", "fstring-on-tracer", Severity.WARNING,
         "f-string interpolating a traced value inside a jitted "
         "function: concretizes (or silently bakes one trace's value)."),
    Rule("TPU201", "donated-buffer-read", Severity.ERROR,
         "Argument donated to a jitted call is read afterwards: the "
         "buffer was surrendered to XLA and may alias the output."),
    Rule("TPU202", "cond-in-donated-jit", Severity.ERROR,
         "lax.cond inside a jit with donate_argnums: with a persistent "
         "compilation cache, cache-deserialized executables corrupt "
         "cond's donated pass-through buffers (PR-2 bisect, jax<=0.4.37 "
         "CPU). Use a jnp.where select or suppress with the measured "
         "rationale."),
    Rule("TPU301", "float64-in-jit", Severity.WARNING,
         "float64 inside jitted code: accidental double promotion "
         "silently doubles HBM/ICI bytes (or truncates under the "
         "default x64-disabled config)."),
    Rule("TPU302", "jnp-array-in-jit", Severity.WARNING,
         "jnp.array(...) construction inside jitted code: builds a "
         "fresh constant every trace — hoist it out of the jit or use "
         "jnp.asarray on an existing array."),
    Rule("TPU401", "prng-key-reuse", Severity.ERROR,
         "The same PRNG key consumed by more than one jax.random "
         "sampling call without split/fold_in between: the draws are "
         "identical, not independent."),
    Rule("TPU501", "unused-import", Severity.WARNING,
         "Imported name never referenced in the module."),
    Rule("TPU502", "dead-code", Severity.WARNING,
         "Statement unreachable after return/raise/break/continue."),
    # -- project-wide passes (analysis/conc.py, spmd.py, contracts.py;
    # these never fire from the per-file lint pass) -------------------
    Rule("CONC101", "lock-order-cycle", Severity.ERROR,
         "Cycle in the project-wide lock-order graph: two threads "
         "taking the cycle's locks in opposite orders deadlock. "
         "Project-level finding, fingerprinted on the sorted edge set."),
    Rule("CONC102", "signal-unsafe-call", Severity.ERROR,
         "Lock acquisition, event-bus publish, or shared file-handle "
         "mutation reachable from a signal-handler registration — the "
         "handler may interrupt the frame that holds the resource "
         "(the PR-8 FlightRecorder deadlock, codified)."),
    Rule("CONC103", "unlocked-shared-closure", Severity.WARNING,
         "threading.Thread target closes over a variable both the "
         "thread and the spawning scope mutate with no common lock."),
    Rule("SPMD101", "rank-divergent-collective", Severity.ERROR,
         "Collective (psum/pmean/all_gather/ppermute/all_to_all/...) "
         "reachable under control flow conditioned on a rank-dependent "
         "value (process_index, TPUIC_FLEET_RANK, rank attrs) — ranks "
         "that skip it hang the fleet at the next sync point."),
    Rule("SPMD102", "collective-order-divergence", Severity.WARNING,
         "Two functions execute the same pair of collectives in "
         "opposite orders — opposite sync-point acquisition orders "
         "across ranks, the collective flavor of CONC101."),
    Rule("CTR101", "event-kind-contract", Severity.ERROR,
         "Every published event kind must be registered in EVENT_KINDS "
         "and every registered kind must have a schema row in "
         "docs/observability.md."),
    Rule("CTR102", "prom-row-contract", Severity.WARNING,
         "Every metric row name emitted by telemetry/prom.py must "
         "appear in docs/observability.md (and stay statically "
         "enumerable so this check can see it)."),
    Rule("CTR103", "exit-code-contract", Severity.ERROR,
         "Supervisor EXIT_* constants must be distinct, never shadowed "
         "in gang.py, never bypassed with raw sys.exit(<int>) "
         "literals, and documented (value + name) in "
         "docs/robustness.md."),
)}


def _dotted(node: ast.AST) -> Optional[str]:
    """'jax.lax.cond' for Attribute/Name chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _int_elems(node: Optional[ast.AST]) -> Tuple[Set[int], bool]:
    """(literal ints in a donate/static argnums expression, definitely
    empty?).  Non-literal expressions — ``(0,) if donate else ()`` —
    count as 'maybe non-empty' with no known indices."""
    if node is None:
        return set(), True
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return {node.value}, False
    if isinstance(node, (ast.Tuple, ast.List)):
        out: Set[int] = set()
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.add(e.value)
        return out, not node.elts
    return set(), False  # dynamic expression: assume maybe-donating


def _str_elems(node: Optional[ast.AST]) -> Set[str]:
    if node is None:
        return set()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        return {e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)}
    return set()


@dataclasses.dataclass
class _JitInfo:
    static_idx: Set[int] = dataclasses.field(default_factory=set)
    static_names: Set[str] = dataclasses.field(default_factory=set)
    donate_idx: Set[int] = dataclasses.field(default_factory=set)
    donates: bool = False


def _jit_call_info(call: ast.Call) -> _JitInfo:
    info = _JitInfo()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            idx, _ = _int_elems(kw.value)
            info.static_idx |= idx
        elif kw.arg == "static_argnames":
            info.static_names |= _str_elems(kw.value)
        elif kw.arg in ("donate_argnums", "donate_argnames"):
            idx, empty = _int_elems(kw.value)
            info.donate_idx |= idx
            if not empty:
                info.donates = True
    return info


def _is_jit_func(node: ast.AST) -> bool:
    d = _dotted(node)
    return d in ("jax.jit", "jit", "pjit", "jax.pjit")


def _decorator_jit(dec: ast.AST) -> Optional[_JitInfo]:
    """_JitInfo when the decorator applies jax.jit, else None."""
    if _is_jit_func(dec):
        return _JitInfo()
    if isinstance(dec, ast.Call):
        if _is_jit_func(dec.func):
            return _jit_call_info(dec)
        d = _dotted(dec.func)
        if d in ("partial", "functools.partial") and dec.args \
                and _is_jit_func(dec.args[0]):
            return _jit_call_info(dec)
    return None


def _param_names(fn) -> List[str]:
    a = fn.args
    return ([p.arg for p in a.posonlyargs] + [p.arg for p in a.args])


class _Ctx:
    """Jit / hot-loop context threaded through the recursive walk."""

    __slots__ = ("in_jit", "traced", "static", "donates", "hot",
                 "allowed")

    def __init__(self, in_jit=False, traced=frozenset(), static=frozenset(),
                 donates=False, hot=False, allowed=frozenset()):
        self.in_jit = in_jit
        self.traced = traced
        self.static = static
        self.donates = donates
        self.hot = hot            # inside a designated hot-loop function
        self.allowed = allowed    # rules allowlisted on the def line
        # allowed == {"*"} means every rule (bare '# tpuic-ok:')


class Analyzer:
    def __init__(self, tree: ast.Module, path: str, source: str,
                 supp: Optional[Dict] = None) -> None:
        self.tree = tree
        self.path = path.replace("\\", "/")
        self.source = source
        self.findings: List[Finding] = []
        self.hot_path = any(self.path.endswith(s)
                            for s in HOT_PATH_SUFFIXES)
        self.hot_funcs = next((fns for s, fns in HOT_LOOP_FUNCS.items()
                               if self.path.endswith(s)), frozenset())
        if supp is None:  # direct Analyzer use; lint_source passes it in
            from tpuic.analysis.core import suppressions
            supp = suppressions(source)
        self._supp = supp
        # Pre-pass: functions wrapped by name — jax.jit(train_step, ...).
        self.wrapped: Dict[str, _JitInfo] = {}
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call) and _is_jit_func(node.func)
                    and node.args and isinstance(node.args[0], ast.Name)):
                info = _jit_call_info(node)
                prev = self.wrapped.get(node.args[0].id)
                if prev is not None:  # merge multiple wrap sites
                    info.static_idx |= prev.static_idx
                    info.static_names |= prev.static_names
                    info.donate_idx |= prev.donate_idx
                    info.donates = info.donates or prev.donates
                self.wrapped[node.args[0].id] = info

    # -- helpers -----------------------------------------------------------
    def add(self, rule: str, node: ast.AST, message: str,
            ctx: Optional[_Ctx] = None) -> None:
        if ctx is not None and ("*" in ctx.allowed or rule in ctx.allowed):
            return  # def-line function allowlist
        r = RULES[rule]
        self.findings.append(Finding(rule, r.severity, self.path,
                                     getattr(node, "lineno", 1), message))

    def _traced_name_nodes(self, node: ast.AST,
                           traced: frozenset) -> List[ast.Name]:
        """Loads of traced params in ``node``, excluding anything under a
        static attribute access (x.shape, x.ndim, x.dtype, x.size)."""
        hits: List[ast.Name] = []

        def rec(n: ast.AST) -> None:
            if isinstance(n, ast.Attribute) and n.attr in _STATIC_ATTRS:
                return
            if isinstance(n, ast.Compare) and all(
                    isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                    for op in n.ops):
                # `x is None` / `"k" in params`: structural tests that
                # never concretize a tracer — the dominant JAX idiom for
                # optional args and pytree membership.
                return
            if isinstance(n, ast.Name) and n.id in traced \
                    and isinstance(n.ctx, ast.Load):
                hits.append(n)
                return
            for c in ast.iter_child_nodes(n):
                rec(c)
        rec(node)
        return hits

    # -- per-module rules --------------------------------------------------
    def run(self) -> List[Finding]:
        self._unused_imports()
        self._walk_block(self.tree.body, _Ctx())
        return self.findings

    def _unused_imports(self) -> None:
        if self.path.endswith("__init__.py"):
            return  # re-export modules: unused-by-design
        imported: List[Tuple[str, ast.AST, str]] = []
        used: Set[str] = set()
        exported: Set[str] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    name = a.asname or a.name.split(".")[0]
                    imported.append((name, node, a.name))
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    name = a.asname or a.name
                    imported.append((name, node, a.name))
            elif isinstance(node, ast.Name):
                if not isinstance(node.ctx, ast.Store):
                    used.add(node.id)
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == "__all__":
                        exported |= _str_elems(node.value)
        seen: Set[int] = set()
        for name, node, orig in imported:
            if name in used or name in exported or name.startswith("_"):
                continue
            key = (id(node) << 16) ^ hash(name)
            if key in seen:
                continue
            seen.add(key)
            self.add("TPU501", node, f"'{name}' imported but unused")

    # -- the recursive walk ------------------------------------------------
    def _walk_block(self, body: Sequence[ast.stmt], ctx: _Ctx) -> None:
        terminated = False
        for stmt in body:
            if terminated:
                self.add("TPU502", stmt,
                         "unreachable: previous statement always exits "
                         "this block")
                terminated = False  # one finding per dead region
            self._walk_stmt(stmt, ctx)
            if isinstance(stmt, (ast.Return, ast.Raise, ast.Break,
                                 ast.Continue)):
                terminated = True

    def _walk_stmt(self, stmt: ast.stmt, ctx: _Ctx) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._enter_function(stmt, ctx)
            return
        if isinstance(stmt, ast.ClassDef):
            for s in stmt.body:
                self._walk_stmt(s, ctx)
            return
        # Expression-level rules over this statement's OWN expressions
        # (nested statements are walked by their own _walk_stmt calls).
        self._scan_exprs(stmt, ctx)
        if ctx.in_jit and isinstance(stmt, (ast.If, ast.While)):
            hits = self._traced_name_nodes(stmt.test, ctx.traced)
            if hits:
                names = ", ".join(sorted({h.id for h in hits}))
                kw = "while" if isinstance(stmt, ast.While) else "if"
                self.add("TPU102", stmt,
                         f"Python `{kw}` on traced argument(s) {names} "
                         "inside jitted code — retraces per value; use "
                         "lax.cond/jnp.where or static_argnums", ctx)
        # Recurse into child blocks.
        for field in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, field, None)
            if sub:
                self._walk_block(sub, ctx)
        for h in getattr(stmt, "handlers", []) or []:
            self._walk_block(h.body, ctx)

    def _enter_function(self, fn, outer: _Ctx) -> None:
        info = None
        for dec in fn.decorator_list:
            info = _decorator_jit(dec)
            if info is not None:
                break
        if info is None:
            info = self.wrapped.get(fn.name)
        params = _param_names(fn)
        hot = outer.hot or fn.name in self.hot_funcs
        # Def-line allowlist: '# tpuic-ok: TPU101 why' on the def line
        # silences that rule for the whole function body (the drain-site
        # allowlist mechanism).  Inherited by nested defs.
        allowed = set(outer.allowed)
        if fn.lineno in self._supp:
            ids = self._supp[fn.lineno]
            allowed |= {"*"} if ids is None else ids
        if info is not None:
            static = {params[i] for i in info.static_idx
                      if i < len(params)} | info.static_names
            ctx = _Ctx(True, frozenset(p for p in params
                                       if p not in static),
                       frozenset(static),
                       info.donates or bool(info.donate_idx),
                       hot, frozenset(allowed))
        elif outer.in_jit:
            # Nested def inside jitted code traces with the parent; its
            # own params are traced values too (closure-invoked).
            ctx = _Ctx(True, outer.traced | frozenset(params),
                       outer.static, outer.donates, hot,
                       frozenset(allowed))
        else:
            ctx = _Ctx(hot=hot, allowed=frozenset(allowed))
        self._check_key_reuse(fn, ctx)
        self._check_donated_reads(fn, ctx)
        self._walk_block(fn.body, ctx)

    # -- expression-level rules -------------------------------------------
    def _scan_exprs(self, stmt: ast.stmt, ctx: _Ctx) -> None:
        """Check the statement's own expression subtree; recursion stops
        at nested statements (their own _walk_stmt visit covers them), so
        a call nested three blocks deep is reported exactly once."""
        def rec(n: ast.AST) -> None:
            for c in ast.iter_child_nodes(n):
                if isinstance(c, ast.stmt):
                    continue
                self._check_expr(c, ctx)
                rec(c)
        self._check_expr(stmt, ctx)
        rec(stmt)

    def _check_expr(self, node: ast.AST, ctx: _Ctx) -> None:
        if isinstance(node, ast.Call):
            self._check_call(node, ctx)
        elif isinstance(node, ast.JoinedStr) and ctx.in_jit:
            hits = []
            for v in node.values:
                if isinstance(v, ast.FormattedValue):
                    hits += self._traced_name_nodes(v.value, ctx.traced)
            if hits:
                names = ", ".join(sorted({h.id for h in hits}))
                self.add("TPU103", node,
                         f"f-string interpolates traced value(s) "
                         f"{names} inside jitted code", ctx)
        elif ctx.in_jit and isinstance(node, ast.Attribute):
            d = _dotted(node)
            if d in ("jnp.float64", "np.float64", "jax.numpy.float64",
                     "numpy.float64"):
                self.add("TPU301", node,
                         f"{d} inside jitted code — accidental double "
                         "promotion", ctx)
        elif ctx.in_jit and isinstance(node, ast.Constant) \
                and node.value == "float64":
            self.add("TPU301", node,
                     "'float64' dtype literal inside jitted code", ctx)

    def _check_call(self, call: ast.Call, ctx: _Ctx) -> None:
        d = _dotted(call.func)
        # .item() — a blocking scalar sync wherever it appears in a
        # hot-path module, and a trace-breaker inside jit.
        if (isinstance(call.func, ast.Attribute)
                and call.func.attr == "item" and not call.args):
            if ctx.in_jit or ctx.hot:
                self.add("TPU101", call,
                         ".item() is a blocking host sync"
                         + (" inside jitted code" if ctx.in_jit else
                            " inside the hot loop"), ctx)
            return
        if d in _SYNC_CALLS:
            if ctx.in_jit:
                self.add("TPU101", call,
                         f"{d}(): {_SYNC_CALLS[d]} — illegal on tracers "
                         "inside jitted code", ctx)
            elif d == "jax.device_get" and self.hot_path:
                self.add("TPU101", call,
                         "jax.device_get(): blocking device->host "
                         "transfer in a hot-path module; belongs in the "
                         "deferred drain", ctx)
            elif ctx.hot and d != "jax.device_get":
                self.add("TPU101", call,
                         f"{d}(): {_SYNC_CALLS[d]} — a blocking readback "
                         "when handed a device value, inside the hot "
                         "loop", ctx)
            return
        if d == "float" and len(call.args) == 1 \
                and (ctx.in_jit or ctx.hot):
            arg = call.args[0]
            if not isinstance(arg, ast.Constant) and not any(
                    isinstance(n, ast.Attribute) and n.attr in _STATIC_ATTRS
                    for n in ast.walk(arg)):
                if ctx.in_jit and self._traced_name_nodes(arg, ctx.traced):
                    self.add("TPU101", call,
                             "float() on a traced value concretizes "
                             "(host sync / trace error)", ctx)
                elif not ctx.in_jit:
                    self.add("TPU101", call,
                             "float() forces a blocking scalar readback "
                             "inside the hot loop; defer it to the "
                             "drain site", ctx)
            return
        if ctx.in_jit:
            if d == "range" and self._traced_name_nodes(call, ctx.traced):
                self.add("TPU102", call,
                         "range() over a traced argument inside jitted "
                         "code — concretizes; use lax.fori_loop or "
                         "static_argnums", ctx)
            elif d in ("jnp.array", "jax.numpy.array"):
                self.add("TPU302", call,
                         "jnp.array(...) inside jitted code rebuilds the "
                         "constant every trace — hoist it or use "
                         "jnp.asarray", ctx)
            elif ctx.donates and d in ("jax.lax.cond", "lax.cond"):
                self.add("TPU202", call,
                         "lax.cond inside a donated jit: donated "
                         "pass-through + persistent compile cache "
                         "corrupts buffers (PR-2 bisect); prefer a "
                         "jnp.where select", ctx)

    # -- PRNG key reuse ----------------------------------------------------
    def _check_key_reuse(self, fn, ctx: Optional[_Ctx] = None) -> None:
        """Within ONE function scope (nested defs excluded — exclusive
        cond branches would false-positive), a key name consumed by two
        sampling calls with no rebind between is a reuse."""
        tracked: Set[str] = {p for p in _param_names(fn)
                             if "rng" in p.lower() or "key" in p.lower()}
        own_nodes = self._scope_nodes(fn)
        events: List[Tuple[int, int, str, str, ast.AST]] = []
        for node in own_nodes:
            if isinstance(node, ast.Assign):
                names = [t.id for t in node.targets
                         if isinstance(t, ast.Name)]
                src = node.value
                maker = False
                if isinstance(src, ast.Call):
                    sd = _dotted(src.func) or ""
                    maker = sd.split(".")[-1] in _KEY_MAKERS
                elif isinstance(src, (ast.Subscript, ast.Starred)):
                    maker = True  # keys = split(...); k = keys[0]
                for n in names:
                    if maker or n in tracked:
                        events.append((node.lineno, node.col_offset,
                                       "bind" if maker else "unbind", n,
                                       node))
                        if maker:
                            tracked.add(n)
            elif isinstance(node, ast.Call):
                sd = _dotted(node.func) or ""
                parts = sd.split(".")
                if len(parts) >= 2 and parts[-2] == "random" \
                        and parts[-1] not in _KEY_MAKERS and node.args:
                    a0 = node.args[0]
                    if isinstance(a0, ast.Name):
                        events.append((node.lineno, node.col_offset,
                                       "consume", a0.id, node))
        events.sort(key=lambda e: (e[0], e[1]))
        consumed: Set[str] = set()
        for lineno, _col, kind, name, node in events:
            if kind in ("bind", "unbind"):
                consumed.discard(name)
            elif kind == "consume":
                if name in consumed:
                    self.add("TPU401", node,
                             f"PRNG key '{name}' already consumed by an "
                             "earlier jax.random call — split or fold_in "
                             "before reusing", ctx)
                consumed.add(name)

    def _scope_nodes(self, fn) -> List[ast.AST]:
        """All nodes in fn's body excluding nested function/class bodies."""
        out: List[ast.AST] = []

        def rec(n: ast.AST) -> None:
            out.append(n)
            for c in ast.iter_child_nodes(n):
                if isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                    continue
                rec(c)
        for s in fn.body:
            rec(s)
        return out

    # -- donated buffers read after the call -------------------------------
    def _check_donated_reads(self, fn, ctx: Optional[_Ctx] = None) -> None:
        """``f = jax.jit(g, donate_argnums=(0,)); out = f(x); ... x ...``
        — x was surrendered; the later read is the bug."""
        own = self._scope_nodes(fn)
        jitted: Dict[str, Set[int]] = {}
        donated_calls: List[Tuple[int, str]] = []  # (call lineno, arg name)
        for node in own:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call) \
                    and _is_jit_func(node.value.func):
                info = _jit_call_info(node.value)
                if info.donate_idx:
                    jitted[node.targets[0].id] = info.donate_idx
        if not jitted:
            return
        handled: set = set()
        for node in own:
            rebound: Set[str] = set()
            call = None
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                call = node.value
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        rebound.add(t.id)
                    elif isinstance(t, (ast.Tuple, ast.List)):
                        rebound |= {e.id for e in t.elts
                                    if isinstance(e, ast.Name)}
            elif isinstance(node, ast.Call):
                call = node
            if call is None or id(call) in handled \
                    or not isinstance(call.func, ast.Name) \
                    or call.func.id not in jitted:
                continue
            handled.add(id(call))
            end = getattr(call, "end_lineno", call.lineno) or call.lineno
            for i in jitted[call.func.id]:
                if i < len(call.args) and isinstance(call.args[i], ast.Name):
                    name = call.args[i].id
                    if name not in rebound:
                        # `state = step(state, ...)` rebinds the donated
                        # name to the RESULT — the surrendered buffer is
                        # no longer reachable, which is the correct idiom.
                        donated_calls.append((end, name))
        # Static-metadata reads survive donation: `x.dtype` / `x.shape` /
        # `x.ndim` / `x.size` live on the (host-side) array object, not in
        # the surrendered device buffer. The bf16 tier's cast-then-donate
        # sites (`x16 = x.astype(bf16); out = step(x16); log(x16.dtype)`)
        # are the common benign shape — only a VALUE read after donation
        # is the bug.
        static_reads = {
            id(a.value) for a in own
            if isinstance(a, ast.Attribute)
            and isinstance(a.value, ast.Name) and a.attr in _STATIC_ATTRS}
        for call_line, name in donated_calls:
            later = sorted(
                (n for n in own if isinstance(n, ast.Name)
                 and n.id == name and n.lineno > call_line),
                key=lambda n: (n.lineno, n.col_offset))
            for n in later:
                if isinstance(n.ctx, ast.Store):
                    break  # rebound: the old buffer is gone cleanly
                if id(n) in static_reads:
                    continue  # metadata-only read; buffer untouched
                self.add("TPU201", n,
                         f"'{name}' was donated to a jitted call on line "
                         f"{call_line} and is read here — the buffer may "
                         "alias the output", ctx)
                break


def run_rules(tree: ast.Module, path: str, source: str,
              supp: Optional[Dict] = None) -> List[Finding]:
    return Analyzer(tree, path, source, supp=supp).run()
