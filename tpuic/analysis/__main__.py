"""``python -m tpuic.analysis [paths...]`` — the multi-pass analyzer.

Four passes (``--passes``, default all): ``lint`` (the per-file JAX/TPU
footgun rules, TPU1xx-5xx), ``conc`` (project-wide lock-order graph,
signal-path safety, thread-closure races, CONC1xx), ``spmd``
(rank-divergent / order-divergent collectives, SPMD1xx), and ``ctr``
(event-kind, prom-row, and exit-code cross-artifact contracts, CTR1xx).

Exit codes: 0 = clean against the baseline, 1 = new findings (or, with
``--strict``, stale baseline entries), 2 = usage error.

    python -m tpuic.analysis tpuic/                 # gate vs baseline
    python -m tpuic.analysis tpuic/ --no-baseline   # every finding
    python -m tpuic.analysis tpuic/ --passes conc,spmd
    python -m tpuic.analysis tpuic/ --write-baseline  # accept current
    python -m tpuic.analysis --list-rules           # the catalog
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from tpuic.analysis.baseline import (load_baseline, new_findings,
                                     write_baseline)
from tpuic.analysis.core import PASSES, Finding, analyze_paths
from tpuic.analysis.rules import RULES

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_BASELINE = os.path.join(_REPO, "analysis_baseline.json")


def _print_findings(findings: List[Finding], as_json: bool) -> None:
    if as_json:
        print(json.dumps([{
            "rule": f.rule, "family": f.family,
            "severity": str(f.severity), "path": f.path,
            "line": f.line, "message": f.message, "anchor": f.anchor,
            **({"fkey": f.fkey} if f.fkey else {}),
        } for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render())


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="python -m tpuic.analysis",
                                description=__doc__)
    p.add_argument("paths", nargs="*", default=None,
                   help="files/directories to lint (default: tpuic/)")
    p.add_argument("--baseline", default=DEFAULT_BASELINE,
                   help="baseline JSON path (default: "
                        "analysis_baseline.json at the repo root)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding; exit 1 if any")
    p.add_argument("--write-baseline", action="store_true",
                   help="accept the current findings as the baseline")
    p.add_argument("--passes", default=",".join(PASSES),
                   help="comma-separated passes to run "
                        f"(default: {','.join(PASSES)})")
    p.add_argument("--select", default=None,
                   help="comma-separated rule IDs to run (default: all)")
    p.add_argument("--exclude", default="",
                   help="comma-separated path substrings to skip")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable findings")
    p.add_argument("--strict", action="store_true",
                   help="also fail on stale baseline entries")
    p.add_argument("--list-rules", action="store_true")
    args = p.parse_args(argv)

    if args.list_rules:
        for r in sorted(RULES.values(), key=lambda r: r.id):
            print(f"{r.id}  {r.name:<24} [{r.severity}]\n    {r.doc}")
        return 0

    paths = args.paths or [os.path.join(_REPO, "tpuic")]
    select = ([s.strip() for s in args.select.split(",") if s.strip()]
              if args.select else None)
    if select:
        unknown = [s for s in select if s not in RULES]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)}",
                  file=sys.stderr)
            return 2
    passes = [s.strip() for s in args.passes.split(",") if s.strip()]
    bad_passes = [s for s in passes if s not in PASSES]
    if bad_passes:
        print(f"unknown pass(es): {', '.join(bad_passes)} "
              f"(valid: {', '.join(PASSES)})", file=sys.stderr)
        return 2
    exclude = [e.strip() for e in args.exclude.split(",") if e.strip()]
    findings, files = analyze_paths(paths, exclude=exclude,
                                    select=select, passes=passes)

    if args.write_baseline:
        write_baseline(args.baseline, findings)
        print(f"baseline: {len(findings)} finding(s) across "
              f"{len(files)} file(s) written to {args.baseline}")
        return 0

    if args.no_baseline:
        _print_findings(findings, args.as_json)
        if not args.as_json:
            print(f"{len(findings)} finding(s) in {len(files)} file(s)")
        return 1 if findings else 0

    baseline = load_baseline(args.baseline)
    fresh, stale = new_findings(findings, baseline)
    _print_findings(fresh, args.as_json)
    if not args.as_json:
        tag = "" if os.path.exists(args.baseline) else " (no baseline file)"
        print(f"{len(fresh)} new finding(s) vs baseline{tag}; "
              f"{len(findings)} total in {len(files)} file(s); "
              f"{stale} stale baseline entr(y/ies)")
        if stale and not args.strict:
            print("  (stale entries are fixed debt — refresh with "
                  "--write-baseline)")
    if fresh:
        return 1
    if stale and args.strict:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
