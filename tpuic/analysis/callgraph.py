"""Project-wide parse + call graph: the shared substrate of the
multi-pass analyzers (conc/spmd/ctr — docs/analysis.md, "Pass
architecture").

One :class:`Project` parses every collected file exactly once and
indexes every function (including nested defs and methods, by
qualname).  Call resolution is deliberately conservative — an edge the
resolver is not sure about is an edge that does not exist:

- a bare name resolves through the lexical chain of enclosing defs,
  then module top-level functions, then project-module imports;
- ``self.method(...)`` resolves within the enclosing class only;
- ``obj.method(...)`` resolves only when ``method`` is defined exactly
  once in the whole project and is not on the common-name stoplist
  (``get``/``close``/``run``/... would wire the graph into soup).

Unresolved calls simply contribute no edges; the downstream rules are
precision-biased by construction (a cried-wolf deadlock report gets the
whole pass suppressed and protects nothing).
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tpuic.analysis.core import suppressions

# Method/function names too common to resolve by project-wide
# uniqueness: an attribute call on these creates no call edge.
COMMON_NAMES = frozenset({
    "get", "set", "put", "add", "pop", "open", "close", "run", "start",
    "stop", "join", "wait", "send", "recv", "read", "write", "flush",
    "items", "keys", "values", "append", "extend", "update", "copy",
    "clear", "submit", "result", "state", "snapshot", "reset", "render",
    "main", "info", "warning", "error", "debug", "exception", "publish",
    "subscribe", "install", "load", "save", "report", "name", "next",
    "format", "encode", "decode", "strip", "split", "setdefault",
})

_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Attribute/Name chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _module_dotted(path: str) -> str:
    """'tpuic.telemetry.events' for a file under the repo root; for
    files elsewhere (test fixture trees) the path is made relative to
    its own deepest package-looking ancestor, falling back to the bare
    stem — only cross-file *identity* matters, not importability."""
    p = os.path.normpath(os.path.abspath(path))
    try:
        rel = os.path.relpath(p, _ROOT)
    except ValueError:
        rel = ".."
    if rel.startswith(".."):
        # Fixture tree: synthesize from the trailing path components so
        # 'pkg/sub/mod.py' in a tmp dir still reads as 'pkg.sub.mod'.
        parts = p.replace("\\", "/").split("/")
        tail = parts[-3:] if len(parts) >= 3 else parts
        rel = "/".join(tail)
    rel = rel[:-3] if rel.endswith(".py") else rel
    return rel.replace("\\", "/").replace("/", ".")


@dataclasses.dataclass
class FuncInfo:
    """One def (top-level, method, or nested) in the project."""
    qualname: str                      # 'Class.method' / 'f.<locals>.g'
    name: str
    module: "ModuleInfo"
    node: ast.AST                      # FunctionDef / AsyncFunctionDef
    cls: Optional[str]                 # nearest enclosing class, if any
    parent: Optional["FuncInfo"]       # lexically enclosing def, if any
    local_defs: Dict[str, "FuncInfo"] = dataclasses.field(
        default_factory=dict)
    calls: List[ast.Call] = dataclasses.field(default_factory=list)

    def params(self) -> List[str]:
        a = self.node.args
        return [p.arg for p in a.posonlyargs] + [p.arg for p in a.args]

    def allowlisted(self, rule: str) -> bool:
        """Whether a '# tpuic-ok: RULE why' on this def line (or any
        enclosing def's) allowlists ``rule`` for the whole body — the
        same mechanism the lint pass's drain-site allowlist uses."""
        f: Optional[FuncInfo] = self
        while f is not None:
            ids = f.module.supp.get(f.node.lineno, "absent")
            if ids != "absent" and (ids is None or rule in ids):
                return True
            f = f.parent
        return False


class ModuleInfo:
    """One parsed file: tree, suppression map, per-module indexes."""

    def __init__(self, path: str, source: str) -> None:
        self.path = path.replace("\\", "/")
        self.dotted = _module_dotted(path)
        self.source = source
        self.lines = source.splitlines()
        self.supp = suppressions(source)
        self.tree: Optional[ast.Module] = None
        try:
            self.tree = ast.parse(source, filename=path)
        except SyntaxError:
            pass  # the lint pass reports TPU000; project passes skip it
        self.functions: Dict[str, FuncInfo] = {}   # by qualname
        self.toplevel: Dict[str, FuncInfo] = {}    # module-level defs
        self.classes: Dict[str, Dict[str, FuncInfo]] = {}
        self.imports: Dict[str, Tuple[str, Optional[str]]] = {}
        if self.tree is not None:
            self._index()

    def _index(self) -> None:
        for node in self.tree.body:
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.imports[a.asname or a.name.split(".")[0]] = (
                        a.name, None)
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    if a.name != "*":
                        self.imports[a.asname or a.name] = (node.module,
                                                            a.name)
        self._walk(self.tree.body, cls=None, parent=None, prefix="")

    def _walk(self, body: Sequence[ast.stmt], cls: Optional[str],
              parent: Optional[FuncInfo], prefix: str) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = prefix + stmt.name
                fi = FuncInfo(qual, stmt.name, self, stmt, cls, parent)
                self.functions[qual] = fi
                if parent is not None:
                    parent.local_defs[stmt.name] = fi
                elif cls is not None:
                    self.classes.setdefault(cls, {})[stmt.name] = fi
                else:
                    self.toplevel[stmt.name] = fi
                for n in ast.walk(stmt):
                    if isinstance(n, ast.Call):
                        fi.calls.append(n)
                self._walk(stmt.body, cls, fi,
                           qual + ".<locals>.")
            elif isinstance(stmt, ast.ClassDef):
                self._walk(stmt.body, stmt.name, parent,
                           prefix + stmt.name + ".")
            else:
                # defs nested in plain statements (if TYPE_CHECKING:...)
                for field in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, field, None)
                    if sub:
                        self._walk(sub, cls, parent, prefix)
                for h in getattr(stmt, "handlers", []) or []:
                    self._walk(h.body, cls, parent, prefix)


class Project:
    """Every module parsed once + global function index + resolution."""

    def __init__(self, files: Sequence[str],
                 sources: Optional[Dict[str, str]] = None) -> None:
        self.modules: Dict[str, ModuleInfo] = {}
        self.by_dotted: Dict[str, ModuleInfo] = {}
        for f in files:
            if sources is not None and f in sources:
                src = sources[f]
            else:
                with open(f, encoding="utf-8") as fh:
                    src = fh.read()
            m = ModuleInfo(f, src)
            self.modules[m.path] = m
            self.by_dotted[m.dotted] = m
        self.name_index: Dict[str, List[FuncInfo]] = {}
        for m in self.modules.values():
            for fi in m.functions.values():
                self.name_index.setdefault(fi.name, []).append(fi)

    # -- lookup --------------------------------------------------------
    def module_ending(self, suffix: str) -> Optional[ModuleInfo]:
        """The unique module whose path ends with ``suffix`` (e.g.
        'tpuic/telemetry/events.py'), else None."""
        hits = [m for m in self.modules.values()
                if m.path.endswith(suffix)]
        return hits[0] if len(hits) == 1 else None

    def funcs(self) -> Iterable[FuncInfo]:
        for m in self.modules.values():
            yield from m.functions.values()

    # -- call resolution ----------------------------------------------
    def resolve_name(self, caller: Optional[FuncInfo], mod: ModuleInfo,
                     name: str) -> Optional[FuncInfo]:
        f = caller
        while f is not None:
            if name in f.local_defs:
                return f.local_defs[name]
            f = f.parent
        if name in mod.toplevel:
            return mod.toplevel[name]
        imp = mod.imports.get(name)
        if imp is not None:
            src_mod, src_name = imp
            target = self.by_dotted.get(src_mod)
            if target is not None and src_name is not None:
                return target.toplevel.get(src_name)
        return None

    def resolve_call(self, caller: FuncInfo,
                     call: ast.Call) -> List[FuncInfo]:
        d = dotted(call.func)
        if d is None:
            return []
        parts = d.split(".")
        if len(parts) == 1:
            hit = self.resolve_name(caller, caller.module, parts[0])
            return [hit] if hit is not None else []
        if parts[0] == "self" and len(parts) == 2 and caller.cls:
            meth = caller.module.classes.get(caller.cls, {}).get(parts[1])
            if meth is not None:
                return [meth]
        tail = parts[-1]
        if tail in COMMON_NAMES:
            return []
        cands = self.name_index.get(tail, [])
        return list(cands) if len(cands) == 1 else []

    def reachable(self, roots: Iterable[FuncInfo]) -> List[FuncInfo]:
        """BFS closure over resolved call edges, roots included, in
        discovery order (stable for deterministic findings)."""
        seen: Set[int] = set()
        order: List[FuncInfo] = []
        queue = list(roots)
        while queue:
            fi = queue.pop(0)
            if id(fi) in seen:
                continue
            seen.add(id(fi))
            order.append(fi)
            for call in fi.calls:
                for callee in self.resolve_call(fi, call):
                    if id(callee) not in seen:
                        queue.append(callee)
        return order
