"""CONC — project-wide concurrency analysis (docs/analysis.md).

Three rules over the :class:`~tpuic.analysis.callgraph.Project` call
graph:

- **CONC101 lock-order-cycle**: every ``with <lock>:`` block contributes
  ordered edges L→M for each lock M acquired inside it (directly,
  lexically nested, or transitively through resolved calls).  A cycle in
  that graph is a potential deadlock the moment two threads run the two
  paths concurrently.  The finding is project-level (a cycle spans
  files) and fingerprints on the sorted edge set, not a line.
- **CONC102 signal-unsafe-call**: functions reachable from any
  ``signal.signal``/``faulthandler.register`` registration form the
  signal path.  Inside it, acquiring a project lock, publishing to the
  event bus, or mutating a *shared* (self-attribute) file handle is
  flagged — the handler may have interrupted the very frame that holds
  the lock / owns the handle (the PR-8 FlightRecorder deadlock,
  codified; its lock-free+bus-free ``dump()`` is the good fixture).
  Opening and writing a *local* file is fine — that is exactly what a
  dump-from-signal must do.
- **CONC103 unlocked-shared-closure**: a ``threading.Thread(target=f)``
  where the nested target ``f`` mutates a closure variable the spawning
  scope also mutates after the spawn, with neither side under a lock.

Lock identity is ``module::Class.attr`` for ``self._lock`` attributes,
``module::name`` for module globals, and ``module::func().name`` for
function locals.  ``threading.Condition(self._lock)`` aliases the
wrapped lock (waiting on the condition IS holding that lock).  A
``self.X`` / ``obj.X`` acquisition whose attribute name is defined as a
lock exactly once project-wide resolves to it; ambiguous receivers
contribute acquisition *sites* (CONC102) but no order edges.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tpuic.analysis.callgraph import FuncInfo, Project, dotted
from tpuic.analysis.core import Finding, Severity

_LOCK_CTORS = {"threading.Lock": False, "threading.RLock": True,
               "Lock": False, "RLock": True}
_COND_CTORS = {"threading.Condition", "Condition"}
_MUTATORS = {"append", "extend", "add", "update", "insert", "pop",
             "remove", "discard", "clear", "setdefault"}
_FH_MUTATORS = {"write", "writelines", "flush", "truncate"}


@dataclasses.dataclass(frozen=True)
class LockDef:
    key: str          # 'module::Class.attr' — the graph node identity
    attr: str         # bare attribute/variable name
    path: str
    line: int
    reentrant: bool


class _LockIndex:
    """Every lock/condition construction in the project + resolution of
    acquisition expressions back to lock identities."""

    def __init__(self, project: Project) -> None:
        self.project = project
        self.defs: Dict[str, LockDef] = {}
        self.by_attr: Dict[str, List[LockDef]] = {}
        for mod in project.modules.values():
            if mod.tree is not None:
                self._scan_module(mod)

    def _add(self, key: str, attr: str, path: str, line: int,
             reentrant: bool) -> LockDef:
        d = self.defs.get(key)
        if d is None:
            d = LockDef(key, attr, path, line, reentrant)
            self.defs[key] = d
            self.by_attr.setdefault(attr, []).append(d)
        return d

    def _scan_module(self, mod) -> None:
        # Walk with (class, function) context so `self._lock = Lock()`
        # lands on the right class even inside nested defs.
        def walk(body, cls: Optional[str], fn: Optional[str]) -> None:
            for stmt in body:
                if isinstance(stmt, ast.ClassDef):
                    walk(stmt.body, stmt.name, fn)
                    continue
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    walk(stmt.body, cls, fn or stmt.name)
                    continue
                if isinstance(stmt, ast.Assign) \
                        and isinstance(stmt.value, ast.Call):
                    self._scan_assign(mod, stmt, cls, fn)
                for field in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, field, None)
                    if sub:
                        walk(sub, cls, fn)
                for h in getattr(stmt, "handlers", []) or []:
                    walk(h.body, cls, fn)
        walk(mod.tree.body, None, None)

    def _target_key(self, mod, target: ast.AST, cls: Optional[str],
                    fn: Optional[str]) -> Optional[Tuple[str, str]]:
        """(graph key, bare attr name) for a lock-assignment target."""
        if isinstance(target, ast.Attribute) \
                and isinstance(target.value, ast.Name) \
                and target.value.id == "self" and cls is not None:
            return f"{mod.dotted}::{cls}.{target.attr}", target.attr
        if isinstance(target, ast.Name):
            if fn is None:
                return f"{mod.dotted}::{target.id}", target.id
            return f"{mod.dotted}::{fn}().{target.id}", target.id
        return None

    def _scan_assign(self, mod, stmt: ast.Assign, cls: Optional[str],
                     fn: Optional[str]) -> None:
        d = dotted(stmt.value.func)
        if d in _LOCK_CTORS:
            for t in stmt.targets:
                tk = self._target_key(mod, t, cls, fn)
                if tk is not None:
                    self._add(tk[0], tk[1], mod.path, stmt.lineno,
                              _LOCK_CTORS[d])
        elif d in _COND_CTORS:
            # Condition(self._lock) aliases the wrapped lock; a bare
            # Condition() owns a private (R)Lock of its own.
            args = stmt.value.args
            alias: Optional[LockDef] = None
            if args:
                src = args[0]
                if isinstance(src, ast.Attribute) \
                        and isinstance(src.value, ast.Name) \
                        and src.value.id == "self" and cls is not None:
                    alias = self.defs.get(
                        f"{mod.dotted}::{cls}.{src.attr}")
                elif isinstance(src, ast.Name):
                    alias = self.defs.get(f"{mod.dotted}::{src.id}")
            for t in stmt.targets:
                tk = self._target_key(mod, t, cls, fn)
                if tk is None:
                    continue
                if alias is not None:
                    self.defs[tk[0]] = alias  # same node, second name
                    self.by_attr.setdefault(tk[1], []).append(alias)
                else:
                    self._add(tk[0], tk[1], mod.path, stmt.lineno, True)

    def resolve(self, fi: FuncInfo, expr: ast.AST) -> Optional[LockDef]:
        """Lock identity for an acquisition expression, else None."""
        mod = fi.module
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name):
            if expr.value.id == "self" and fi.cls is not None:
                d = self.defs.get(f"{mod.dotted}::{fi.cls}.{expr.attr}")
                if d is not None:
                    return d
            cands = self.by_attr.get(expr.attr, [])
            uniq = {c.key: c for c in cands}
            if len(uniq) == 1:
                return next(iter(uniq.values()))
            return None
        if isinstance(expr, ast.Name):
            # Enclosing-function locals first, then module globals.
            f: Optional[FuncInfo] = fi
            while f is not None:
                d = self.defs.get(
                    f"{mod.dotted}::{f.name}().{expr.id}")
                if d is not None:
                    return d
                f = f.parent
            return self.defs.get(f"{mod.dotted}::{expr.id}")
        return None


def _acquisitions(index: _LockIndex, fi: FuncInfo
                  ) -> List[Tuple[LockDef, int]]:
    """Every lock acquisition in ``fi``'s own body (nested defs have
    their own FuncInfo): with-blocks and explicit .acquire() calls."""
    out: List[Tuple[LockDef, int]] = []
    for node in _own_nodes(fi):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                d = index.resolve(fi, item.context_expr)
                if d is not None:
                    out.append((d, node.lineno))
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "acquire":
            d = index.resolve(fi, node.func.value)
            if d is not None:
                out.append((d, node.lineno))
    return out


def _own_nodes(fi: FuncInfo) -> List[ast.AST]:
    """All nodes in fi's body excluding nested def/class bodies."""
    out: List[ast.AST] = []

    def rec(n: ast.AST) -> None:
        out.append(n)
        for c in ast.iter_child_nodes(n):
            if isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
                continue
            rec(c)
    for s in fi.node.body:
        rec(s)
    return out


def _transitive_acquires(project: Project, index: _LockIndex
                         ) -> Dict[int, Set[str]]:
    """id(FuncInfo) -> lock keys acquired by the function or anything it
    (transitively) calls.  Iterated to a fixpoint; graphs are small."""
    funcs = list(project.funcs())
    direct: Dict[int, Set[str]] = {
        id(f): {d.key for d, _ in _acquisitions(index, f)}
        for f in funcs}
    callees: Dict[int, List[int]] = {}
    for f in funcs:
        outs: List[int] = []
        for call in f.calls:
            outs.extend(id(c) for c in project.resolve_call(f, call))
        callees[id(f)] = outs
    acc = {k: set(v) for k, v in direct.items()}
    changed = True
    while changed:
        changed = False
        for f in funcs:
            s = acc[id(f)]
            before = len(s)
            for c in callees[id(f)]:
                s |= acc.get(c, set())
            if len(s) != before:
                changed = True
    return acc


# -- CONC101 ------------------------------------------------------------
def _lock_edges(project: Project, index: _LockIndex,
                trans: Dict[int, Set[str]]
                ) -> Dict[Tuple[str, str], Tuple[str, int, str]]:
    """(L, M) -> one representative (path, line, holder-qualname) where
    M is acquired (directly or via a resolved call) inside a with-block
    holding L."""
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    for fi in project.funcs():
        for node in _own_nodes(fi):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            outer = [index.resolve(fi, it.context_expr)
                     for it in node.items]
            outer = [d for d in outer if d is not None]
            if not outer:
                continue
            inner: List[Tuple[str, int]] = []
            for sub in ast.walk(node):
                if sub is node:
                    continue
                if isinstance(sub, (ast.With, ast.AsyncWith)):
                    for it in sub.items:
                        d = index.resolve(fi, it.context_expr)
                        if d is not None:
                            inner.append((d.key, sub.lineno))
                elif isinstance(sub, ast.Call):
                    if isinstance(sub.func, ast.Attribute) \
                            and sub.func.attr == "acquire":
                        d = index.resolve(fi, sub.func.value)
                        if d is not None:
                            inner.append((d.key, sub.lineno))
                    for callee in project.resolve_call(fi, sub):
                        for key in trans.get(id(callee), ()):
                            inner.append((key, sub.lineno))
            for L in outer:
                for key, line in inner:
                    if key == L.key:
                        continue
                    edges.setdefault((L.key, key),
                                     (fi.module.path, line, fi.qualname))
    return edges


def _cycles(edges: Iterable[Tuple[str, str]]) -> List[List[str]]:
    """Strongly connected components with >= 2 nodes (lock cycles)."""
    graph: Dict[str, List[str]] = {}
    for a, b in edges:
        graph.setdefault(a, []).append(b)
        graph.setdefault(b, [])
    idx: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on: Set[str] = set()
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]

    def strong(v: str) -> None:  # iterative Tarjan
        work = [(v, 0)]
        while work:
            node, pi = work[-1]
            if pi == 0:
                idx[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on.add(node)
            recursed = False
            for i in range(pi, len(graph[node])):
                w = graph[node][i]
                if w not in idx:
                    work[-1] = (node, i + 1)
                    work.append((w, 0))
                    recursed = True
                    break
                if w in on:
                    low[node] = min(low[node], idx[w])
            if recursed:
                continue
            if low[node] == idx[node]:
                comp: List[str] = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1:
                    out.append(sorted(comp))
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
    for v in sorted(graph):
        if v not in idx:
            strong(v)
    return out


# -- CONC102 ------------------------------------------------------------
def _signal_handlers(project: Project) -> List[Tuple[FuncInfo, str]]:
    """(handler FuncInfo, registration 'path:line') pairs for every
    ``signal.signal(sig, handler)`` with a resolvable handler.
    ``faulthandler.register`` takes no Python callable (C level), so it
    anchors the path-set but contributes no reachable functions."""
    out: List[Tuple[FuncInfo, str]] = []
    for fi in project.funcs():
        for call in fi.calls:
            if dotted(call.func) != "signal.signal" \
                    or len(call.args) < 2:
                continue
            h = call.args[1]
            target: Optional[FuncInfo] = None
            if isinstance(h, ast.Name):
                target = project.resolve_name(fi, fi.module, h.id)
            elif isinstance(h, ast.Attribute) \
                    and isinstance(h.value, ast.Name) \
                    and h.value.id == "self" and fi.cls is not None:
                target = fi.module.classes.get(fi.cls, {}).get(h.attr)
            if target is not None:
                out.append((target,
                            f"{fi.module.path}:{call.lineno}"))
    return out


def _conc102_violations(index: _LockIndex, fi: FuncInfo
                        ) -> List[Tuple[int, str]]:
    out: List[Tuple[int, str]] = []
    for d, line in _acquisitions(index, fi):
        out.append((line, f"acquires lock '{d.key}'"))
    for node in _own_nodes(fi):
        if not isinstance(node, ast.Call):
            continue
        d = dotted(node.func)
        if d is not None and d.split(".")[-1].endswith("publish"):
            out.append((node.lineno,
                        f"publishes to the event bus via {d}()"))
        elif isinstance(node.func, ast.Attribute) \
                and node.func.attr in _FH_MUTATORS \
                and isinstance(node.func.value, ast.Attribute) \
                and isinstance(node.func.value.value, ast.Name) \
                and node.func.value.value.id == "self":
            out.append((node.lineno,
                        f"mutates shared file handle "
                        f"'self.{node.func.value.attr}."
                        f"{node.func.attr}()'"))
    return out


# -- CONC103 ------------------------------------------------------------
def _thread_closure_races(index: _LockIndex, fi: FuncInfo
                          ) -> List[Tuple[int, str]]:
    """Thread(target=<nested def>) whose target and spawning scope both
    mutate one closure variable after the spawn, with no lock on either
    side."""
    out: List[Tuple[int, str]] = []
    own = _own_nodes(fi)
    lock_lines: List[Tuple[int, int]] = []  # guarded line spans
    for n in own:
        if isinstance(n, (ast.With, ast.AsyncWith)) and any(
                index.resolve(fi, it.context_expr) is not None
                for it in n.items):
            lock_lines.append((n.lineno,
                               getattr(n, "end_lineno", n.lineno)
                               or n.lineno))

    def guarded(line: int, spans=None) -> bool:
        for lo, hi in (spans if spans is not None else lock_lines):
            if lo <= line <= hi:
                return True
        return False

    for node in own:
        if not isinstance(node, ast.Call):
            continue
        if dotted(node.func) not in ("threading.Thread", "Thread"):
            continue
        target: Optional[FuncInfo] = None
        for kw in node.keywords:
            if kw.arg == "target" and isinstance(kw.value, ast.Name):
                target = fi.local_defs.get(kw.value.id)
        if target is None:
            continue
        t_params = set(target.params())
        t_spans = []
        for n in _own_nodes(target):
            if isinstance(n, (ast.With, ast.AsyncWith)) and any(
                    index.resolve(target, it.context_expr) is not None
                    for it in n.items):
                t_spans.append((n.lineno,
                                getattr(n, "end_lineno", n.lineno)
                                or n.lineno))
        t_mutated: Set[str] = set()
        for n in _own_nodes(target):
            name: Optional[str] = None
            if isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr in _MUTATORS \
                    and isinstance(n.func.value, ast.Name):
                name = n.func.value.id
            elif isinstance(n, (ast.Assign, ast.AugAssign)):
                tgt = n.targets[0] if isinstance(n, ast.Assign) \
                    else n.target
                if isinstance(tgt, ast.Subscript) \
                        and isinstance(tgt.value, ast.Name):
                    name = tgt.value.id
            if name is None or name in t_params or guarded(
                    n.lineno, t_spans):
                continue
            # Closure var only if the SPAWNING scope binds it.
            if any(isinstance(m, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == name
                    for t in m.targets) for m in own):
                t_mutated.add(name)
        if not t_mutated:
            continue
        for n in own:
            name = None
            if isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr in _MUTATORS \
                    and isinstance(n.func.value, ast.Name):
                name = n.func.value.id
            elif isinstance(n, (ast.Assign, ast.AugAssign)):
                tgt = n.targets[0] if isinstance(n, ast.Assign) \
                    else n.target
                if isinstance(tgt, ast.Subscript) \
                        and isinstance(tgt.value, ast.Name):
                    name = tgt.value.id
            if name in t_mutated and n.lineno > node.lineno \
                    and not guarded(n.lineno):
                out.append((node.lineno,
                            f"thread target '{target.name}' and the "
                            f"spawning scope both mutate '{name}' "
                            f"with no common lock"))
                break
    return out


# -- the pass -----------------------------------------------------------
def lock_order_edges(project: Project) -> Set[Tuple[str, str]]:
    """The static lock-order graph as (holder-key, acquired-key) pairs —
    the cross-check input for ``runtime.LockOrderWatch.check()``."""
    index = _LockIndex(project)
    trans = _transitive_acquires(project, index)
    return set(_lock_edges(project, index, trans).keys())


def run_conc(project: Project) -> List[Finding]:
    index = _LockIndex(project)
    trans = _transitive_acquires(project, index)
    findings: List[Finding] = []

    edges = _lock_edges(project, index, trans)
    for cycle in _cycles(edges.keys()):
        in_cycle = set(cycle)
        cyc_edges = sorted((a, b) for a, b in edges
                           if a in in_cycle and b in in_cycle)
        path, line, qual = edges[cyc_edges[0]]
        desc = ", ".join(f"{a} -> {b}" for a, b in cyc_edges)
        findings.append(Finding(
            "CONC101", Severity.ERROR, path, line,
            f"lock-order cycle ({desc}) — two threads taking these "
            f"locks in opposite orders deadlock; first edge closes in "
            f"{qual}()",
            fkey="conc101:" + ";".join(f"{a}->{b}"
                                       for a, b in cyc_edges)))

    seen_sites: Set[Tuple[str, int, str]] = set()
    for handler, reg in _signal_handlers(project):
        for fi in project.reachable([handler]):
            if fi.allowlisted("CONC102"):
                continue
            for line, what in _conc102_violations(index, fi):
                site = (fi.module.path, line, what)
                if site in seen_sites:
                    continue
                seen_sites.add(site)
                findings.append(Finding(
                    "CONC102", Severity.ERROR, fi.module.path, line,
                    f"{what} inside the signal path "
                    f"({handler.qualname}() registered at {reg}, "
                    f"reached via {fi.qualname}()) — the handler may "
                    f"have interrupted the frame that holds it; the "
                    f"signal path must stay lock-free and bus-free"))

    for fi in project.funcs():
        if fi.allowlisted("CONC103"):
            continue
        for line, msg in _thread_closure_races(index, fi):
            findings.append(Finding(
                "CONC103", Severity.WARNING, fi.module.path, line,
                msg + " — guard both sides or hand results over a "
                      "queue"))
    return findings
