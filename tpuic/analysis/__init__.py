"""Static + runtime guard rails for the JAX/TPU footguns this repo keeps
paying for (docs/analysis.md).

Two halves, one discipline:

- **Linter** (``python -m tpuic.analysis tpuic/``): AST rules for the
  hazard classes PRs 1-3 each debugged by hand — host syncs in hot-path
  modules, recompile hazards inside jitted functions, donation misuse
  (including the bisected cond+donation+compile-cache corruption),
  accidental float64 promotion, PRNG-key reuse — plus the generic
  hygiene rules (unused imports, dead code) that keep the tree clean.
  Findings are gated against a committed baseline
  (``analysis_baseline.json``): new violations fail CI, legacy ones are
  visible suppressions.
- **Runtime contract checkers** (``tpuic.analysis.runtime``): context
  managers + pytest fixtures asserting compile-count flatness after
  warmup, bounded device-transfer counts, and tracer-leak freedom over a
  block — the one shared home for the compile-counter asserts
  test_serve/test_faults/test_telemetry used to copy-paste, also run by
  the train/serve smoke scripts.
"""

from tpuic.analysis.core import (PASSES, Finding, Severity,
                                 analyze_paths, collect_files,
                                 lint_paths, lint_source)
from tpuic.analysis.rules import RULES, Rule
from tpuic.analysis.baseline import (fingerprint, load_baseline,
                                     new_findings, write_baseline)

__all__ = [
    "Finding", "Severity", "Rule", "RULES", "PASSES",
    "analyze_paths", "collect_files", "lint_paths", "lint_source",
    "fingerprint", "load_baseline", "new_findings", "write_baseline",
]
