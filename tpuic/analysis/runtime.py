"""Runtime contract checkers — the dynamic half of tpuic.analysis.

What the linter can't see statically, these assert at runtime, with one
shared API instead of the compile-counter monkeypatching test_serve /
test_faults / test_telemetry used to each reinvent (docs/analysis.md):

- ``watch_compiles()`` / ``assert_compiles_flat()``: XLA compile
  counting via a process-wide ``jax.monitoring`` listener.  The
  steady-state contract from PR 1-3: after warmup, a request stream or
  train loop performs ZERO further backend compiles.
- ``jit_cache_size(fn)`` / ``jit_cache_flat(*fns)``: per-function
  executable-cache flatness (the PR-2 skip-guard assertion style — one
  compiled program across skip and apply paths).
- ``count_device_gets()`` / ``bounded_device_gets(n)``: device->host
  transfer counting (the deferred-drain discipline: one batched get per
  log interval, nothing per step).
- ``no_tracer_leaks()``: ``jax.check_tracer_leaks`` over a block.

Every checker is host-side arithmetic over events jax already emits:
enabling them adds zero device syncs and zero compiles (asserted by
tests/test_analysis.py with the checkers nested inside each other —
the same on-vs-off discipline PR 2/3 applied to their own features).

All helpers import jax lazily so ``python -m tpuic.analysis`` (the
linter) stays importable and fast in environments without jax.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Dict, Iterator, Optional

# jax.monitoring key suffixes (jax 0.4.x): one trio per compilation —
# jaxpr_trace / jaxpr_to_mlir_module / backend_compile.  Retraces that
# hit the executable cache emit a lone jaxpr_trace, so backend_compile
# is THE "new executable built" signal.
_COMPILE_PREFIX = "/jax/core/compile/"
BACKEND_COMPILE = "backend_compile_duration"
JAXPR_TRACE = "jaxpr_trace_duration"


class _CompileMonitor:
    """Process-wide monotonic counters over jax.monitoring compile
    events.  jax has no listener unregister, so this installs exactly
    once and contexts snapshot/diff the counters."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._installed = False
        self.counts: Dict[str, int] = {}

    def install(self) -> bool:
        with self._lock:
            if self._installed:
                return True
            try:
                from jax import monitoring as _jm
            except Exception:
                return False

            def _listener(key: str, duration: float, **kw) -> None:
                if key.startswith(_COMPILE_PREFIX):
                    k = key[len(_COMPILE_PREFIX):]
                    with self._lock:
                        self.counts[k] = self.counts.get(k, 0) + 1

            _jm.register_event_duration_secs_listener(_listener)
            self._installed = True
            return True

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.counts)


_monitor = _CompileMonitor()


class CompileWatch:
    """Handle yielded by :func:`watch_compiles`: compile/trace deltas
    since the context opened.  Live while the context is open; frozen
    at context exit, so a watch handle read later reports only its own
    block, not whatever compiled after it."""

    def __init__(self) -> None:
        self._start = _monitor.snapshot()
        self._end: Optional[Dict[str, int]] = None

    def _freeze(self) -> None:
        self._end = _monitor.snapshot()

    def _delta(self, key: str) -> int:
        now = self._end if self._end is not None else _monitor.snapshot()
        return now.get(key, 0) - self._start.get(key, 0)

    @property
    def compiles(self) -> int:
        """New XLA executables built since the context opened."""
        return self._delta(BACKEND_COMPILE)

    @property
    def traces(self) -> int:
        """Jaxpr traces since the context opened (a retrace that hits
        the executable cache still counts here, not in ``compiles``)."""
        return self._delta(JAXPR_TRACE)


@contextlib.contextmanager
def watch_compiles() -> Iterator[CompileWatch]:
    """Observe (don't assert) compile activity over a block."""
    if not _monitor.install():
        raise RuntimeError("jax.monitoring unavailable — cannot watch "
                           "compiles")
    w = CompileWatch()
    try:
        yield w
    finally:
        w._freeze()


@contextlib.contextmanager
def assert_compiles_flat(max_new: int = 0, *,
                         what: str = "block") -> Iterator[CompileWatch]:
    """The steady-state contract: at most ``max_new`` (default zero) new
    XLA executables are built inside the block.  Warm up first; then
    every device call must be a cache hit."""
    with watch_compiles() as w:
        yield w
    got = w.compiles
    assert got <= max_new, (
        f"compile counter not flat over {what}: {got} new backend "
        f"compile(s) (allowed {max_new}) — a steady-state path is "
        "retracing/lowering; hunt the shape or Python-value dependence")


def jit_cache_size(fn) -> int:
    """Executable-cache entry count of a ``jax.jit``-wrapped callable
    (the PR-2 assertion: the guard's skip and apply paths share ONE
    compiled program, so this stays at 1)."""
    getter = getattr(fn, "_cache_size", None)
    if getter is None:
        raise TypeError(f"{fn!r} has no _cache_size — not a jit-wrapped "
                        "callable?")
    return getter()


@contextlib.contextmanager
def jit_cache_flat(*fns, max_new: int = 0) -> Iterator[None]:
    """Assert the given jitted callables gain at most ``max_new`` cache
    entries (combined) inside the block — zero recompiles by default."""
    before = sum(jit_cache_size(f) for f in fns)
    yield
    after = sum(jit_cache_size(f) for f in fns)
    assert after - before <= max_new, (
        f"jit cache grew {after - before} entr(y/ies) (allowed "
        f"{max_new}) across {len(fns)} function(s): a new input "
        "shape/dtype/static-arg combination retraced inside the block")


class DeviceGetCount:
    """Handle yielded by :func:`count_device_gets`."""

    def __init__(self) -> None:
        self.count = 0


@contextlib.contextmanager
def count_device_gets() -> Iterator[DeviceGetCount]:
    """Count ``jax.device_get`` calls over a block (the transfer-budget
    half of the deferred-drain discipline).  Patches ``jax.device_get``
    for the span — nest-safe, restored on exit."""
    import jax

    counter = DeviceGetCount()
    real_get = jax.device_get

    def counting_get(tree):
        counter.count += 1
        return real_get(tree)

    jax.device_get = counting_get
    try:
        yield counter
    finally:
        jax.device_get = real_get


@contextlib.contextmanager
def bounded_device_gets(max_gets: int, *,
                        what: str = "block") -> Iterator[DeviceGetCount]:
    """Assert at most ``max_gets`` device->host transfers in the block.

    The train loop's budget: one batched get per log interval (plus one
    step-counter read per epoch) — anything per-step is a regression to
    the 4-RTTs-per-log-point stall PERF_ANALYSIS round 4 measured."""
    with count_device_gets() as c:
        yield c
    assert c.count <= max_gets, (
        f"device transfer budget exceeded over {what}: {c.count} "
        f"jax.device_get call(s) (allowed {max_gets}) — a blocking "
        "readback crept onto the hot path")


@contextlib.contextmanager
def no_tracer_leaks() -> Iterator[None]:
    """``jax.check_tracer_leaks`` over a block: a tracer escaping its
    trace (stashed on self, closed over and mutated) raises instead of
    silently baking one trace's value into later calls."""
    import jax

    with jax.check_tracer_leaks():
        yield
