"""Runtime contract checkers — the dynamic half of tpuic.analysis.

What the linter can't see statically, these assert at runtime, with one
shared API instead of the compile-counter monkeypatching test_serve /
test_faults / test_telemetry used to each reinvent (docs/analysis.md):

- ``watch_compiles()`` / ``assert_compiles_flat()``: XLA compile
  counting via a process-wide ``jax.monitoring`` listener.  The
  steady-state contract from PR 1-3: after warmup, a request stream or
  train loop performs ZERO further backend compiles.
- ``jit_cache_size(fn)`` / ``jit_cache_flat(*fns)``: per-function
  executable-cache flatness (the PR-2 skip-guard assertion style — one
  compiled program across skip and apply paths).
- ``count_device_gets()`` / ``bounded_device_gets(n)``: device->host
  transfer counting (the deferred-drain discipline: one batched get per
  log interval, nothing per step).
- ``no_tracer_leaks()``: ``jax.check_tracer_leaks`` over a block.
- ``LockOrderWatch``: the dynamic half of the CONC101 lock-order rule —
  patches the ``threading.Lock``/``RLock`` factories for a block,
  records the *actual* acquisition-order edges per thread, and
  ``check()`` cross-checks them against the static lock graph: an
  observed edge that closes a cycle is a hard failure (a real deadlock
  the static pass under-approximated), a static edge never exercised is
  a stale warning (the graph — or the test — has drifted).

Every checker is host-side arithmetic over events jax already emits:
enabling them adds zero device syncs and zero compiles (asserted by
tests/test_analysis.py with the checkers nested inside each other —
the same on-vs-off discipline PR 2/3 applied to their own features).

All helpers import jax lazily so ``python -m tpuic.analysis`` (the
linter) stays importable and fast in environments without jax.
"""

from __future__ import annotations

import contextlib
import linecache
import os
import re
import sys
import threading
from typing import (Dict, Iterable, Iterator, List, Optional, Sequence,
                    Set, Tuple)

# jax.monitoring key suffixes (jax 0.4.x): one trio per compilation —
# jaxpr_trace / jaxpr_to_mlir_module / backend_compile.  Retraces that
# hit the executable cache emit a lone jaxpr_trace, so backend_compile
# is THE "new executable built" signal.
_COMPILE_PREFIX = "/jax/core/compile/"
BACKEND_COMPILE = "backend_compile_duration"
JAXPR_TRACE = "jaxpr_trace_duration"


class _CompileMonitor:
    """Process-wide monotonic counters over jax.monitoring compile
    events.  jax has no listener unregister, so this installs exactly
    once and contexts snapshot/diff the counters."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._installed = False
        self.counts: Dict[str, int] = {}

    def install(self) -> bool:
        with self._lock:
            if self._installed:
                return True
            try:
                from jax import monitoring as _jm
            except Exception:
                return False

            def _listener(key: str, duration: float, **kw) -> None:
                if key.startswith(_COMPILE_PREFIX):
                    k = key[len(_COMPILE_PREFIX):]
                    with self._lock:
                        self.counts[k] = self.counts.get(k, 0) + 1

            _jm.register_event_duration_secs_listener(_listener)
            self._installed = True
            return True

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.counts)


_monitor = _CompileMonitor()


class CompileWatch:
    """Handle yielded by :func:`watch_compiles`: compile/trace deltas
    since the context opened.  Live while the context is open; frozen
    at context exit, so a watch handle read later reports only its own
    block, not whatever compiled after it."""

    def __init__(self) -> None:
        self._start = _monitor.snapshot()
        self._end: Optional[Dict[str, int]] = None

    def _freeze(self) -> None:
        self._end = _monitor.snapshot()

    def _delta(self, key: str) -> int:
        now = self._end if self._end is not None else _monitor.snapshot()
        return now.get(key, 0) - self._start.get(key, 0)

    @property
    def compiles(self) -> int:
        """New XLA executables built since the context opened."""
        return self._delta(BACKEND_COMPILE)

    @property
    def traces(self) -> int:
        """Jaxpr traces since the context opened (a retrace that hits
        the executable cache still counts here, not in ``compiles``)."""
        return self._delta(JAXPR_TRACE)


@contextlib.contextmanager
def watch_compiles() -> Iterator[CompileWatch]:
    """Observe (don't assert) compile activity over a block."""
    if not _monitor.install():
        raise RuntimeError("jax.monitoring unavailable — cannot watch "
                           "compiles")
    w = CompileWatch()
    try:
        yield w
    finally:
        w._freeze()


@contextlib.contextmanager
def assert_compiles_flat(max_new: int = 0, *,
                         what: str = "block") -> Iterator[CompileWatch]:
    """The steady-state contract: at most ``max_new`` (default zero) new
    XLA executables are built inside the block.  Warm up first; then
    every device call must be a cache hit."""
    with watch_compiles() as w:
        yield w
    got = w.compiles
    assert got <= max_new, (
        f"compile counter not flat over {what}: {got} new backend "
        f"compile(s) (allowed {max_new}) — a steady-state path is "
        "retracing/lowering; hunt the shape or Python-value dependence")


def jit_cache_size(fn) -> int:
    """Executable-cache entry count of a ``jax.jit``-wrapped callable
    (the PR-2 assertion: the guard's skip and apply paths share ONE
    compiled program, so this stays at 1)."""
    getter = getattr(fn, "_cache_size", None)
    if getter is None:
        raise TypeError(f"{fn!r} has no _cache_size — not a jit-wrapped "
                        "callable?")
    return getter()


@contextlib.contextmanager
def jit_cache_flat(*fns, max_new: int = 0) -> Iterator[None]:
    """Assert the given jitted callables gain at most ``max_new`` cache
    entries (combined) inside the block — zero recompiles by default."""
    before = sum(jit_cache_size(f) for f in fns)
    yield
    after = sum(jit_cache_size(f) for f in fns)
    assert after - before <= max_new, (
        f"jit cache grew {after - before} entr(y/ies) (allowed "
        f"{max_new}) across {len(fns)} function(s): a new input "
        "shape/dtype/static-arg combination retraced inside the block")


class DeviceGetCount:
    """Handle yielded by :func:`count_device_gets`."""

    def __init__(self) -> None:
        self.count = 0


@contextlib.contextmanager
def count_device_gets() -> Iterator[DeviceGetCount]:
    """Count ``jax.device_get`` calls over a block (the transfer-budget
    half of the deferred-drain discipline).  Patches ``jax.device_get``
    for the span — nest-safe, restored on exit."""
    import jax

    counter = DeviceGetCount()
    real_get = jax.device_get

    def counting_get(tree):
        counter.count += 1
        return real_get(tree)

    jax.device_get = counting_get
    try:
        yield counter
    finally:
        jax.device_get = real_get


@contextlib.contextmanager
def bounded_device_gets(max_gets: int, *,
                        what: str = "block") -> Iterator[DeviceGetCount]:
    """Assert at most ``max_gets`` device->host transfers in the block.

    The train loop's budget: one batched get per log interval (plus one
    step-counter read per epoch) — anything per-step is a regression to
    the 4-RTTs-per-log-point stall PERF_ANALYSIS round 4 measured."""
    with count_device_gets() as c:
        yield c
    assert c.count <= max_gets, (
        f"device transfer budget exceeded over {what}: {c.count} "
        f"jax.device_get call(s) (allowed {max_gets}) — a blocking "
        "readback crept onto the hot path")


@contextlib.contextmanager
def no_tracer_leaks() -> Iterator[None]:
    """``jax.check_tracer_leaks`` over a block: a tracer escaping its
    trace (stashed on self, closed over and mutated) raises instead of
    silently baking one trace's value into later calls."""
    import jax

    with jax.check_tracer_leaks():
        yield


# -- lock-order watch ----------------------------------------------------

class LockOrderViolation(AssertionError):
    """An observed acquisition edge closed a cycle: two threads really
    did take the same locks in opposite orders inside the watched block
    — the deadlock the static CONC101 pass exists to prevent."""


_LOCK_NAME_RE = re.compile(r"(?:self\.)?(\w+)\s*(?::[^=]+)?=\s*threading")


class _WatchedLock:
    """Thin shim over a real Lock/RLock that reports acquisitions and
    releases to its :class:`LockOrderWatch`.  Everything else (including
    the ``_release_save``/``_acquire_restore``/``_is_owned`` trio
    ``threading.Condition`` borrows from RLocks) delegates to the real
    lock via ``__getattr__`` — a Condition built over a watched lock
    keeps working; its wait-window release is simply not tracked, which
    only ever *under*-reports edges, never invents one."""

    def __init__(self, real, name: str, watch: "LockOrderWatch") -> None:
        self._real = real
        self._name = name
        self._watch = watch

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._real.acquire(blocking, timeout)
        if got:
            self._watch._note_acquire(self._name)
        return got

    def release(self) -> None:
        self._watch._note_release(self._name)
        self._real.release()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __getattr__(self, attr):
        return getattr(self._real, attr)

    def __repr__(self) -> str:
        return f"<watched {self._name} {self._real!r}>"


def _edge_tail(key: str) -> str:
    """'_lock' for 'tpuic.serve.engine::Engine._lock' — the attr-name
    tail both the static keys and the runtime creation-site names end
    with, so the two vocabularies compare."""
    return key.rsplit("::", 1)[-1].rsplit(".", 1)[-1]


class LockOrderWatch:
    """Record the actual lock-acquisition order for a block.

    Patches the ``threading.Lock``/``threading.RLock`` factories so
    every lock *created inside the block* is a :class:`_WatchedLock`
    (pre-existing locks are untouched — watch the code under test by
    constructing it inside the block).  Each lock is named by its
    creation site (``module::attr`` via the assignment's source text),
    which is the same identity the static pass gives class-attribute
    locks, so ``check()`` can cross the two graphs.

    Per-thread held stacks turn every acquisition under a held lock
    into an edge; an edge whose reverse path already exists is recorded
    as a violation and raised by :meth:`check` (not inside ``acquire``
    — raising mid-acquire would leave the code under test half-locked).
    """

    def __init__(self) -> None:
        self._real_lock = threading.Lock
        self._real_rlock = threading.RLock
        self._mu = self._real_lock()      # guards edges/violations
        self._held = threading.local()
        self.edges: Dict[Tuple[str, str], str] = {}   # edge -> thread
        self.violations: List[str] = []

    # -- naming --------------------------------------------------------
    def _name_lock(self, kind: str) -> str:
        f = sys._getframe(2)
        while f is not None and f.f_globals.get("__name__") == __name__:
            f = f.f_back
        if f is None:  # unreachable in practice; keep a stable fallback
            return f"?::{kind}"
        line = linecache.getline(f.f_code.co_filename, f.f_lineno)
        m = _LOCK_NAME_RE.search(line)
        base = m.group(1) if m else \
            f"{kind}@{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"
        return f"{f.f_globals.get('__name__', '?')}::{base}"

    # -- recording -----------------------------------------------------
    def _stack(self) -> List[str]:
        try:
            return self._held.stack
        except AttributeError:
            self._held.stack = []
            return self._held.stack

    def _reaches(self, src: str, dst: str) -> bool:
        seen = {src}
        queue = [src]
        while queue:
            node = queue.pop()
            for (a, b) in self.edges:
                if a == node and b not in seen:
                    if b == dst:
                        return True
                    seen.add(b)
                    queue.append(b)
        return False

    def _note_acquire(self, name: str) -> None:
        stack = self._stack()
        # get_ident, not current_thread(): the latter builds a
        # _DummyThread (whose Event would be a watched lock → infinite
        # re-entry) when called from a thread mid-bootstrap.
        thread = f"tid={threading.get_ident()}"
        with self._mu:
            for held in stack:
                if held == name or (held, name) in self.edges:
                    continue
                # Reverse reachability BEFORE inserting: a path name->held
                # plus this edge held->name is an order inversion.
                if self._reaches(name, held):
                    self.violations.append(
                        f"{held} -> {name} (thread {thread}) closes a "
                        f"cycle with the already-observed reverse path")
                self.edges[(held, name)] = thread
        stack.append(name)

    def _note_release(self, name: str) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == name:
                del stack[i]
                break

    # -- patching ------------------------------------------------------
    def install(self) -> None:
        watch = self

        def make_lock():
            return _WatchedLock(watch._real_lock(),
                                watch._name_lock("Lock"), watch)

        def make_rlock():
            return _WatchedLock(watch._real_rlock(),
                                watch._name_lock("RLock"), watch)

        threading.Lock = make_lock
        threading.RLock = make_rlock

    def uninstall(self) -> None:
        threading.Lock = self._real_lock
        threading.RLock = self._real_rlock

    # -- the cross-check ----------------------------------------------
    def check(self, static_edges: Iterable[Tuple[str, str]] = ()
              ) -> List[str]:
        """Raise :class:`LockOrderViolation` if any observed edge closed
        a cycle; otherwise return the *stale* static edges — (A, B)
        pairs the static graph claims but this run never exercised
        (compared by attr-name tail, the vocabulary both sides share).
        Stale edges are warnings, not failures: the block may simply
        not drive that path — but a persistently stale edge means the
        static graph or the test has drifted."""
        if self.violations:
            raise LockOrderViolation(
                "lock-order inversion(s) observed at runtime:\n  " +
                "\n  ".join(self.violations))
        observed = {(_edge_tail(a), _edge_tail(b)) for a, b in self.edges}
        stale: List[str] = []
        for a, b in static_edges:
            if (_edge_tail(a), _edge_tail(b)) not in observed:
                stale.append(f"static edge {a} -> {b} never observed")
        return stale


@contextlib.contextmanager
def lock_order_watch() -> Iterator[LockOrderWatch]:
    """Watch lock creation + acquisition order over a block; calls
    ``check()`` (cycle detection only — pass static edges yourself for
    the drift half) on clean exit."""
    w = LockOrderWatch()
    w.install()
    try:
        yield w
    finally:
        w.uninstall()
    w.check()


def static_lock_edges(paths: Sequence[str]) -> Set[Tuple[str, str]]:
    """The static CONC101 lock graph for the given files/dirs — the
    ``check()`` input for cross-checking a runtime watch against what
    the analyzer believes (docs/analysis.md, "Runtime cross-check")."""
    from tpuic.analysis.callgraph import Project
    from tpuic.analysis.conc import lock_order_edges
    from tpuic.analysis.core import collect_files

    return lock_order_edges(Project(collect_files(paths)))
