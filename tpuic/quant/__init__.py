"""Post-training quantization for the serve tier (docs/performance.md,
"Quantized serving").

Two weight-only variants of a trained checkpoint, built at engine
start-up with zero retraining:

- **bf16**: every floating leaf cast to bfloat16 — half the weight HBM
  traffic; compute dtype is whatever the model was built with (flax
  promotes per-layer), so a bf16-dtype model gives full bf16 compute
  and an f32 model gives "bf16 storage, f32 math".
- **int8**: absmax **per-output-channel** symmetric quantization of
  every weight matrix/kernel (the last axis is the output channel in
  both flax layouts — Dense ``[in, out]`` and Conv ``[kh, kw, in,
  out]``): ``scale_c = absmax_c / 127``, ``q = round(w / scale)``.
  Biases, BN parameters and running stats stay float32 (they are a
  rounding error of the total bytes and carry the calibration).  The
  dequantize (``q.astype(f32) * scale``) happens *inside* the compiled
  program, so HBM holds int8 weights (4x smaller than f32) and XLA
  fuses the widening into each consumer.

The quantized tree swaps every quantized leaf for a
``{'q': int8, 'scale': f32}`` dict, so it rides ``jax.device_put`` /
the engine's variant plumbing like any other pytree;
:func:`dequantize_variables` restores the exact original structure for
``model.apply``.

**Accuracy gate** (the serve ladder's admission contract): a quantized
variant ships only when its top-1 predictions agree with fp32 on the
pinned synthetic eval set within a committed epsilon
(:func:`top1_agreement`, ``scripts/quant_gate.py``; CI runs it
bidirectionally — a seeded weight corruption must FAIL the same gate).
"""

from __future__ import annotations

from typing import Optional, Tuple

QUANT_LEAF = "__tpuic_int8__"   # marker key of a quantized leaf dict
DTYPE_TAGS = ("fp32", "bf16", "int8")
# The committed accuracy epsilon: a quantized ladder rung must agree
# with fp32 top-1 on at least (1 - epsilon) of the pinned eval set.
# 0.1 is sized to the PINNED gate workload (a seeded random-init model,
# whose near-zero logit margins make ~5% int8 top-1 flips intrinsic —
# measured 0.941 int8 / 0.980 bf16 agreement on the pinned seed; a
# trained checkpoint's margins put agreement well above 0.99).  The
# must-fail corruption arm lands at ~0.0 agreement, so the gate keeps
# a >9x firing margin both ways (scripts/quant_gate.py).
DEFAULT_EPSILON = 0.1


def absmax_quantize(w, axis: int = -1) -> Tuple[object, object]:
    """Symmetric per-channel int8: returns ``(q, scale)`` with
    ``q * scale ~= w``; ``scale`` keeps ``w``'s rank (size-1 axes) so
    the dequant is one broadcast multiply."""
    import jax.numpy as jnp

    w = jnp.asarray(w, jnp.float32)
    reduce_axes = tuple(i for i in range(w.ndim)
                        if i != (axis % w.ndim))
    absmax = jnp.max(jnp.abs(w), axis=reduce_axes, keepdims=True)
    scale = jnp.maximum(absmax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return q, scale


def _is_weight(name: str, leaf) -> bool:
    """Quantize matrix-shaped ``kernel``/``embedding`` leaves only: 1-D
    vectors (biases, BN scale/bias/stats, positional params) carry the
    model's calibration and are byte-trivial."""
    return (getattr(leaf, "ndim", 0) >= 2
            and name in ("kernel", "embedding"))


def quantize_variables(variables) -> dict:
    """Original variables tree -> the int8 tree the engine device_puts.

    Every quantizable leaf becomes ``{QUANT_LEAF: True-shaped marker,
    'q': int8, 'scale': f32}``; everything else (batch_stats included)
    is float32 passthrough."""
    def walk(node):
        if not isinstance(node, dict):
            return node
        out = {}
        for k, v in node.items():
            if not isinstance(v, dict) and _is_weight(k, v):
                q, s = absmax_quantize(v)
                out[k] = {QUANT_LEAF: 1, "q": q, "scale": s}
            else:
                out[k] = walk(v)
        return out
    return walk(dict(variables))


def dequantize_variables(qvars, dtype=None):
    """Inverse of :func:`quantize_variables`, run *inside* the compiled
    forward: int8 leaves widen to ``dtype`` (float32 default) via one
    fused multiply; passthrough leaves are returned untouched."""
    import jax.numpy as jnp

    dt = jnp.float32 if dtype is None else jnp.dtype(dtype)

    def walk(node):
        if isinstance(node, dict):
            if QUANT_LEAF in node:
                return (node["q"].astype(jnp.float32)
                        * node["scale"]).astype(dt)
            return {k: walk(v) for k, v in node.items()}
        return node
    return walk(qvars)


def bf16_variables(variables):
    """Cast every floating leaf to bfloat16 (weight-HBM halving; flax
    promotes per-layer according to the model's compute dtype)."""
    import jax
    import jax.numpy as jnp

    def cast(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(jnp.bfloat16)
        return x
    return jax.tree_util.tree_map(cast, variables)


def quantized_forward(forward_fn, dtype=None):
    """Wrap an engine forward so it accepts the int8 tree: dequantize
    (inside jit — the executable's inputs stay int8), then run."""
    def forward(qvariables, images):
        return forward_fn(dequantize_variables(qvariables, dtype), images)
    return forward


def serve_variants(model, variables, tags, *, normalize: bool = False,
                   mean=None, std=None) -> dict:
    """``{tag: (forward_fn, variables)}`` for the engine's dtype ladder.

    ``model`` + ``variables`` are the fp32 pair the checkpoint loader
    returns; each tag shares the model's forward (serve/engine.py
    ``make_forward``) with its own weight representation.  Unknown tags
    raise up front — a typo'd ladder must fail the CLI, not serve fp32
    under an int8 label."""
    from tpuic.serve.engine import make_forward

    base = make_forward(model, normalize=normalize, mean=mean, std=std)
    out = {}
    for tag in tags:
        if tag == "fp32":
            out[tag] = (base, variables)
        elif tag == "bf16":
            out[tag] = (base, bf16_variables(variables))
        elif tag == "int8":
            out[tag] = (quantized_forward(base),
                        quantize_variables(variables))
        else:
            raise ValueError(f"unknown serve dtype {tag!r}; "
                             f"supported: {DTYPE_TAGS}")
    return out


def corrupt_variables(variables, seed: int = 0, factor: float = 12.0):
    """Seeded weight corruption for the accuracy gate's must-fail arm:
    every quantizable kernel gets additive Gaussian noise at ``factor``
    times its own std, drawn from a per-leaf key (the leaf's tree path
    folded into ``seed``) — big enough to flip predictions,
    deterministic so the CI proof is reproducible."""
    import zlib

    import jax
    import jax.numpy as jnp

    def walk(node, path=()):
        if isinstance(node, dict):
            return {k: walk(v, path + (k,)) for k, v in node.items()}
        if _is_weight(path[-1] if path else "", node):
            k = jax.random.fold_in(
                jax.random.key(seed),
                zlib.crc32("/".join(path).encode()) & 0x7FFFFFFF)
            noise = jax.random.normal(k, node.shape, jnp.float32)
            return node + factor * jnp.std(node) * noise
        return node
    return walk(dict(variables))


def top1_agreement(forward_a, vars_a, forward_b, vars_b, images,
                   batch: int = 32) -> float:
    """Fraction of the pinned eval images on which the two forwards
    agree on the top-1 class — the accuracy-delta statistic the ladder
    gate compares against the committed epsilon.  ``forward_*`` follow
    the engine contract (``(probs, order)`` pytrees); images is
    [N, S, S, C]."""
    import numpy as np

    n = images.shape[0]
    agree = 0
    for lo in range(0, n, batch):
        chunk = images[lo:lo + batch]
        _, oa = forward_a(vars_a, chunk)
        _, ob = forward_b(vars_b, chunk)
        agree += int(np.sum(np.asarray(oa)[:, 0] == np.asarray(ob)[:, 0]))
    return agree / max(1, n)


def eval_images(n: int = 256, size: int = 24, seed: int = 0,
                dtype="uint8"):
    """THE pinned synthetic eval set (seeded, shared by the CI gate,
    bench_serve's ladder gate, and the tests): uniform uint8 images —
    deterministic across machines, no dataset dependency."""
    import numpy as np

    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, (n, size, size, 3)).astype(dtype)
