"""Classifier = backbone + MLP head.

Re-design of reference nn/classifier.py:7-37: ``Classifier(name, num_classes)``
selects a backbone by string and replaces its final FC with the
in->128->64->32->n MLP head (nn/classifier.py:26-34). Differences by design:

- The reference mutates ``encoder.fc`` in place; here backbone and head are
  separate submodules (``backbone``, ``head``) — the converter maps torch's
  ``encoder.fc.*`` onto ``head`` when importing checkpoints.
- The reference's efficientnet branch is broken (sets ``fc`` on a model whose
  attr is ``_fc``, nn/classifier.py:17-18+27 — AttributeError); here the
  intended behavior is implemented.
- Inception-v3's aux head (nn/classifier.py:22-23) surfaces as a second logits
  output in train mode, consumed by the 0.4-weighted aux loss (train.py:48-52).
"""

from __future__ import annotations

from typing import Any, Sequence

import jax.numpy as jnp
from flax import linen as nn

from tpuic.models.layers import MLPHead


class Classifier(nn.Module):
    backbone: nn.Module
    num_classes: int
    head_widths: Sequence[int] = (128, 64, 32)
    has_aux: bool = False
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, images: jnp.ndarray, train: bool = False):
        """images: [B, H, W, 3] float32 (normalized). Returns logits [B, C];
        inception in train mode returns (logits, aux_logits)."""
        out = self.backbone(images, train=train)
        aux = None
        if isinstance(out, tuple):
            out, aux = out
        logits = MLPHead(self.num_classes, self.head_widths, dtype=self.dtype,
                         param_dtype=self.param_dtype, name="head")(out)
        if self.has_aux and train:
            return logits, aux
        return logits
