"""Vision Transformer (ViT-B/16 and friends) as Flax modules.

BASELINE.md parity config 4: 'ViT-B/16 / ImageNet (attention path, exercises
XLA SPMD)'. The reference has no attention model; this is the build's
attention-bearing backbone, designed mesh-aware from the start:

- Attention and MLP dense kernels carry flax logical-axis partitioning
  metadata (('embed','model') on up-projections, ('model','embed') on
  down-projections), so tensor parallelism over the mesh's ``model`` axis is
  Megatron-style: QKV/up sharded on heads/hidden, out/down sharded on the
  input dim, with XLA inserting the psum on the second contraction.
- Sequence length for 224² at patch 16 is a fixed 197 tokens (SURVEY.md §5:
  no ring/context parallelism needed at this scale; the token axis is simply
  a named dim a future ``seq`` mesh axis can shard).
- bfloat16 activations; attention softmax in float32 for stability.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn


def _dense(features, name, dtype, param_dtype, logical):
    return nn.Dense(
        features, dtype=dtype, param_dtype=param_dtype, name=name,
        kernel_init=nn.with_logical_partitioning(
            nn.initializers.xavier_uniform(), logical),
    )


ATTENTION_IMPLS = ("dense", "flash", "ring", "ring-flash", "ulysses",
                   "ulysses-flash")


class MlpUpGelu(nn.Module):
    """Dense(mlp_up) + exact GELU as one rematerializable region
    (ModelConfig.remat_policy='gelu').

    Under ``nn.remat`` nothing inside the region is saved: the [B,N,4D]
    pre-activation — together with its dtype-cast copies and the erf-vjp
    internals, which a step-level names policy demonstrably still saves
    (print_saved_residuals; see resolve_remat_policy's note) — never
    becomes a residual. The backward recomputes W1·x + gelu from the
    [B,N,D] region input; the only 4D-wide residual left is the region
    OUTPUT, which mlp_down's backward needs regardless. Targets the
    dual-output mlp_up fusion writes the ViT-B b64 profile fingered as
    the largest single contributor to the 0.537-vs-0.70 MFU gap
    (PERF_ANALYSIS.md §10f).

    The math and the param layout replicate the ``nn.Dense`` this
    replaces (kernel/bias under the same module name, same init, same
    dtype promotion, exact erf GELU) so checkpoints, the torch
    converter, and sharding rules are unaffected."""

    features: int
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        kernel = self.param(
            "kernel",
            nn.with_logical_partitioning(nn.initializers.xavier_uniform(),
                                         ("embed", "model")),
            (x.shape[-1], self.features), self.param_dtype)
        bias = self.param("bias", nn.initializers.zeros_init(),
                          (self.features,), self.param_dtype)
        x, kernel, bias = nn.dtypes.promote_dtype(x, kernel, bias,
                                                  dtype=self.dtype)
        return nn.gelu(x @ kernel + bias, approximate=False)


class MultiHeadAttention(nn.Module):
    num_heads: int
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    # 'dense': einsum + f32 softmax. 'flash': Pallas blockwise online-softmax
    # kernels, forward AND backward — neither materializes the [N,N]
    # probability matrix (tpuic/kernels/flash_attention.py).
    # 'ring': sequence-parallel ring attention over the mesh's 'seq' axis
    # (tpuic/parallel/ring_attention.py) — K/V blocks rotate via ppermute.
    # 'ring-flash': the ring with the Pallas flash kernel as its per-step
    # block primitive (long-context: no dense score tile per step).
    # 'ulysses': sequence parallelism via all-to-all head redistribution
    # (tpuic/parallel/ulysses.py) — needs heads % seq-axis == 0.
    # All fall back to 'dense' numerics when the mesh has no seq sharding.
    attention: str = "dense"
    # Device mesh: keeps the flash kernel batch-parallel under a sharded jit
    # (shard_map over the 'data' axis) and carries the 'seq' axis for ring
    # attention; None => single-device pallas_call / dense.
    mesh: Any = None
    # Selective remat (ModelConfig.remat_policy='attention'): wrap the dense
    # logits->softmax->probs@v core in jax.checkpoint, so the ONLY saved
    # residuals are q/k/v ([B,N,H,Dh], linear in N) and the backward
    # recomputes one einsum + softmax per layer. This is done here at the
    # module level, not with checkpoint_name tags + a names policy in the
    # train step: softmax's own backward wants its (un-nameable, internal)
    # output, so a save-anything-except-names policy still saves quadratic
    # precision-cast copies of it — measured via print_saved_residuals.
    # No effect on the flash/ring/ulysses paths (no [N,N] tensor to drop).
    remat_core: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool) -> jnp.ndarray:
        if self.attention not in ATTENTION_IMPLS:
            raise ValueError(f"unknown attention impl '{self.attention}'; "
                             f"available: {ATTENTION_IMPLS}")
        d = x.shape[-1]
        head_dim = d // self.num_heads
        qkv = _dense(3 * d, "qkv", self.dtype, self.param_dtype,
                     ("embed", "model"))(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(t.shape[0], t.shape[1], self.num_heads, head_dim)

        q, k, v = heads(q), heads(k), heads(v)
        if self.attention == "flash":
            from tpuic.kernels import flash_attention
            # None block sizes -> length-adaptive (one k-pass at ViT's
            # N=197; 512-blocks at long N to amortize grid overhead).
            out = flash_attention(q, k, v, None, None, None, self.mesh)
        elif (self.attention == "ring" and self.mesh is not None
              and self.mesh.shape.get("seq", 1) > 1):
            from tpuic.parallel import ring_attention
            out = ring_attention(q, k, v, self.mesh)
        elif (self.attention == "ring-flash" and self.mesh is not None
              and self.mesh.shape.get("seq", 1) > 1):
            # Ring SP with the Pallas flash kernel as the per-step block
            # primitive: O(N/P · D) activations instead of the dense
            # ring's O(N/P · N/P) score tile.
            from tpuic.parallel import ring_flash_attention
            out = ring_flash_attention(q, k, v, self.mesh)
        elif (self.attention in ("ulysses", "ulysses-flash")
              and self.mesh is not None
              and self.mesh.shape.get("seq", 1) > 1):
            from tpuic.parallel import ulysses_attention
            out = ulysses_attention(
                q, k, v, self.mesh,
                use_flash=self.attention == "ulysses-flash")
        else:
            scale = 1.0 / np.sqrt(head_dim)

            @jax.named_scope("attention_core")
            def core(q, k, v):
                logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
                probs = nn.softmax(logits.astype(jnp.float32),
                                   axis=-1).astype(self.dtype)
                return jnp.einsum("bhqk,bkhd->bqhd", probs, v)

            if self.remat_core:
                # Drop every [B,H,N,N] intermediate — the tensors that
                # dominate ViT activation memory past b64
                # (PERF_ANALYSIS.md §10b). See the remat_core field note.
                core = jax.checkpoint(core)
            out = core(q, k, v)
        out = out.reshape(out.shape[0], out.shape[1], d)
        return _dense(d, "out", self.dtype, self.param_dtype,
                      ("model", "embed"))(out)


class EncoderBlock(nn.Module):
    num_heads: int
    mlp_ratio: int = 4
    dropout: float = 0.0
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    attention: str = "dense"
    mesh: Any = None
    # >0 replaces this block's dense MLP with a Switch MoE of that many
    # experts (models/moe.py) — expert-parallel over the mesh 'model' axis.
    moe_experts: int = 0
    # Stochastic depth (Huang et al., 2016; standard in ViT recipes): in
    # train mode each residual BRANCH is dropped per-sample with this
    # probability and survivors are rescaled by 1/keep. The [B,1,1] mask
    # broadcasts — one bernoulli per sample, not per activation — so the
    # op fuses into the residual add (no extra HBM pass).
    drop_path: float = 0.0
    # See MultiHeadAttention.remat_core.
    remat_core: bool = False
    # See MlpUpGelu (ModelConfig.remat_policy='gelu').
    remat_mlp: bool = False

    def _residual(self, x: jnp.ndarray, y: jnp.ndarray,
                  deterministic: bool) -> jnp.ndarray:
        if deterministic or self.drop_path == 0.0:
            return x + y
        keep = 1.0 - self.drop_path
        mask = jax.random.bernoulli(self.make_rng("dropout"), keep,
                                    (y.shape[0], 1, 1))
        # max() guards the degenerate rate 1.0 (keep=0 -> 0/0 = NaN; the
        # mask is all-False there, so the scale value is never used).
        return x + y * (mask.astype(y.dtype) / max(keep, 1e-6))

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool) -> jnp.ndarray:
        d = x.shape[-1]
        y = nn.LayerNorm(dtype=self.dtype, param_dtype=self.param_dtype,
                         name="ln1")(x)
        y = MultiHeadAttention(self.num_heads, self.dtype, self.param_dtype,
                               self.attention, self.mesh,
                               remat_core=self.remat_core,
                               name="attn")(y, deterministic)
        if self.dropout:
            y = nn.Dropout(self.dropout)(y, deterministic=deterministic)
        x = self._residual(x, y, deterministic)
        y = nn.LayerNorm(dtype=self.dtype, param_dtype=self.param_dtype,
                         name="ln2")(x)
        if self.moe_experts:
            from tpuic.models.moe import SwitchMoEMlp
            y = SwitchMoEMlp(self.moe_experts, self.mlp_ratio,
                             dtype=self.dtype, param_dtype=self.param_dtype,
                             name="moe")(y, deterministic)
        else:
            up_cls = (nn.remat(MlpUpGelu) if self.remat_mlp else MlpUpGelu)
            y = up_cls(d * self.mlp_ratio, self.dtype, self.param_dtype,
                       name="mlp_up")(y)
            y = _dense(d, "mlp_down", self.dtype, self.param_dtype,
                       ("model", "embed"))(y)
        if self.dropout:
            y = nn.Dropout(self.dropout)(y, deterministic=deterministic)
        return self._residual(x, y, deterministic)


class ViT(nn.Module):
    """Returns the CLS-token feature [B, hidden]."""

    patch: int = 16
    hidden: int = 768
    depth: int = 12
    num_heads: int = 12
    mlp_ratio: int = 4
    dropout: float = 0.0
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    attention: str = "dense"
    mesh: Any = None
    # MoE: every ``moe_every``-th block (odd blocks, GShard/Switch
    # convention) uses a SwitchMoEMlp with ``moe_experts`` experts.
    moe_experts: int = 0
    moe_every: int = 2
    # Stochastic-depth rate of the LAST block; per-block rates ramp
    # linearly from 0 (the standard DeiT schedule).
    drop_path: float = 0.0
    # See MultiHeadAttention.remat_core.
    remat_core: bool = False
    # Per-block remat (ModelConfig.remat_policy='blocks'): every encoder
    # block runs under nn.remat with the default save-nothing policy, so
    # the only sequence-length-sized residuals are the 12 block INPUTS
    # ([B,N,D], ~100 MB at b16/N=4097) and the backward recomputes one
    # block at a time. This is the long-context memory mode: at N=4097 the
    # 'dots' policy OOMs by saving every [B,N,4D] mlp_up output (4.6 GB)
    # plus attention outputs — measured 19.5 GB vs 15.75 HBM
    # (PERF_ANALYSIS.md §10f). Composes with any attention impl; with
    # 'flash' the per-block recompute peak is O(N·D), which is what lets
    # flash train through shapes where dense cannot even rematerialize.
    remat_blocks: bool = False
    # See MlpUpGelu (ModelConfig.remat_policy='gelu').
    remat_mlp: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        B = x.shape[0]
        x = x.astype(self.dtype)
        # 'tokenize' names the patchify/cls/pos phase for the device-time
        # waterfall (telemetry/profile.py); the encoder blocks below are
        # already scoped by their flax module names (blockN).
        with jax.named_scope("tokenize"):
            x = nn.Conv(self.hidden, (self.patch, self.patch),
                        strides=(self.patch, self.patch), dtype=self.dtype,
                        param_dtype=self.param_dtype, name="patch_embed")(x)
            x = x.reshape(B, -1, self.hidden)  # [B, N, D]
            cls = self.param("cls", nn.initializers.zeros,
                             (1, 1, self.hidden), self.param_dtype)
            x = jnp.concatenate(
                [jnp.broadcast_to(cls, (B, 1, self.hidden)
                                  ).astype(self.dtype), x], axis=1)
            pos = self.param("pos_embed", nn.initializers.normal(0.02),
                             (1, x.shape[1], self.hidden), self.param_dtype)
            x = x + pos.astype(self.dtype)
        # static_argnums counts self: (self, x, deterministic) -> 2.
        block_cls = (nn.remat(EncoderBlock, static_argnums=(2,))
                     if self.remat_blocks else EncoderBlock)
        for i in range(self.depth):
            moe = (self.moe_experts
                   if self.moe_experts
                   and i % self.moe_every == self.moe_every - 1 else 0)
            dp = (self.drop_path * i / max(1, self.depth - 1)
                  if self.drop_path else 0.0)
            x = block_cls(self.num_heads, self.mlp_ratio, self.dropout,
                          self.dtype, self.param_dtype, self.attention,
                          self.mesh, moe, dp,
                          remat_core=self.remat_core,
                          remat_mlp=self.remat_mlp,
                          name=f"block{i}")(x, not train)
        with jax.named_scope("cls_pool"):
            x = nn.LayerNorm(dtype=self.dtype, param_dtype=self.param_dtype,
                             name="ln_final")(x)
            return x[:, 0].astype(jnp.float32)


def vit_b16(**kw) -> ViT:
    return ViT(patch=16, hidden=768, depth=12, num_heads=12, **kw)


def vit_l16(**kw) -> ViT:
    return ViT(patch=16, hidden=1024, depth=24, num_heads=16, **kw)


def vit_b32(**kw) -> ViT:
    """Patch-32 base: 4x fewer tokens (50 at 224px) — the cheap-inference
    point of the torchvision ViT family (vit_b_32)."""
    return ViT(patch=32, hidden=768, depth=12, num_heads=12, **kw)


def vit_l32(**kw) -> ViT:
    return ViT(patch=32, hidden=1024, depth=24, num_heads=16, **kw)


def vit_s16(**kw) -> ViT:
    return ViT(patch=16, hidden=384, depth=12, num_heads=6, **kw)


def vit_tiny(**kw) -> ViT:
    """Test-scale ViT (fast CI)."""
    return ViT(patch=4, hidden=64, depth=2, num_heads=4, **kw)


def vit_s16_moe(**kw) -> ViT:
    """ViT-S/16 with 8-expert Switch MoE in every other block."""
    return ViT(patch=16, hidden=384, depth=12, num_heads=6, moe_experts=8,
               **kw)


def vit_tiny_moe(**kw) -> ViT:
    """Test-scale MoE ViT (fast CI; 4 experts, MoE in block 1)."""
    return ViT(patch=4, hidden=64, depth=2, num_heads=4, moe_experts=4, **kw)
