"""EfficientNet family (B0-B7) as Flax modules.

Capability parity with the reference's 'efficientnet-b3' branch
(nn/classifier.py:17-18, via the efficientnet_pytorch package) and the
BASELINE.md parity config 3 (EfficientNet-B0). Note the reference's branch is
actually broken — it sets ``.fc`` on a model whose head attribute is ``._fc``
(nn/classifier.py:27 would AttributeError); here the intended behavior works.

Architecture follows the EfficientNet paper (Tan & Le 2019): MBConv blocks
(expand 1x1 → depthwise kxk → squeeze-excite → project 1x1) with compound
width/depth scaling, swish activation, and stochastic depth. TPU notes:
depthwise convs via ``feature_group_count`` lower to XLA's native depthwise
path; SE pooling is a cheap global mean that XLA fuses.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from tpuic.models.layers import batch_norm

# (expand_ratio, channels, num_blocks, stride, kernel)  — B0 base config.
_BASE_BLOCKS: Tuple[Tuple[int, int, int, int, int], ...] = (
    (1, 16, 1, 1, 3),
    (6, 24, 2, 2, 3),
    (6, 40, 2, 2, 5),
    (6, 80, 3, 2, 3),
    (6, 112, 3, 1, 5),
    (6, 192, 4, 2, 5),
    (6, 320, 1, 1, 3),
)

# name -> (width_mult, depth_mult, dropout) — the published compound-scaling
# coefficients (EfficientNet paper, Table; matches efficientnet_pytorch).
_SCALING = {
    "b0": (1.0, 1.0, 0.2),
    "b1": (1.0, 1.1, 0.2),
    "b2": (1.1, 1.2, 0.3),
    "b3": (1.2, 1.4, 0.3),
    "b4": (1.4, 1.8, 0.4),
    "b5": (1.6, 2.2, 0.4),
    "b6": (1.8, 2.6, 0.5),
    "b7": (2.0, 3.1, 0.5),
}


def _round_filters(filters: int, width_mult: float, divisor: int = 8) -> int:
    filters *= width_mult
    new = max(divisor, int(filters + divisor / 2) // divisor * divisor)
    if new < 0.9 * filters:
        new += divisor
    return int(new)


def _round_repeats(repeats: int, depth_mult: float) -> int:
    return int(math.ceil(depth_mult * repeats))


class SqueezeExcite(nn.Module):
    features: int
    se_features: int
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        s = jnp.mean(x, axis=(1, 2), keepdims=True)
        s = nn.Conv(self.se_features, (1, 1), dtype=self.dtype,
                    param_dtype=self.param_dtype, name="reduce")(s)
        s = nn.swish(s)
        s = nn.Conv(self.features, (1, 1), dtype=self.dtype,
                    param_dtype=self.param_dtype, name="expand")(s)
        return x * nn.sigmoid(s)


class MBConv(nn.Module):
    in_features: int
    out_features: int
    expand_ratio: int
    strides: int
    kernel: int
    drop_rate: float = 0.0
    se_ratio: float = 0.25
    bn_momentum: float = 0.9
    bn_eps: float = 1e-3  # torch EfficientNet uses 1e-3
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool) -> jnp.ndarray:
        bn = partial(batch_norm, train, momentum=self.bn_momentum,
                     eps=self.bn_eps, dtype=self.dtype,
                     param_dtype=self.param_dtype)
        kw = dict(dtype=self.dtype, param_dtype=self.param_dtype)
        residual = x
        mid = self.in_features * self.expand_ratio
        y = x
        if self.expand_ratio != 1:
            y = nn.Conv(mid, (1, 1), use_bias=False, **kw, name="expand_conv")(y)
            y = nn.swish(bn(name="expand_bn")(y))
        # TF-style SAME padding (asymmetric on stride-2) — matches the
        # efficientnet_pytorch package's Conv2dStaticSamePadding, so torch
        # checkpoints convert with exact forward parity.
        y = nn.Conv(mid, (self.kernel, self.kernel),
                    strides=(self.strides, self.strides),
                    padding="SAME", feature_group_count=mid,
                    use_bias=False, **kw, name="dw_conv")(y)
        y = nn.swish(bn(name="dw_bn")(y))
        y = SqueezeExcite(mid, max(1, int(self.in_features * self.se_ratio)),
                          **kw, name="se")(y)
        y = nn.Conv(self.out_features, (1, 1), use_bias=False, **kw,
                    name="project_conv")(y)
        y = bn(name="project_bn")(y)
        if self.strides == 1 and self.in_features == self.out_features:
            if train and self.drop_rate > 0.0:
                # Stochastic depth (per-sample drop-path).
                import jax
                keep = 1.0 - self.drop_rate
                rng = self.make_rng("dropout")
                shape = (y.shape[0],) + (1,) * (y.ndim - 1)
                mask = jax.random.bernoulli(rng, keep, shape).astype(y.dtype)
                y = y * mask / keep
            y = y + residual
        return y


class EfficientNet(nn.Module):
    """Returns pooled features [B, F]."""

    width_mult: float = 1.0
    depth_mult: float = 1.0
    drop_path_rate: float = 0.2
    bn_momentum: float = 0.9
    bn_eps: float = 1e-3
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        kw = dict(dtype=self.dtype, param_dtype=self.param_dtype)
        bn = partial(batch_norm, train, momentum=self.bn_momentum,
                     eps=self.bn_eps, **kw)
        x = x.astype(self.dtype)
        stem = _round_filters(32, self.width_mult)
        x = nn.Conv(stem, (3, 3), strides=(2, 2), padding="SAME",
                    use_bias=False, **kw, name="stem_conv")(x)
        x = nn.swish(bn(name="stem_bn")(x))
        in_f = stem
        total_blocks = sum(_round_repeats(r, self.depth_mult)
                           for _, _, r, _, _ in _BASE_BLOCKS)
        bi = 0
        for si, (expand, ch, repeats, stride, kernel) in enumerate(_BASE_BLOCKS):
            out_f = _round_filters(ch, self.width_mult)
            for r in range(_round_repeats(repeats, self.depth_mult)):
                drop = self.drop_path_rate * bi / max(1, total_blocks)
                x = MBConv(in_f, out_f, expand, stride if r == 0 else 1,
                           kernel, drop_rate=drop,
                           bn_momentum=self.bn_momentum, bn_eps=self.bn_eps,
                           **kw, name=f"block{si}_{r}")(x, train)
                in_f = out_f
                bi += 1
        head = _round_filters(1280, self.width_mult)
        x = nn.Conv(head, (1, 1), use_bias=False, **kw, name="head_conv")(x)
        x = nn.swish(bn(name="head_bn")(x))
        # 'gap' scope: the pool is the only phase flax's module path
        # does not name (device-time waterfall, telemetry/profile.py).
        with jax.named_scope("gap"):
            x = jnp.mean(x, axis=(1, 2))
        return x.astype(jnp.float32)


def efficientnet(variant: str, **kw) -> EfficientNet:
    width, depth, _ = _SCALING[variant]
    return EfficientNet(width_mult=width, depth_mult=depth, **kw)
