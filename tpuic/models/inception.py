"""Inception-v3 with auxiliary head, as a Flax module.

The reference's default backbone (train.py:122 'inceptionv3';
nn/classifier.py:20-23): torchvision inception_v3 with ``AuxLogits.fc``
replaced by a fresh Linear and the main ``fc`` replaced by the MLP head. In
train mode it returns (features, aux_logits) and the driver applies
``loss1 + 0.4 * loss2`` (train.py:48-52) — reproduced by
tpuic.train.loss.classification_loss.

Architecture follows Szegedy et al. 2015 (v3) exactly as torchvision builds
it: stem (5 convs + 2 pools), 3×InceptionA, InceptionB, 4×InceptionC,
InceptionD, 2×InceptionE, aux classifier branching after the InceptionC
stack. All convs are BN convs (no bias, BN eps 1e-3). Input 299×299 (the
reference resizes to 299, train.py:110).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from tpuic.models.layers import batch_norm


class ConvBN(nn.Module):
    features: int
    kernel: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    padding: Any = 0
    bn_momentum: float = 0.9
    bn_eps: float = 1e-3
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool) -> jnp.ndarray:
        x = nn.Conv(self.features, self.kernel, strides=self.strides,
                    padding=self.padding, use_bias=False, dtype=self.dtype,
                    param_dtype=self.param_dtype, name="conv")(x)
        x = batch_norm(train, momentum=self.bn_momentum, eps=self.bn_eps,
                       dtype=self.dtype, param_dtype=self.param_dtype,
                       name="bn")(x)
        return nn.relu(x)


def _avgpool3(x):
    return nn.avg_pool(x, (3, 3), strides=(1, 1), padding=((1, 1), (1, 1)))


class InceptionA(nn.Module):
    pool_features: int
    conv_kw: dict = None

    @nn.compact
    def __call__(self, x, train):
        C = partial(ConvBN, **self.conv_kw)
        b1 = C(64, (1, 1), name="b1x1")(x, train)
        b5 = C(48, (1, 1), name="b5_1")(x, train)
        b5 = C(64, (5, 5), padding=2, name="b5_2")(b5, train)
        b3 = C(64, (1, 1), name="b3_1")(x, train)
        b3 = C(96, (3, 3), padding=1, name="b3_2")(b3, train)
        b3 = C(96, (3, 3), padding=1, name="b3_3")(b3, train)
        bp = C(self.pool_features, (1, 1), name="bpool")(_avgpool3(x), train)
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class InceptionB(nn.Module):
    conv_kw: dict = None

    @nn.compact
    def __call__(self, x, train):
        C = partial(ConvBN, **self.conv_kw)
        b3 = C(384, (3, 3), strides=(2, 2), name="b3")(x, train)
        bd = C(64, (1, 1), name="bd_1")(x, train)
        bd = C(96, (3, 3), padding=1, name="bd_2")(bd, train)
        bd = C(96, (3, 3), strides=(2, 2), name="bd_3")(bd, train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2))
        return jnp.concatenate([b3, bd, bp], axis=-1)


class InceptionC(nn.Module):
    channels_7x7: int
    conv_kw: dict = None

    @nn.compact
    def __call__(self, x, train):
        C = partial(ConvBN, **self.conv_kw)
        c7 = self.channels_7x7
        b1 = C(192, (1, 1), name="b1x1")(x, train)
        b7 = C(c7, (1, 1), name="b7_1")(x, train)
        b7 = C(c7, (1, 7), padding=((0, 0), (3, 3)), name="b7_2")(b7, train)
        b7 = C(192, (7, 1), padding=((3, 3), (0, 0)), name="b7_3")(b7, train)
        bd = C(c7, (1, 1), name="bd_1")(x, train)
        bd = C(c7, (7, 1), padding=((3, 3), (0, 0)), name="bd_2")(bd, train)
        bd = C(c7, (1, 7), padding=((0, 0), (3, 3)), name="bd_3")(bd, train)
        bd = C(c7, (7, 1), padding=((3, 3), (0, 0)), name="bd_4")(bd, train)
        bd = C(192, (1, 7), padding=((0, 0), (3, 3)), name="bd_5")(bd, train)
        bp = C(192, (1, 1), name="bpool")(_avgpool3(x), train)
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)


class InceptionD(nn.Module):
    conv_kw: dict = None

    @nn.compact
    def __call__(self, x, train):
        C = partial(ConvBN, **self.conv_kw)
        b3 = C(192, (1, 1), name="b3_1")(x, train)
        b3 = C(320, (3, 3), strides=(2, 2), name="b3_2")(b3, train)
        b7 = C(192, (1, 1), name="b7_1")(x, train)
        b7 = C(192, (1, 7), padding=((0, 0), (3, 3)), name="b7_2")(b7, train)
        b7 = C(192, (7, 1), padding=((3, 3), (0, 0)), name="b7_3")(b7, train)
        b7 = C(192, (3, 3), strides=(2, 2), name="b7_4")(b7, train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2))
        return jnp.concatenate([b3, b7, bp], axis=-1)


class InceptionE(nn.Module):
    conv_kw: dict = None

    @nn.compact
    def __call__(self, x, train):
        C = partial(ConvBN, **self.conv_kw)
        b1 = C(320, (1, 1), name="b1x1")(x, train)
        b3 = C(384, (1, 1), name="b3_1")(x, train)
        b3a = C(384, (1, 3), padding=((0, 0), (1, 1)), name="b3_2a")(b3, train)
        b3b = C(384, (3, 1), padding=((1, 1), (0, 0)), name="b3_2b")(b3, train)
        b3 = jnp.concatenate([b3a, b3b], axis=-1)
        bd = C(448, (1, 1), name="bd_1")(x, train)
        bd = C(384, (3, 3), padding=1, name="bd_2")(bd, train)
        bda = C(384, (1, 3), padding=((0, 0), (1, 1)), name="bd_3a")(bd, train)
        bdb = C(384, (3, 1), padding=((1, 1), (0, 0)), name="bd_3b")(bd, train)
        bd = jnp.concatenate([bda, bdb], axis=-1)
        bp = C(192, (1, 1), name="bpool")(_avgpool3(x), train)
        return jnp.concatenate([b1, b3, bd, bp], axis=-1)


class InceptionAux(nn.Module):
    """Aux classifier (torchvision InceptionAux): the reference swaps its fc
    for Linear(768, num_classes) (nn/classifier.py:22-23)."""

    num_classes: int
    conv_kw: dict = None

    @nn.compact
    def __call__(self, x, train):
        C = partial(ConvBN, **self.conv_kw)
        x = nn.avg_pool(x, (5, 5), strides=(3, 3))
        x = C(128, (1, 1), name="conv0")(x, train)
        x = C(768, (5, 5), name="conv1")(x, train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        param_dtype=self.conv_kw.get("param_dtype",
                                                     jnp.float32),
                        name="fc")(x.astype(jnp.float32))


class InceptionV3(nn.Module):
    """Returns features [B, 2048]; in train mode (features, aux_logits).

    ``aux_classes`` sizes the aux head (the reference gives it num_classes).
    """

    aux_classes: int = 0  # 0 disables the aux branch
    bn_momentum: float = 0.9
    bn_eps: float = 1e-3
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False):
        kw = dict(bn_momentum=self.bn_momentum, bn_eps=self.bn_eps,
                  dtype=self.dtype, param_dtype=self.param_dtype)
        C = partial(ConvBN, **kw)
        x = x.astype(self.dtype)
        x = C(32, (3, 3), strides=(2, 2), name="stem1")(x, train)
        x = C(32, (3, 3), name="stem2")(x, train)
        x = C(64, (3, 3), padding=1, name="stem3")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = C(80, (1, 1), name="stem4")(x, train)
        x = C(192, (3, 3), name="stem5")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = InceptionA(32, conv_kw=kw, name="mixed5b")(x, train)
        x = InceptionA(64, conv_kw=kw, name="mixed5c")(x, train)
        x = InceptionA(64, conv_kw=kw, name="mixed5d")(x, train)
        x = InceptionB(conv_kw=kw, name="mixed6a")(x, train)
        x = InceptionC(128, conv_kw=kw, name="mixed6b")(x, train)
        x = InceptionC(160, conv_kw=kw, name="mixed6c")(x, train)
        x = InceptionC(160, conv_kw=kw, name="mixed6d")(x, train)
        x = InceptionC(192, conv_kw=kw, name="mixed6e")(x, train)
        aux = None
        if self.aux_classes and train:
            aux = InceptionAux(self.aux_classes, conv_kw=kw,
                               name="aux")(x, train)
        x = InceptionD(conv_kw=kw, name="mixed7a")(x, train)
        x = InceptionE(conv_kw=kw, name="mixed7b")(x, train)
        x = InceptionE(conv_kw=kw, name="mixed7c")(x, train)
        # 'gap' scope: the pool is the only phase flax's module path
        # does not name (device-time waterfall, telemetry/profile.py).
        with jax.named_scope("gap"):
            x = jnp.mean(x, axis=(1, 2)).astype(jnp.float32)  # [B, 2048]
        if self.aux_classes and train:
            return x, aux
        return x
