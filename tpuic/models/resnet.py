"""ResNet family (18/34/50/101) as Flax modules.

Backbone capability parity with the reference's torchvision selections
(nn/classifier.py:11-15 offers resnet50/resnet101 pretrained; BASELINE.md adds
resnet18 for the CIFAR-10 config). Torchvision's exact architecture is
reproduced — 7x7/stride-2 stem, maxpool, 4 stages of Basic/Bottleneck blocks,
global average pool — so its pretrained checkpoints can be converted 1:1
(tpuic/checkpoint/torch_convert.py). Layout is NHWC (TPU-native; torch is
NCHW), compute dtype is configurable bfloat16 for the MXU.

A ``small_stem`` variant (3x3 stride-1 stem, no maxpool) is provided for
32x32 CIFAR inputs, where the ImageNet stem would destroy resolution.

``space_to_depth`` stem (the public MLPerf ResNet TPU optimization): the
7x7/stride-2 conv on [H, W, 3] is algebraically identical to a 4x4/stride-1
conv on the 2x2 space-to-depth transform [H/2, W/2, 12] with the 7x7 kernel
zero-padded to 8x8 and re-indexed (``s2d_stem_kernel``). C=3 feeds the
128-lane MXU at ~2% utilization; C=12 is 4x better and the stride-2 gather
disappears. Same math, better layout — exactness is pinned in
tests/test_models.py.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

from tpuic.models.layers import batch_norm, conv1x1, conv3x3


def _fused_ready(mod: nn.Module, train: bool) -> bool:
    """The fused-inference branch applies only when (a) the flag is on,
    (b) this is an inference call (training BN needs batch statistics
    the per-image kernel cannot see), and (c) the variables already
    exist — init() must run the unfused branch so the parameter
    structure (and therefore every checkpoint) is identical either way."""
    return (mod.fused_inference and not train
            and mod.has_variable("params", "conv1"))


def _fused_cbr(mod: nn.Module, x, conv: str, bn: str, *, strides=1,
               padding=0, relu=True):
    """One fused conv+BN+ReLU call reading the UNFUSED branch's variables
    (kernels/conv_bn_relu.py) — same params, same running stats, one
    VMEM pass instead of conv-out/bn-out/relu-out HBM roundtrips."""
    from tpuic.kernels import fused_conv_bn_from_flax
    v = mod.variables
    return fused_conv_bn_from_flax(
        x, v["params"][conv]["kernel"], v["params"][bn],
        v["batch_stats"][bn], strides=strides, padding=padding, relu=relu,
        eps=mod.bn_eps)


class BasicBlock(nn.Module):
    features: int
    strides: int = 1
    bn_momentum: float = 0.9
    bn_eps: float = 1e-5
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    bn_f32_stats: bool = True
    fused_inference: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool) -> jnp.ndarray:
        if _fused_ready(self, train):
            y = _fused_cbr(self, x, "conv1", "bn1", strides=self.strides,
                           padding=1)
            y = _fused_cbr(self, y, "conv2", "bn2", padding=1, relu=False)
            residual = x
            if "downsample_conv" in self.variables["params"]:
                residual = _fused_cbr(self, x, "downsample_conv",
                                      "downsample_bn",
                                      strides=self.strides, relu=False)
            return nn.relu(y + residual)
        bn = partial(batch_norm, train, momentum=self.bn_momentum,
                     eps=self.bn_eps, dtype=self.dtype,
                     param_dtype=self.param_dtype,
                     f32_stats=self.bn_f32_stats)
        kw = dict(dtype=self.dtype, param_dtype=self.param_dtype)
        residual = x
        y = conv3x3(self.features, self.strides, **kw, name="conv1")(x)
        y = bn(name="bn1")(y)
        y = nn.relu(y)
        y = conv3x3(self.features, **kw, name="conv2")(y)
        y = bn(name="bn2")(y)
        if residual.shape != y.shape:
            residual = conv1x1(self.features, self.strides, **kw,
                               name="downsample_conv")(x)
            residual = bn(name="downsample_bn")(residual)
        return nn.relu(y + residual)


class Bottleneck(nn.Module):
    features: int  # bottleneck width; block output is 4*features
    strides: int = 1
    bn_momentum: float = 0.9
    bn_eps: float = 1e-5
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    bn_f32_stats: bool = True
    fused_inference: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool) -> jnp.ndarray:
        if _fused_ready(self, train):
            y = _fused_cbr(self, x, "conv1", "bn1")
            # torchvision places the stride on the 3x3 (v1.5 ResNet).
            y = _fused_cbr(self, y, "conv2", "bn2", strides=self.strides,
                           padding=1)
            y = _fused_cbr(self, y, "conv3", "bn3", relu=False)
            residual = x
            if "downsample_conv" in self.variables["params"]:
                residual = _fused_cbr(self, x, "downsample_conv",
                                      "downsample_bn",
                                      strides=self.strides, relu=False)
            return nn.relu(y + residual)
        bn = partial(batch_norm, train, momentum=self.bn_momentum,
                     eps=self.bn_eps, dtype=self.dtype,
                     param_dtype=self.param_dtype,
                     f32_stats=self.bn_f32_stats)
        kw = dict(dtype=self.dtype, param_dtype=self.param_dtype)
        out_features = self.features * 4
        residual = x
        y = conv1x1(self.features, **kw, name="conv1")(x)
        y = nn.relu(bn(name="bn1")(y))
        # torchvision places the stride on the 3x3 (v1.5 ResNet).
        y = conv3x3(self.features, self.strides, **kw, name="conv2")(y)
        y = nn.relu(bn(name="bn2")(y))
        y = conv1x1(out_features, **kw, name="conv3")(y)
        y = bn(name="bn3")(y)
        if residual.shape != y.shape:
            residual = conv1x1(out_features, self.strides, **kw,
                               name="downsample_conv")(x)
            residual = bn(name="downsample_bn")(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """Returns pooled features [B, F]; the classifier head is separate."""

    stage_sizes: Sequence[int]
    block: type
    num_filters: int = 64
    small_stem: bool = False
    space_to_depth: bool = False
    bn_momentum: float = 0.9
    bn_eps: float = 1e-5
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    bn_f32_stats: bool = True
    # Inference-only Pallas fused conv+BN+ReLU (kernels/conv_bn_relu.py):
    # identical parameter structure (init always runs the unfused branch),
    # so the flag can be flipped on any existing checkpoint.
    fused_inference: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        kw = dict(dtype=self.dtype, param_dtype=self.param_dtype)
        x = x.astype(self.dtype)
        fused = _fused_ready(self, train)
        # jax.named_scope tags ('stem'/'gap') thread the structural
        # phases flax's module path does not name into the HLO op
        # metadata — the device-time waterfall (telemetry/profile.py)
        # rolls layers up from exactly these paths; the blocks below are
        # already scoped by their flax module names (layerN_i).
        with jax.named_scope("stem"):
            if fused:
                if self.small_stem:
                    x = _fused_cbr(self, x, "conv1", "bn1", padding=1)
                elif self.space_to_depth:
                    b, h, w, c = x.shape
                    if h % 2 or w % 2:
                        raise ValueError(
                            f"space_to_depth stem needs even H/W, "
                            f"got {(h, w)}")
                    x = x.reshape(b, h // 2, 2, w // 2, 2, c)
                    x = x.transpose(0, 1, 3, 2, 4, 5).reshape(
                        b, h // 2, w // 2, 4 * c)
                    x = _fused_cbr(self, x, "conv1", "bn1",
                                   padding=((2, 1), (2, 1)))
                else:
                    x = _fused_cbr(self, x, "conv1", "bn1", strides=2,
                                   padding=3)
            elif self.small_stem:
                x = nn.Conv(self.num_filters, (3, 3), padding=1,
                            use_bias=False, **kw, name="conv1")(x)
            elif self.space_to_depth:
                b, h, w, c = x.shape
                if h % 2 or w % 2:
                    raise ValueError(
                        f"space_to_depth stem needs even H/W, got {(h, w)}")
                x = x.reshape(b, h // 2, 2, w // 2, 2, c)
                x = x.transpose(0, 1, 3, 2, 4, 5).reshape(b, h // 2, w // 2,
                                                          4 * c)
                # Taps of output row oi cover original rows 2oi-3..2oi+3;
                # with the kernel zero-padded to 8 the window is
                # 2(oi-2)..2oi+3 — four s2d rows, hence 4x4 stride-1 with
                # (2, 1) padding.
                x = nn.Conv(self.num_filters, (4, 4), strides=(1, 1),
                            padding=((2, 1), (2, 1)), use_bias=False, **kw,
                            name="conv1")(x)
            else:
                x = nn.Conv(self.num_filters, (7, 7), strides=(2, 2),
                            padding=3, use_bias=False, **kw, name="conv1")(x)
            if not fused:  # the fused stem already applied bn1 + relu
                x = batch_norm(train, momentum=self.bn_momentum,
                               eps=self.bn_eps,
                               f32_stats=self.bn_f32_stats, **kw,
                               name="bn1")(x)
                x = nn.relu(x)
            if not self.small_stem:
                x = nn.max_pool(x, (3, 3), strides=(2, 2),
                                padding=((1, 1), (1, 1)))
        for stage, n_blocks in enumerate(self.stage_sizes):
            for i in range(n_blocks):
                strides = 2 if stage > 0 and i == 0 else 1
                x = self.block(self.num_filters * 2 ** stage, strides,
                               self.bn_momentum, self.bn_eps, self.dtype,
                               self.param_dtype, self.bn_f32_stats,
                               fused_inference=self.fused_inference,
                               name=f"layer{stage + 1}_{i}")(x, train)
        with jax.named_scope("gap"):
            x = jnp.mean(x, axis=(1, 2))  # global average pool
        return x.astype(jnp.float32)


def s2d_stem_kernel(w77: jnp.ndarray) -> jnp.ndarray:
    """[7,7,Cin,F] stem kernel -> its space-to-depth equivalent
    [4,4,4*Cin,F]: zero-pad to 8x8 with the extra row/col at the LEADING
    edge (the conv's effective window starts one original pixel earlier),
    then fold each 2x2 tap block into channels in (di, dj, channel) order —
    matching the activation transform in ResNet.__call__."""
    k, _, cin, f = w77.shape
    assert k == 7, w77.shape
    w88 = jnp.pad(w77, ((1, 0), (1, 0), (0, 0), (0, 0)))
    w = w88.reshape(4, 2, 4, 2, cin, f)          # (pi, di, qi, dj, c, f)
    w = w.transpose(0, 2, 1, 3, 4, 5)            # (pi, qi, di, dj, c, f)
    return w.reshape(4, 4, 4 * cin, f)


def resnet18(**kw) -> ResNet:
    return ResNet(stage_sizes=(2, 2, 2, 2), block=BasicBlock, **kw)


def resnet34(**kw) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), block=BasicBlock, **kw)


def resnet50(**kw) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), block=Bottleneck, **kw)


def resnet101(**kw) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 23, 3), block=Bottleneck, **kw)


def resnet152(**kw) -> ResNet:
    return ResNet(stage_sizes=(3, 8, 36, 3), block=Bottleneck, **kw)
