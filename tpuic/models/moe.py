"""Mixture-of-Experts MLP with expert parallelism (Switch-style top-1).

The reference is dense-only (SURVEY.md §2c: "Expert parallel (EP/MoE): No").
This is a beyond-parity capability, designed the TPU way (GShard/Switch
lineage): routing and dispatch are pure einsums over STATIC shapes — a
[T, E, C] one-hot dispatch tensor instead of data-dependent gathers — so the
whole layer jits, shards, and differentiates like any other matmul stack.

Expert parallelism is a sharding annotation, not a runtime: expert weight
tensors carry the ``expert`` logical axis (parallel/sharding.py maps it onto
the mesh ``model`` axis), so each device holds E/ep experts and GSPMD
inserts the token all-to-alls around the expert contraction. DP/TP/EP
compose on the same mesh.

Semantics:
- top-1 routing (Switch Transformer): each token goes to its argmax expert,
  scaled by the router probability; router math in float32.
- routing GROUPS are batch rows (GShard convention): capacity and the
  dispatch one-hots are per image, C = ceil(capacity_factor * N / E), so
  the dispatch tensor is [B, N, E, C] — linear in batch size. A single
  global group would make it ~capacity_factor*T^2/E elements, ~13 GB at
  vit-s16-moe's batch-256 scale.
- tokens over capacity are DROPPED (contribute zero; the transformer's
  residual carries them through unchanged) — the standard static-shape
  trade.
- load-balancing auxiliary loss (Switch eq. 4): the layer sows its router
  stats ('moe_router' in the 'intermediates' collection); the train step
  computes ``switch_aux_loss`` with the batch padding mask applied and
  adds ModelConfig.moe_aux_weight times the mean over MoE layers to the
  task loss.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
import numpy as np
from flax import linen as nn


def switch_aux_loss(probs: jnp.ndarray, onehot: jnp.ndarray,
                    mask: jnp.ndarray | None = None) -> jnp.ndarray:
    """Switch Transformer load-balancing loss (eq. 4): E * sum_e f_e * p_e.

    probs/onehot: [B, N, E] router softmax and top-1 one-hot (sown by
    SwitchMoEMlp as 'moe_router'). ``mask``: optional [B] validity (the
    Loader's padding mask) — masked samples contribute to neither the
    routed-token fractions nor the mean probabilities.
    """
    E = probs.shape[-1]
    if mask is None:
        frac = jnp.mean(onehot, axis=(0, 1))
        mean_prob = jnp.mean(probs, axis=(0, 1))
    else:
        w = mask.astype(jnp.float32)[:, None, None]          # [B,1,1]
        denom = jnp.maximum(jnp.sum(w) * probs.shape[1], 1.0)
        frac = jnp.sum(onehot * w, axis=(0, 1)) / denom
        mean_prob = jnp.sum(probs * w, axis=(0, 1)) / denom
    return E * jnp.sum(frac * mean_prob)


class SwitchMoEMlp(nn.Module):
    num_experts: int
    mlp_ratio: int = 4
    capacity_factor: float = 1.25
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray,
                 deterministic: bool = True) -> jnp.ndarray:
        B, N, D = x.shape
        E = self.num_experts
        H = D * self.mlp_ratio
        C = int(np.ceil(self.capacity_factor * N / E))

        # Router in f32 (tiny; numerically load-bearing).
        router_kernel = self.param(
            "router", nn.with_logical_partitioning(
                nn.initializers.normal(0.02), ("embed", "unsharded")),
            (D, E), self.param_dtype)
        logits = jnp.einsum("bnd,de->bne", x.astype(jnp.float32),
                            router_kernel.astype(jnp.float32))
        probs = nn.softmax(logits, axis=-1)             # [B, N, E] f32
        gate = jnp.max(probs, axis=-1)                  # [B, N]
        expert_idx = jnp.argmax(probs, axis=-1)         # [B, N]
        onehot = nn.one_hot(expert_idx, E, dtype=jnp.float32)  # [B, N, E]

        # Queue position within the (batch-row) group; one_hot of the
        # 0-based slot is all-zero both for unrouted (-1) and over-capacity
        # (>= C) tokens, which IS the drop mask.
        pos = jnp.cumsum(onehot, axis=1) * onehot       # [B, N, E], 1-based
        disp = nn.one_hot((pos - 1.0).astype(jnp.int32), C,
                          dtype=jnp.float32)            # [B, N, E, C]

        # Router stats for the load-balancing aux loss. The loss itself is
        # computed OUTSIDE the layer (train/step.py via switch_aux_loss) so
        # the batch padding mask can exclude wrapped duplicate samples —
        # the layer has no access to the mask, and an unmasked aux would
        # double-weight padded rows in f_e/p_e (round-3 review finding).
        self.sow("intermediates", "moe_router", (probs, onehot))

        w1 = self.param("w1", nn.with_logical_partitioning(
            nn.initializers.xavier_uniform(), ("expert", "embed", "unsharded")),
            (E, D, H), self.param_dtype)
        b1 = self.param("b1", nn.with_logical_partitioning(
            nn.initializers.zeros, ("expert", "unsharded")),
            (E, H), self.param_dtype)
        w2 = self.param("w2", nn.with_logical_partitioning(
            nn.initializers.xavier_uniform(), ("expert", "unsharded", "embed")),
            (E, H, D), self.param_dtype)
        b2 = self.param("b2", nn.with_logical_partitioning(
            nn.initializers.zeros, ("expert", "embed")),
            (E, D), self.param_dtype)

        dt = self.dtype
        # Dispatch -> per-expert token blocks [B, E, C, D]; GSPMD turns the
        # resharding from batch-sharded to expert-sharded into all-to-alls
        # over the mesh when 'expert' is mapped.
        expert_in = jnp.einsum("bnec,bnd->becd", disp.astype(dt),
                               x.astype(dt))
        h = jnp.einsum("becd,edh->bech", expert_in, w1.astype(dt))
        # exact GELU to match the dense MLP path (vit.py) and torch.
        h = nn.gelu(h + b1.astype(dt)[None, :, None, :], approximate=False)
        out = jnp.einsum("bech,ehd->becd", h, w2.astype(dt))
        out = out + b2.astype(dt)[None, :, None, :]

        combine = (disp * gate[..., None, None]).astype(dt)  # [B, N, E, C]
        return jnp.einsum("bnec,becd->bnd", combine, out)
