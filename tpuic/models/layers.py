"""Shared model building blocks.

The MLP classifier head reproduces the reference's
``in_features -> 128 -> ReLU -> 64 -> ReLU -> 32 -> ReLU -> num_classes`` head
(nn/classifier.py:26-34). BatchNorm notes:

- The reference converts every BN layer to SyncBatchNorm over the world group
  (train.py:124), so training statistics are global-batch statistics. In this
  framework the train step is jitted over a mesh with the batch sharded on the
  ``data`` axis, so a plain ``nn.BatchNorm`` reduction over the batch dim *is*
  a global-batch reduction — GSPMD inserts the cross-replica all-reduce.
  SyncBN is the default semantics here, not an opt-in wrapper.
- Momentum/eps defaults follow torch BN (momentum 0.1 torch-style == 0.9 flax
  EMA style; eps 1e-5), which the reference inherits untouched.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax.numpy as jnp
from flax import linen as nn


class MLPHead(nn.Module):
    """Reference nn/classifier.py:26-34 head: widths (128, 64, 32) + ReLU."""

    num_classes: int
    widths: Sequence[int] = (128, 64, 32)
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        for i, w in enumerate(self.widths):
            x = nn.Dense(w, dtype=self.dtype, param_dtype=self.param_dtype,
                         name=f"fc{i}")(x)
            x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     param_dtype=self.param_dtype, name="out")(x)
        return x.astype(jnp.float32)


def batch_norm(train: bool, *, momentum: float = 0.9, eps: float = 1e-5,
               dtype: Any = jnp.float32, param_dtype: Any = jnp.float32,
               f32_stats: bool = True,
               name: str | None = None) -> nn.BatchNorm:
    """BatchNorm with torch-default hyperparameters (see module docstring).

    Under the sharded-jit train step this computes *global* batch statistics —
    the reference's SyncBatchNorm (train.py:124) semantics.

    ``f32_stats=False`` accumulates batch mean/var in the compute dtype
    (bf16) instead of float32 — a bandwidth experiment: the BN stat
    fusions are the top HBM readers in the ResNet-50 step profile
    (ModelConfig.bn_f32_stats).
    """
    return nn.BatchNorm(use_running_average=not train, momentum=momentum,
                        epsilon=eps, dtype=dtype, param_dtype=param_dtype,
                        force_float32_reductions=f32_stats,
                        name=name)


Conv = nn.Conv


def conv3x3(features: int, strides: int = 1, *, dtype=jnp.float32,
            param_dtype=jnp.float32, name: str | None = None) -> nn.Conv:
    return nn.Conv(features, (3, 3), strides=(strides, strides), padding=1,
                   use_bias=False, dtype=dtype, param_dtype=param_dtype,
                   name=name)


def conv1x1(features: int, strides: int = 1, *, dtype=jnp.float32,
            param_dtype=jnp.float32, name: str | None = None) -> nn.Conv:
    return nn.Conv(features, (1, 1), strides=(strides, strides),
                   use_bias=False, dtype=dtype, param_dtype=param_dtype,
                   name=name)
