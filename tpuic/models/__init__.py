"""Model registry.

``create_model(name, num_classes)`` is the framework equivalent of
``Classifier(name, num_classes)`` in reference nn/classifier.py:8-34. Accepted
names cover the reference's selector strings ('resnet50', 'resnet101',
'inceptionv3', 'efficientnet-b3' — nn/classifier.py:11-23) plus the
BASELINE.md parity-config additions ('resnet18', 'efficientnet-b0',
'vit-b16').
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax.numpy as jnp

from tpuic.config import ModelConfig
from tpuic.models.classifier import Classifier
from tpuic.models import resnet as _resnet
from tpuic.models import efficientnet as _effnet
from tpuic.models import inception as _inception
from tpuic.models import vit as _vit

# name -> (factory(num_classes, dtype, param_dtype, bn_momentum, bn_eps),
#          has_aux)
_REGISTRY: Dict[str, Tuple[Callable[..., Any], bool]] = {}


def register(name: str, factory: Callable[..., Any], has_aux: bool = False):
    _REGISTRY[name] = (factory, has_aux)


def available_models():
    return sorted(_REGISTRY)


# Single source of truth: the module whose attention dispatch consumes it.
from tpuic.models.vit import ATTENTION_IMPLS  # noqa: E402,F401


def create_backbone(name: str, num_classes: int = 0, *, dtype=jnp.float32,
                    param_dtype=jnp.float32, bn_momentum: float = 0.9,
                    bn_eps: float = 1e-5, attention: str = "dense",
                    mesh=None, bn_f32_stats: bool = True,
                    drop_path: float = 0.0, remat_core: bool = False,
                    remat_blocks: bool = False, remat_mlp: bool = False,
                    fused_conv_bn: bool = False):
    if name not in _REGISTRY:
        raise ValueError(f"unknown model '{name}'; available: {available_models()}")
    if attention not in ATTENTION_IMPLS:
        raise ValueError(f"unknown attention impl '{attention}'; "
                         f"available: {ATTENTION_IMPLS}")
    factory, has_aux = _REGISTRY[name]
    return factory(num_classes=num_classes, dtype=dtype,
                   param_dtype=param_dtype, bn_momentum=bn_momentum,
                   bn_eps=bn_eps, attention=attention, mesh=mesh,
                   bn_f32_stats=bn_f32_stats, drop_path=drop_path,
                   remat_core=remat_core, remat_blocks=remat_blocks,
                   remat_mlp=remat_mlp,
                   fused_conv_bn=fused_conv_bn), has_aux


def create_model(name: str, num_classes: int, *, head_widths=(128, 64, 32),
                 dtype="bfloat16", param_dtype="float32",
                 bn_momentum: float = 0.9, bn_eps: float = 1e-5,
                 attention: str = "dense", mesh=None,
                 bn_f32_stats: bool = True,
                 drop_path: float = 0.0,
                 remat_core: bool = False,
                 remat_blocks: bool = False,
                 remat_mlp: bool = False,
                 fused_conv_bn: bool = False) -> Classifier:
    dt, pdt = jnp.dtype(dtype), jnp.dtype(param_dtype)
    backbone, has_aux = create_backbone(name, num_classes, dtype=dt,
                                        param_dtype=pdt,
                                        bn_momentum=bn_momentum, bn_eps=bn_eps,
                                        attention=attention, mesh=mesh,
                                        bn_f32_stats=bn_f32_stats,
                                        drop_path=drop_path,
                                        remat_core=remat_core,
                                        remat_blocks=remat_blocks,
                                        remat_mlp=remat_mlp,
                                        fused_conv_bn=fused_conv_bn)
    return Classifier(backbone=backbone, num_classes=num_classes,
                      head_widths=tuple(head_widths), has_aux=has_aux,
                      dtype=dt, param_dtype=pdt)


def create_model_from_config(cfg: ModelConfig, mesh=None) -> Classifier:
    return create_model(cfg.name, cfg.num_classes, head_widths=cfg.head_widths,
                        dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                        bn_momentum=cfg.bn_momentum, bn_eps=cfg.bn_eps,
                        attention=cfg.attention, mesh=mesh,
                        bn_f32_stats=cfg.bn_f32_stats,
                        drop_path=cfg.drop_path,
                        # 'attention' selective remat lives in the model
                        # (ViT remat_core), not a step-level jax.checkpoint
                        # (train/step.py resolve_remat_policy).
                        remat_core=(cfg.remat
                                    and cfg.remat_policy == "attention"),
                        # 'blocks' per-block remat likewise lives in the
                        # model (ViT remat_blocks, nn.remat per encoder
                        # block) — the long-context memory mode.
                        remat_blocks=(cfg.remat
                                      and cfg.remat_policy == "blocks"),
                        # 'gelu' likewise: MlpUpGelu under nn.remat (ViT
                        # remat_mlp) — the mlp_up pre-activation is never
                        # a residual; see models/vit.py MlpUpGelu.
                        remat_mlp=(cfg.remat
                                   and cfg.remat_policy == "gelu"),
                        # Inference-only Pallas fused conv+BN+ReLU for
                        # the ResNet family (kernels/conv_bn_relu.py);
                        # training and non-ResNet backbones ignore it.
                        fused_conv_bn=cfg.fused_conv_bn)


def _register_builtins():
    def _rn(factory, **extra):
        def make(*, num_classes, dtype, param_dtype, bn_momentum, bn_eps,
                 attention, mesh, bn_f32_stats, drop_path, remat_core,
                 remat_blocks, remat_mlp, fused_conv_bn):
            del (num_classes, attention, mesh, drop_path, remat_core,
                 remat_blocks, remat_mlp)
            return factory(dtype=dtype, param_dtype=param_dtype,
                           bn_momentum=bn_momentum, bn_eps=bn_eps,
                           bn_f32_stats=bn_f32_stats,
                           fused_inference=fused_conv_bn, **extra)
        return make

    register("resnet18", _rn(_resnet.resnet18))
    register("resnet34", _rn(_resnet.resnet34))
    register("resnet50", _rn(_resnet.resnet50))
    register("resnet101", _rn(_resnet.resnet101))
    register("resnet152", _rn(_resnet.resnet152))
    register("resnet18-cifar", _rn(_resnet.resnet18, small_stem=True))
    # MLPerf-style space-to-depth stem: identical math to resnet50 (the
    # 7x7/s2 stem re-indexed as 4x4/s1 on [H/2,W/2,12]), better MXU layout;
    # convert standard stem weights with models.resnet.s2d_stem_kernel.
    register("resnet50-s2d", _rn(_resnet.resnet50, space_to_depth=True))

    def _eff(variant):
        def make(*, num_classes, dtype, param_dtype, bn_momentum, bn_eps,
                 attention, mesh, bn_f32_stats, drop_path, remat_core,
                 remat_blocks, remat_mlp, fused_conv_bn):
            # torch effnet: eps 1e-3; f32 stats kept (experiment is
            # ResNet-scoped, ModelConfig.bn_f32_stats); fused conv+BN is
            # ResNet-only too.
            del (num_classes, bn_eps, attention, mesh, bn_f32_stats,
                 drop_path, remat_core, remat_blocks, remat_mlp,
                 fused_conv_bn)
            return _effnet.efficientnet(variant, dtype=dtype,
                                        param_dtype=param_dtype,
                                        bn_momentum=bn_momentum)
        return make

    for v in ("b0", "b1", "b2", "b3", "b4", "b5", "b6", "b7"):
        register(f"efficientnet-{v}", _eff(v))

    def _vit_factory(ctor):
        def make(*, num_classes, dtype, param_dtype, bn_momentum, bn_eps,
                 attention, mesh, bn_f32_stats, drop_path, remat_core,
                 remat_blocks, remat_mlp, fused_conv_bn):
            del num_classes, bn_momentum, bn_eps, bn_f32_stats  # no BN in ViT
            del fused_conv_bn  # ResNet-only
            return ctor(dtype=dtype, param_dtype=param_dtype,
                        attention=attention, mesh=mesh, drop_path=drop_path,
                        remat_core=remat_core, remat_blocks=remat_blocks,
                        remat_mlp=remat_mlp)
        return make

    register("vit-b16", _vit_factory(_vit.vit_b16))
    register("vit-l16", _vit_factory(_vit.vit_l16))
    register("vit-b32", _vit_factory(_vit.vit_b32))
    register("vit-l32", _vit_factory(_vit.vit_l32))
    register("vit-s16", _vit_factory(_vit.vit_s16))
    register("vit-tiny", _vit_factory(_vit.vit_tiny))
    # Switch-MoE variants (models/moe.py): expert-parallel over the mesh
    # 'model' axis; beyond-parity (reference is dense-only, SURVEY.md §2c).
    register("vit-s16-moe", _vit_factory(_vit.vit_s16_moe))
    register("vit-tiny-moe", _vit_factory(_vit.vit_tiny_moe))

    def _inc(*, num_classes, dtype, param_dtype, bn_momentum, bn_eps,
             attention, mesh, bn_f32_stats, drop_path, remat_core,
             remat_blocks, remat_mlp, fused_conv_bn):
        # torch inception: eps 1e-3 (module default); f32 stats kept;
        # fused conv+BN is ResNet-only.
        del (bn_eps, attention, mesh, bn_f32_stats, drop_path,
             remat_core, remat_blocks, remat_mlp, fused_conv_bn)
        return _inception.InceptionV3(aux_classes=num_classes, dtype=dtype,
                                      param_dtype=param_dtype,
                                      bn_momentum=bn_momentum)

    register("inceptionv3", _inc, has_aux=True)


_register_builtins()
