"""Ulysses sequence parallelism — all-to-all head-parallel attention.

The second of the framework's two sequence-parallel strategies (the other is
tpuic/parallel/ring_attention.py; the reference has neither — its only
parallelism is DDP, train.py:128). DeepSpeed-Ulysses (Jacobs et al., 2023)
re-shards between the two natural layouts of attention:

    [B, N/P, H, D]  --all_to_all-->  [B, N, H/P, D]
    (sequence-sharded: how the        (head-sharded: each device runs FULL
     encoder's elementwise/MLP         softmax attention for its H/P heads —
     layers want tokens laid out)      heads are independent, no ring needed)

then all-to-alls back after attention. Communication is two all-to-alls of
the activations per attention call — O(B·N·H·D/P) per device, riding ICI —
versus ring attention's P ppermute hops of K/V. Ulysses wins when H >= P and
the per-device full-N score tile fits VMEM/HBM; ring wins for extreme N
where even one device's full-sequence scores are too large.

Requires H % P == 0 (head count divides the seq-axis size). Autodiff works
through lax.all_to_all natively — the transpose is the reverse all-to-all.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30


def _local_attention(q, k, v, *, scale: float, n_valid: int):
    """Dense f32-softmax attention on full-sequence, local-heads tensors
    [B, N, h_loc, D]; padded key positions (>= n_valid) are masked."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * scale
    n = s.shape[-1]
    if n_valid < n:
        kpos = lax.broadcasted_iota(jnp.int32, s.shape, 3)
        s = jnp.where(kpos < n_valid, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def _ulysses_local(q, k, v, *, axis_name: str, scale: float, n_valid: int,
                   use_flash: bool = False,
                   interpret: Optional[bool] = None):
    """Per-device body under shard_map: seq-sharded in, seq-sharded out."""
    # [B, N/P, H, D] -> [B, N, H/P, D]: gather sequence, scatter heads.
    def to_heads(t):
        return lax.all_to_all(t, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def to_seq(t):
        return lax.all_to_all(t, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    if use_flash:
        # Head-sharded attention is an ordinary full-sequence call — the
        # flash kernel drops in directly (heads are independent). valid_len
        # masks the caller-side token padding; padded positions beyond it
        # never reach softmax.
        from tpuic.kernels import flash_attention
        out = flash_attention(to_heads(q), to_heads(k), to_heads(v),
                              None, None, interpret, None, n_valid)
    else:
        out = _local_attention(to_heads(q), to_heads(k), to_heads(v),
                               scale=scale, n_valid=n_valid)
    return to_seq(out)


def _pad_tokens(t: jnp.ndarray, to: int) -> jnp.ndarray:
    pad = to - t.shape[1]
    if pad == 0:
        return t
    return jnp.pad(t, ((0, 0), (0, pad), (0, 0), (0, 0)))


def ulysses_attention(q, k, v, mesh: Mesh, *, seq_axis: str = "seq",
                      batch_axis: Optional[str] = "data",
                      head_axis: Optional[str] = "model",
                      use_flash: bool = False,
                      interpret: Optional[bool] = None):
    """Bidirectional softmax attention, [B, N, H, D] in/out, with the token
    dim sharded over ``mesh.shape[seq_axis]`` and heads redistributed by
    all-to-all for the attention itself. Composes with batch sharding over
    ``batch_axis`` and with Megatron TP over ``head_axis``: when the model
    axis already shards heads, the all-to-all only redistributes each TP
    rank's local heads over the seq axis (needs (H/tp) % P == 0) instead of
    all-gathering the head-sharded QKV. Falls back to a single local
    computation when the seq axis has size 1.

    ``use_flash`` runs the head-sharded local attention through the Pallas
    flash kernel (attention='ulysses-flash'): no [N, N] score tile in HBM,
    so ulysses stays viable at sequence lengths where the dense local
    softmax would dominate memory."""
    if seq_axis not in mesh.axis_names:
        raise ValueError(f"mesh has no '{seq_axis}' axis: {mesh.axis_names}")
    p = mesh.shape[seq_axis]
    b, n, h, d = q.shape
    scale = 1.0 / (d ** 0.5)

    def _shardable(axis, dim):
        return (axis is not None and axis in mesh.axis_names
                and mesh.shape[axis] > 1 and dim % mesh.shape[axis] == 0)

    hshard = _shardable(head_axis, h)
    h_local = h // mesh.shape[head_axis] if hshard else h
    if p > 1 and h_local % p:
        raise ValueError(
            f"ulysses needs (local) heads % seq axis == 0, got "
            f"H={h}{f'/tp={h_local}' if hshard else ''}, P={p} "
            f"(use ring attention instead)")
    n_local = -(-n // p)
    n_padded = n_local * p
    q, k, v = (_pad_tokens(t, n_padded) for t in (q, k, v))

    spec = P(batch_axis if _shardable(batch_axis, b) else None, seq_axis,
             head_axis if hshard else None)
    out = jax.shard_map(
        functools.partial(_ulysses_local, axis_name=seq_axis, scale=scale,
                          n_valid=n, use_flash=use_flash,
                          interpret=interpret),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        **({"check_vma": False} if use_flash else {}),  # pallas: no vma
    )(q, k, v)
    return out[:, :n]
