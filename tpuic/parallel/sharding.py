"""Parameter/optimizer sharding: tensor parallelism + FSDP (ZeRO-3 style).

The reference replicates the model and optimizer on every rank (DDP,
train.py:128; replicated Adam, train.py:127) — pure data parallelism. Here
sharded training is a config choice on the same mesh (SURVEY.md §2c):

- **Tensor parallelism** (``model`` mesh axis): attention-bearing models
  annotate their kernels with flax logical axes (models/vit.py: ('embed',
  'model') on QKV/up projections, ('model', 'embed') on out/down). Mapping
  the logical ``model`` axis onto the mesh ``model`` axis yields
  Megatron-style head/hidden sharding; GSPMD propagates activation shardings
  and inserts the psum after the second contraction.
- **FSDP** (``data`` mesh axis): every large parameter (and its Adam
  moments, which mirror the param tree) is sharded over the data axis on its
  largest evenly-divisible dimension; XLA all-gathers weights just-in-time
  and reduce-scatters gradients — ZeRO-3 semantics without any runtime code.
- Anything small (biases, norm scales, BN stats, step counters) stays
  replicated: sharding them buys nothing and costs collective latency.

All of this produces *prefix pytrees of NamedSharding* fed to ``jax.jit``'s
in/out_shardings — there is no parameter-server or bucketing runtime to
maintain, which is the point of doing it the XLA way.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np
from flax.linen import spmd as flax_spmd
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Logical-axis name -> mesh-axis name. 'batch' only appears on activations,
# 'embed'/'model' on parameter matrices (models/vit.py).
def logical_rules(tp: bool, fsdp: bool):
    return (
        ("batch", "data"),
        ("embed", "data" if fsdp else None),
        ("model", "model" if tp else None),
        # Expert parallelism (models/moe.py): expert weight tensors shard
        # their leading E dim over the mesh 'model' axis — each device holds
        # E/ep experts; GSPMD inserts the token all-to-alls around the
        # expert einsums. 'unsharded' marks dims that must stay whole.
        ("expert", "model" if tp else None),
        ("unsharded", None),
    )


def _is_box(x) -> bool:
    return isinstance(x, flax_spmd.LogicallyPartitioned)


def _fsdp_dim(shape, data_size: int, taken: frozenset) -> Optional[int]:
    """Largest dim divisible by the data-axis size and not already sharded."""
    best, best_size = None, 0
    for i, s in enumerate(shape):
        if i in taken or s % data_size or s < data_size:
            continue
        if s > best_size:
            best, best_size = i, s
    return best


def state_partition_specs(state, mesh: Mesh, *, tp: bool = True,
                          fsdp: bool = False,
                          min_fsdp_size: int = 2 ** 12) -> Any:
    """PartitionSpec prefix tree for a TrainState (or any pytree of arrays).

    Logically-annotated leaves follow ``logical_rules``; unannotated leaves
    of >= min_fsdp_size elements are FSDP-sharded over 'data' when enabled;
    everything else is replicated. The returned tree replaces each flax
    metadata box with a single spec (a valid jit in_shardings prefix).
    """
    rules = dict(logical_rules(tp, fsdp))
    data_size = mesh.shape.get("data", 1)

    def leaf_spec(leaf):
        if _is_box(leaf):
            names = leaf.names
            val = tuple(int(s) for s in leaf.value.shape)
            axes = [rules.get(n) for n in names]
            # Drop mesh axes that don't divide the dim (e.g. tiny test models
            # on big meshes) or are size 1 (nothing to shard).
            for i, ax in enumerate(axes):
                if ax is None:
                    continue
                size = mesh.shape.get(ax, 1)
                if size <= 1 or val[i] % size:
                    axes[i] = None
            if fsdp and data_size > 1:
                taken = frozenset(i for i, ax in enumerate(axes)
                                  if ax is not None)
                if "data" not in axes and int(np.prod(val)) >= min_fsdp_size:
                    j = _fsdp_dim(val, data_size, taken)
                    if j is not None:
                        axes[j] = "data"
            return P(*axes)
        arr = leaf
        shape = tuple(getattr(arr, "shape", ()))
        size = int(np.prod(shape)) if shape else 1
        if fsdp and data_size > 1 and size >= min_fsdp_size:
            j = _fsdp_dim(shape, data_size, frozenset())
            if j is not None:
                spec = [None] * len(shape)
                spec[j] = "data"
                return P(*spec)
        return P()

    return jax.tree_util.tree_map(leaf_spec, state, is_leaf=_is_box)


def state_shardings(state, mesh: Mesh, *, tp: bool = True, fsdp: bool = False,
                    zero1: bool = False,
                    min_fsdp_size: int = 2 ** 12) -> Any:
    """NamedSharding prefix tree for jit in/out_shardings.

    ``zero1`` (weight-update/optimizer-state sharding, the ZeRO-1 point of
    the ZeRO family and the subject of arXiv:2004.13336): parameters stay
    replicated — DDP semantics, no weight all-gathers in the forward — but
    the optimizer moments shard over ``data``, so each device stores 1/N of
    the Adam state and computes 1/N of the weight update; GSPMD inserts one
    all-gather of the *update* (not the weights) per step. Ignored when
    full FSDP is on (ZeRO-3 already shards the moments with the params).
    """
    specs = state_partition_specs(state, mesh, tp=tp, fsdp=fsdp,
                                  min_fsdp_size=min_fsdp_size)
    if zero1 and not fsdp:
        opt_specs = state_partition_specs(state.opt_state, mesh, tp=tp,
                                          fsdp=True,
                                          min_fsdp_size=min_fsdp_size)
        specs = specs.replace(opt_state=opt_specs)
    return jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), specs,
                                  is_leaf=lambda x: isinstance(x, P))


def shard_state(state, shardings) -> Any:
    """Materialize a (host-built or replicated) state onto its shardings."""
    return jax.tree_util.tree_map(jax.device_put, state, shardings,
                                  is_leaf=_is_box)
