"""Collective utilities — the TPU-native descendant of reference ddp_utils.py.

Design shift (SURVEY.md §2b): the reference issues eager NCCL collectives from
Python — ``reduce_tensor`` (clone → all_reduce SUM → /world_size,
ddp_utils.py:8-12) and a pickle-based variable-size object ``all_gather``
(ddp_utils.py:16-56, used to collect ragged per-sample accuracy lists). Under
SPMD all shapes are static and collectives are *traced*, not issued, so:

- ``reduce_tensor``   → ``global_mean`` (lax.pmean inside the jitted step)
- ragged all_gather   → fixed-shape ``psum`` of (correct_count, total_count)
                        pairs, or ``all_gather_batch`` when per-sample values
                        really are needed (static shapes make padding explicit)

These helpers only work inside shard_map/pmapped code where the axis name is
bound; that is intentional — there is no eager collective path on TPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def pmean_tree(tree, axis_name: str = "data"):
    """Mean-all-reduce every leaf of a pytree across the named mesh axis.

    The gradient-averaging equivalent of DDP's bucketed all-reduce
    (reference train.py:128). XLA's latency-hiding scheduler overlaps these
    reductions with the backward computation, which is the compiled analogue
    of DDP's bucket/backward overlap.
    """
    return jax.tree_util.tree_map(lambda x: lax.pmean(x, axis_name), tree)


def psum_scalar(x, axis_name: str = "data"):
    """Sum-reduce a scalar across the axis (reference ddp_utils.py:10 SUM)."""
    return lax.psum(x, axis_name)


def global_mean(x, axis_name: str = "data"):
    """Mean across the axis — reference train.py:61-63 (all_reduce/world_size)."""
    return lax.pmean(x, axis_name)


def all_gather_batch(x, axis_name: str = "data"):
    """Gather per-shard arrays into one leading-device-axis array.

    Fixed-shape replacement for the pickle all_gather (ddp_utils.py:16-56):
    callers pad to a static per-shard size and carry a validity mask instead of
    gathering ragged lists.
    """
    return lax.all_gather(x, axis_name, tiled=True)
