from tpuic.parallel.collectives import (  # noqa: F401
    pmean_tree, psum_scalar, global_mean, all_gather_batch,
)
from tpuic.parallel.ring_attention import ring_attention  # noqa: F401
from tpuic.parallel.ulysses import ulysses_attention  # noqa: F401
