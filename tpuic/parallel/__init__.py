"""Parallelism strategies beyond data parallel.

The reference's eager NCCL helpers (ddp_utils.py:8-56 — ``reduce_tensor``
and the pickle-based ragged ``all_gather``) have no standalone equivalent
here BY DESIGN: under SPMD, collectives are traced into the jitted step
(grad pmean, SyncBN stat sync, metric reductions — tpuic/train/step.py) and
the ragged gather is redesigned as fixed-shape global outputs: the
per-sample correctness vector returned replicated from the sharded eval
step IS the cross-host all_gather, ridden over ICI by GSPMD
(make_eval_step(per_sample=True), used by Trainer.val_epoch's
misclassified-id collection).
"""

from tpuic.parallel.ring_attention import (ring_attention,  # noqa: F401
                                           ring_flash_attention)
from tpuic.parallel.ulysses import ulysses_attention  # noqa: F401
