"""Pipeline parallelism: GPipe microbatch scheduling as ONE SPMD program.

The reference has no pipeline parallelism (SURVEY.md §2c: "PP: No" — its
model is a single-module forward). This is the TPU-native construction:
instead of a runtime that shuttles activations between stage processes
(GPipe's original design), the whole pipeline is a single jitted program
over a ``stage`` mesh axis —

- stage s's parameters live on mesh slice s (leaves stacked [S, ...] and
  sharded ``P('stage')``);
- microbatches enter at stage 0 and flow stage-to-stage via
  ``lax.ppermute`` (neighbor ICI hops) inside a ``fori_loop`` running the
  classic GPipe schedule of M + S - 1 ticks with bubble steps masked;
- the loop is differentiable, so ``jax.grad`` of a loss through
  ``pipeline_apply`` yields exactly the backward pipeline (reverse
  schedule) without any hand-written scheduling code.

This composes with the other axes: the microbatch dim can itself be
data-sharded, and stage params can carry TP/EP logical axes. Capability is
proven against a sequential reference in tests/test_pipeline.py.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def pipeline_apply(stage_fn: Callable, stage_params, x: jnp.ndarray,
                   mesh: Mesh, axis: str = "stage",
                   x_spec: P = P()) -> jnp.ndarray:
    """Run ``x`` through S pipeline stages with GPipe microbatching.

    stage_fn: (params_one_stage, mb) -> mb — one stage's computation; the
        microbatch shape is the same on both sides (transformer-block
        style).
    stage_params: pytree whose leaves are stacked [S, ...] and sharded
        ``P(axis)`` over the mesh (stage s owns slice s).
    x: [M, mb, ...] microbatches, replicated over the stage axis. To
        compose with data parallelism pass ``x_spec`` sharding the
        microbatch (or later) dims over other mesh axes, e.g.
        ``P(None, 'data')`` on a ('data', 'stage') mesh — the pipeline
        then runs on each data shard's slice and outputs keep ``x_spec``.

    Returns [M, mb, ...] outputs, replicated over the stage axis (sharded
    per ``x_spec`` elsewhere).
    """
    if x_spec and axis in jax.tree_util.tree_leaves(tuple(x_spec)):
        raise ValueError(f"x_spec {x_spec} must not use the pipeline axis "
                         f"'{axis}' — microbatches are replicated over it")
    S = mesh.shape[axis]
    M = x.shape[0]

    for leaf in jax.tree_util.tree_leaves(stage_params):
        if leaf.shape[0] != S:
            raise ValueError(
                f"stage_params leaves must be stacked [{S}, ...] to match "
                f"mesh axis '{axis}'; got leading dim {leaf.shape[0]} — a "
                f"divisible mismatch would silently drop stages")

    def worker(params, xs):
        # Local [1, ...] slice of every stacked leaf -> this stage's params.
        local = jax.tree_util.tree_map(lambda p: p[0], params)
        idx = jax.lax.axis_index(axis)
        mb = jnp.zeros(xs.shape[1:], xs.dtype)
        outs = jnp.zeros_like(xs)
        fwd = [(i, (i + 1) % S) for i in range(S)]

        def tick(t, carry):
            mb, outs = carry
            # Stage 0 ingests microbatch t (a dummy repeat during drain
            # ticks — masked out at write time); others take the handoff.
            feed = jax.lax.dynamic_index_in_dim(
                xs, jnp.clip(t, 0, M - 1), keepdims=False)
            inp = jnp.where(idx == 0, feed, mb)
            y = stage_fn(local, inp)
            # The last stage finishes microbatch t-(S-1) at tick t.
            pos = t - (S - 1)
            prev = jax.lax.dynamic_index_in_dim(
                outs, jnp.clip(pos, 0, M - 1), keepdims=False)
            write = (idx == S - 1) & (pos >= 0)
            outs = jax.lax.dynamic_update_index_in_dim(
                outs, jnp.where(write, y, prev), jnp.clip(pos, 0, M - 1), 0)
            # Hand y to the next stage (the wrap edge S-1 -> 0 carries a
            # value stage 0 ignores).
            mb = jax.lax.ppermute(y, axis, fwd)
            return mb, outs

        _, outs = jax.lax.fori_loop(0, M + S - 1, tick, (mb, outs))
        # Only the last stage holds real outputs; psum replicates them
        # (every other stage contributes zeros).
        return jax.lax.psum(jnp.where(idx == S - 1, outs, 0.0), axis)

    spec = _stage_specs(stage_params, axis)
    return jax.shard_map(worker, mesh=mesh, in_specs=(spec, x_spec),
                         out_specs=x_spec, check_vma=False)(stage_params, x)


def _stage_specs(stage_params, axis: str):
    return jax.tree_util.tree_map(
        lambda p: P(axis, *(None,) * (p.ndim - 1)), stage_params)


def stack_stage_params(init_fn: Callable, rng, n_stages: int):
    """Initialize per-stage params and stack them on a leading [S] dim
    (shard with ``P('stage')`` before use)."""
    keys = jax.random.split(rng, n_stages)
    trees = [init_fn(k) for k in keys]
    return jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *trees)
