"""Ring attention — sequence/context parallelism over a mesh ``seq`` axis.

The reference has no sequence axis at all (SURVEY.md §5: fixed 299x299 CNN
inputs; its only parallelism is DDP data parallelism, train.py:128). This
module is the framework's long-context story, designed TPU-first rather than
ported: the token dimension of softmax attention is sharded over a ``seq``
mesh axis, each device holds one K/V block, and blocks rotate around the ICI
ring with ``lax.ppermute`` while a float32 online softmax accumulates — the
blockwise/RingAttention formulation (Liu et al., 2023). Peak memory per
device is O(N/P · N/P) for the score tile instead of O(N²), and each
ppermute is a neighbor hop on the torus, overlapped by XLA's latency-hiding
scheduler with the block matmuls.

Why not a port: a GPU implementation would be NCCL send/recv with manual
double-buffering; here the whole rotation is traced into one XLA program
via ``shard_map`` + ``ppermute`` and the compiler owns scheduling.

Autodiff: the rotation is plain traced ``jnp`` + ``ppermute`` (whose
transpose is the reverse permute), so ``jax.grad`` through the sharded
attention yields the reverse ring automatically — no custom VJP needed.
Each ring step is wrapped in ``jax.checkpoint``, so the backward pass
recomputes the per-step probability tiles instead of saving all P of them
— activation memory stays O(N/P · N/P) per device in backward too, not
O(N²/P).

Layout: [B, N, H, D] ("bqhd", matching models/vit.py). N is padded up to a
multiple of the ring size; padded key positions are masked to -inf, padded
query rows are sliced off, so any sequence length works (ViT's 197 tokens
included).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30


def _ring_step(qf, k, v, m, l, acc, *, step: int, axis_name: str,
               ring_size: int, n_valid: int, n_local: int):
    """One ring hop: score this device's current K/V block, fold into the
    online softmax, rotate K/V. Wrapped in jax.checkpoint by the caller so
    the backward pass recomputes the O(nq·n_local) probability tile instead
    of saving one per step (which would be O(N²/P) per device)."""
    idx = lax.axis_index(axis_name)
    b, nq = qf.shape[0], qf.shape[1]
    # With src->dst (i, i+1), after `step` hops we hold block idx-step.
    block_id = (idx - step) % ring_size
    kpos = block_id * n_local + lax.broadcasted_iota(
        jnp.int32, (b, 1, nq, n_local), 3)
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, k.astype(jnp.float32),
                   preferred_element_type=jnp.float32)
    s = jnp.where(kpos < n_valid, s, _NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m - m_new)
    l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc = acc * alpha + jnp.einsum(
        "bhqk,bkhd->bhqd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32)
    if step != ring_size - 1:
        perm = [(i, (i + 1) % ring_size) for i in range(ring_size)]
        k = lax.ppermute(k, axis_name, perm)
        v = lax.ppermute(v, axis_name, perm)
    return k, v, m_new, l, acc


def _ring_local(q, k, v, *, axis_name: str, ring_size: int, n_valid: int,
                n_local: int, scale: float):
    """Per-device body under shard_map: q is this device's query block
    [b, nq, H, D]; k/v start as this device's key block and rotate."""
    qf = q.astype(jnp.float32) * scale
    b, nq, h, d = qf.shape
    # Score space is [b, h, nq, bk]; accumulators carried across ring steps.
    m = jnp.full((b, h, nq, 1), _NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, nq, 1), jnp.float32)
    acc = jnp.zeros((b, h, nq, d), jnp.float32)

    for step in range(ring_size):  # ring_size is static: unrolled by trace
        fn = jax.checkpoint(functools.partial(
            _ring_step, step=step, axis_name=axis_name, ring_size=ring_size,
            n_valid=n_valid, n_local=n_local))
        k, v, m, l, acc = fn(qf, k, v, m, l, acc)

    out = acc / jnp.maximum(l, 1e-30)  # padded q rows (l=0) are sliced off
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)  # [b, nq, H, D]


def _pad_tokens(t: jnp.ndarray, to: int) -> jnp.ndarray:
    pad = to - t.shape[1]
    if pad == 0:
        return t
    return jnp.pad(t, ((0, 0), (0, pad), (0, 0), (0, 0)))


def ring_attention(q, k, v, mesh: Mesh, *, seq_axis: str = "seq",
                   batch_axis: Optional[str] = "data",
                   head_axis: Optional[str] = "model"):
    """Bidirectional softmax attention with the sequence dim sharded over
    ``mesh.shape[seq_axis]`` devices. q, k, v, out: [B, N, H, D].

    Batch is additionally sharded over ``batch_axis`` when it divides B
    (composing SP with DP), and heads over ``head_axis`` when it divides H
    (composing SP with Megatron TP — heads are independent, so a TP mesh's
    head-sharded activations stay sharded instead of being all-gathered).
    Falls back to a single-block computation when the seq axis has size 1 —
    same numerics, no collectives.
    """
    if seq_axis not in mesh.axis_names:
        raise ValueError(f"mesh has no '{seq_axis}' axis: {mesh.axis_names}")
    ring = mesh.shape[seq_axis]
    b, n, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    n_local = -(-n // ring)
    n_padded = n_local * ring
    q, k, v = (_pad_tokens(t, n_padded) for t in (q, k, v))

    def _shardable(axis, dim):
        return (axis is not None and axis in mesh.axis_names
                and mesh.shape[axis] > 1 and dim % mesh.shape[axis] == 0)

    spec = P(batch_axis if _shardable(batch_axis, b) else None, seq_axis,
             head_axis if _shardable(head_axis, h) else None)
    out = jax.shard_map(
        functools.partial(_ring_local, axis_name=seq_axis, ring_size=ring,
                          n_valid=n, n_local=n_local, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )(q, k, v)
    return out[:, :n]
