"""Ring attention — sequence/context parallelism over a mesh ``seq`` axis.

The reference has no sequence axis at all (SURVEY.md §5: fixed 299x299 CNN
inputs; its only parallelism is DDP data parallelism, train.py:128). This
module is the framework's long-context story, designed TPU-first rather than
ported: the token dimension of softmax attention is sharded over a ``seq``
mesh axis, each device holds one K/V block, and blocks rotate around the ICI
ring with ``lax.ppermute`` while a float32 online softmax accumulates — the
blockwise/RingAttention formulation (Liu et al., 2023). Peak memory per
device is O(N/P · N/P) for the score tile instead of O(N²), and each
ppermute is a neighbor hop on the torus, overlapped by XLA's latency-hiding
scheduler with the block matmuls.

Why not a port: a GPU implementation would be NCCL send/recv with manual
double-buffering; here the whole rotation is traced into one XLA program
via ``shard_map`` + ``ppermute`` and the compiler owns scheduling.

Autodiff: the rotation is plain traced ``jnp`` + ``ppermute`` (whose
transpose is the reverse permute), so ``jax.grad`` through the sharded
attention yields the reverse ring automatically — no custom VJP needed.
Each ring step is wrapped in ``jax.checkpoint``, so the backward pass
recomputes the per-step probability tiles instead of saving all P of them
— activation memory stays O(N/P · N/P) per device in backward too, not
O(N²/P).

Layout: [B, N, H, D] ("bqhd", matching models/vit.py). N is padded up to a
multiple of the ring size; padded key positions are masked to -inf, padded
query rows are sliced off, so any sequence length works (ViT's 197 tokens
included).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30


def _ring_perm(ring_size: int):
    return [(i, (i + 1) % ring_size) for i in range(ring_size)]


def _ring_step(qf, k, v, m, l, acc, *, step: int, axis_name: str,
               ring_size: int, n_valid: int, n_local: int):
    """One ring hop: score this device's current K/V block, fold into the
    online softmax, rotate K/V. Wrapped in jax.checkpoint by the caller so
    the backward pass recomputes the O(nq·n_local) probability tile instead
    of saving one per step (which would be O(N²/P) per device)."""
    idx = lax.axis_index(axis_name)
    b, nq = qf.shape[0], qf.shape[1]
    # With src->dst (i, i+1), after `step` hops we hold block idx-step.
    block_id = (idx - step) % ring_size
    kpos = block_id * n_local + lax.broadcasted_iota(
        jnp.int32, (b, 1, nq, n_local), 3)
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, k.astype(jnp.float32),
                   preferred_element_type=jnp.float32)
    s = jnp.where(kpos < n_valid, s, _NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m - m_new)
    l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc = acc * alpha + jnp.einsum(
        "bhqk,bkhd->bhqd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32)
    if step != ring_size - 1:
        perm = _ring_perm(ring_size)
        k = lax.ppermute(k, axis_name, perm)
        v = lax.ppermute(v, axis_name, perm)
    return k, v, m_new, l, acc


def _ring_local(q, k, v, *, axis_name: str, ring_size: int, n_valid: int,
                n_local: int, scale: float):
    """Per-device body under shard_map: q is this device's query block
    [b, nq, H, D]; k/v start as this device's key block and rotate."""
    qf = q.astype(jnp.float32) * scale
    b, nq, h, d = qf.shape
    # Score space is [b, h, nq, bk]; accumulators carried across ring steps.
    m = jnp.full((b, h, nq, 1), _NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, nq, 1), jnp.float32)
    acc = jnp.zeros((b, h, nq, d), jnp.float32)

    for step in range(ring_size):  # ring_size is static: unrolled by trace
        fn = jax.checkpoint(functools.partial(
            _ring_step, step=step, axis_name=axis_name, ring_size=ring_size,
            n_valid=n_valid, n_local=n_local))
        k, v, m, l, acc = fn(qf, k, v, m, l, acc)

    out = acc / jnp.maximum(l, 1e-30)  # padded q rows (l=0) are sliced off
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)  # [b, nq, H, D]


def _pad_tokens(t: jnp.ndarray, to: int) -> jnp.ndarray:
    pad = to - t.shape[1]
    if pad == 0:
        return t
    return jnp.pad(t, ((0, 0), (0, pad), (0, 0), (0, 0)))


def _ring_wrapper(q, k, v, mesh, seq_axis, batch_axis, head_axis, make_body,
                  **shard_map_kw):
    """Shared wrapper for both ring variants: validate the seq axis, pad
    tokens to a ring multiple, build the (batch, seq, head) PartitionSpec,
    shard_map the per-device body from ``make_body(ring, n, n_local)``, and
    slice the padding back off."""
    if seq_axis not in mesh.axis_names:
        raise ValueError(f"mesh has no '{seq_axis}' axis: {mesh.axis_names}")
    ring = mesh.shape[seq_axis]
    b, n, h, _ = q.shape
    n_local = -(-n // ring)
    n_padded = n_local * ring
    q, k, v = (_pad_tokens(t, n_padded) for t in (q, k, v))

    def _shardable(axis, dim):
        return (axis is not None and axis in mesh.axis_names
                and mesh.shape[axis] > 1 and dim % mesh.shape[axis] == 0)

    spec = P(batch_axis if _shardable(batch_axis, b) else None, seq_axis,
             head_axis if _shardable(head_axis, h) else None)
    out = jax.shard_map(
        make_body(ring, n, n_local), mesh=mesh,
        in_specs=(spec, spec, spec), out_specs=spec, **shard_map_kw,
    )(q, k, v)
    return out[:, :n]


def ring_attention(q, k, v, mesh: Mesh, *, seq_axis: str = "seq",
                   batch_axis: Optional[str] = "data",
                   head_axis: Optional[str] = "model"):
    """Bidirectional softmax attention with the sequence dim sharded over
    ``mesh.shape[seq_axis]`` devices. q, k, v, out: [B, N, H, D].

    Batch is additionally sharded over ``batch_axis`` when it divides B
    (composing SP with DP), and heads over ``head_axis`` when it divides H
    (composing SP with Megatron TP — heads are independent, so a TP mesh's
    head-sharded activations stay sharded instead of being all-gathered).
    Falls back to a single-block computation when the seq axis has size 1 —
    same numerics, no collectives.
    """
    scale = 1.0 / (q.shape[-1] ** 0.5)

    def make_body(ring, n, n_local):
        return functools.partial(_ring_local, axis_name=seq_axis,
                                 ring_size=ring, n_valid=n, n_local=n_local,
                                 scale=scale)

    return _ring_wrapper(q, k, v, mesh, seq_axis, batch_axis, head_axis,
                         make_body)


# -- ring + flash kernel composition -----------------------------------------
#
# The dense ring above materializes one [nq, n_local] score tile per step in
# HBM; at long context (n_local in the thousands) that tile is itself the
# memory/bandwidth problem flash attention exists to remove. ring-flash runs
# the Pallas flash kernel WITHIN each ring step — per-device peak becomes
# O(block² VMEM + n_local·D HBM) — and combines the per-step (out, lse)
# pairs with a streaming logsumexp. The flash kernels take the step's key
# validity as a device scalar (the rotating block id is only known at trace
# time) and write lse = -1e30 for fully-masked rows so a fully-padded block
# weighs ZERO in the combination (kernels' masked_sentinel).


def _ringflash_combine(out, lse, o_i, lse_i, b, h, n_local):
    """Fold one ring step's (o_i, lse_i) into the running (out, lse).

    lse arrays are the kernels' folded [b*h, 1, nq_padded] layout; weights
    are per (batch, head, token) — reshape to out's [b, n_local, h, 1]."""
    lse_new = jnp.logaddexp(lse, lse_i)

    def w(x):  # [b*h, 1, nq_padded] -> [b, n_local, h, 1]
        x = x.reshape(b, h, -1)[:, :, :n_local]
        return jnp.transpose(x, (0, 2, 1))[..., None]

    out_new = (out * w(jnp.exp(lse - lse_new))
               + o_i.astype(jnp.float32) * w(jnp.exp(lse_i - lse_new)))
    return out_new, lse_new


def _block_valid(idx, step, ring_size, n_valid, n_local):
    """Real-key count of the block this device holds at ``step`` (a traced
    scalar: block ownership rotates). Fully-padded tail blocks yield 0."""
    block_id = (idx - step) % ring_size
    return jnp.clip(n_valid - block_id * n_local, 0, n_local).reshape(1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _ringflash_local(q, k, v, axis_name, ring_size, n_valid, n_local,
                     interpret):
    out, _ = _ringflash_fwd_impl(q, k, v, axis_name, ring_size, n_valid,
                                 n_local, interpret)
    return out


def _ringflash_fwd_impl(q, k, v, axis_name, ring_size, n_valid, n_local,
                        interpret):
    from tpuic.kernels.flash_attention import (_NEG_INF, _resolve_blocks,
                                               _select_kernels)
    bq, bk = _resolve_blocks(n_local, None, None)
    idx = lax.axis_index(axis_name)
    b, _, h, _ = q.shape
    # The packed (natural-layout) kernel keeps the folded lse format
    # exactly, so the ring's cross-block combination is layout-agnostic.
    fwd, _ = _select_kernels(h, q.shape[-1])
    out = lse = None
    for step in range(ring_size):  # static: unrolled by trace
        valid = _block_valid(idx, step, ring_size, n_valid, n_local)
        o_i, lse_i = fwd(q, k, v, bq, bk, interpret, with_lse=True,
                         valid=valid, masked_sentinel=_NEG_INF)
        if out is None:
            out, lse = o_i.astype(jnp.float32), lse_i
        else:
            out, lse = _ringflash_combine(out, lse, o_i, lse_i, b, h,
                                          n_local)
        if step != ring_size - 1:
            perm = _ring_perm(ring_size)
            k = lax.ppermute(k, axis_name, perm)
            v = lax.ppermute(v, axis_name, perm)
    return out.astype(q.dtype), lse


def _ringflash_vjp_fwd(q, k, v, axis_name, ring_size, n_valid, n_local,
                       interpret):
    out, lse = _ringflash_fwd_impl(q, k, v, axis_name, ring_size, n_valid,
                                   n_local, interpret)
    # Residuals are O(n_local · D) + the lse row — never a score tile.
    return out, (q, k, v, out, lse)


def _ringflash_vjp_bwd(axis_name, ring_size, n_valid, n_local, interpret,
                       res, g):
    """Reverse ring: k/v rotate again, each step runs the blockwise flash
    backward against the GLOBAL (out, lse), and the dk/dv accumulators
    travel with their blocks — after ring_size rotations they are home."""
    from tpuic.kernels.flash_attention import (_resolve_blocks,
                                               _select_kernels)
    q, k, v, out, lse = res
    kdt, vdt = k.dtype, v.dtype
    bq, bk = _resolve_blocks(n_local, None, None)
    _, bwd = _select_kernels(q.shape[2], q.shape[3])
    idx = lax.axis_index(axis_name)
    do = g
    dq = jnp.zeros(q.shape, jnp.float32)
    dk = jnp.zeros(k.shape, jnp.float32)
    dv = jnp.zeros(v.shape, jnp.float32)
    perm = _ring_perm(ring_size)
    for step in range(ring_size):
        valid = _block_valid(idx, step, ring_size, n_valid, n_local)
        dq_i, dk_i, dv_i = bwd(q, k, v, out, lse, do, bq, bk,
                               interpret, valid=valid)
        dq = dq + dq_i.astype(jnp.float32)
        dk = dk + dk_i.astype(jnp.float32)
        dv = dv + dv_i.astype(jnp.float32)
        # Rotate every step (incl. the last): ring_size hops return the
        # k/dk/v/dv buffers to their owners.
        k, v, dk, dv = (lax.ppermute(t, axis_name, perm)
                        for t in (k, v, dk, dv))
    return dq.astype(q.dtype), dk.astype(kdt), dv.astype(vdt)


_ringflash_local.defvjp(_ringflash_vjp_fwd, _ringflash_vjp_bwd)


def ring_flash_attention(q, k, v, mesh: Mesh, *, seq_axis: str = "seq",
                         batch_axis: Optional[str] = "data",
                         head_axis: Optional[str] = "model",
                         interpret: Optional[bool] = None):
    """Ring attention with the Pallas flash kernel as the per-step block
    primitive — same signature and semantics as :func:`ring_attention`,
    O(N/P · D) per-device activation memory instead of the dense ring's
    O(N/P · N/P) score tile. See the module-section comment above."""
    if interpret is None:
        from tpuic.kernels import default_interpret
        interpret = default_interpret()

    def make_body(ring, n, n_local):
        # nondiff_argnums are positional: keywords would bypass custom_vjp's
        # argument bookkeeping.
        return lambda q_, k_, v_: _ringflash_local(
            q_, k_, v_, seq_axis, ring, n, n_local, interpret)

    return _ring_wrapper(q, k, v, mesh, seq_axis, batch_axis, head_axis,
                         make_body,
                         check_vma=False)  # pallas outs carry no vma
