"""Fused weighted cross-entropy as Pallas TPU kernels (forward + backward).

Numerics match ``tpuic.train.loss.weighted_cross_entropy`` (itself matching
torch ``nn.CrossEntropyLoss(weight=...)``, reference train.py:157-158): mean
of per-sample NLL scaled by the label's class weight, normalized by the sum of
applied weights; optional validity mask for SPMD batch padding; optional label
smoothing.

Fusion: log-sum-exp, label one-hot (iota comparison — no gather), weight
lookup and masking happen in one VMEM pass over the logits block, instead of
separate softmax/one-hot/mul/sum HLOs. The backward kernel recomputes softmax
and emits ``g * w * (p - onehot) / Σw`` in a single pass.

Sharding: the Pallas calls are opaque to GSPMD/Shardy, so with batch-sharded
logits they would be replicated behind an all-gather. Pass ``mesh`` and both
kernels run inside ``jax.shard_map`` over the ``data`` axis — they are
per-sample computations, so each device processes only its own batch shard.
The kernels emit per-sample [B, 1] columns; the Σ(w·nll)/Σw normalization is
ordinary sharded HLO outside the kernel (a psum, exactly the reference's
loss all-reduce at train.py:61-63).

Both kernels tile the batch dimension; the class dimension stays whole (C is
7..1000 here — one lane-tiled block). All operands are kept ≥2D for Mosaic's
(sublane, lane) tiling: labels/mask/per-sample outputs ride as [B, 1] columns,
class weights as a [1, C] row.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P


def _targets(x, labels_col, label_smoothing: float):
    """(onehot, smoothed target) for a [bb, C] block; labels_col is [bb, 1]."""
    bb, c = x.shape
    classes = jax.lax.broadcasted_iota(jnp.int32, (bb, c), 1)
    onehot = (classes == labels_col).astype(jnp.float32)
    if label_smoothing > 0.0:
        return onehot, onehot * (1.0 - label_smoothing) + label_smoothing / c
    return onehot, onehot


def _fwd_kernel(logits_ref, labels_ref, cw_ref, mask_ref, wnll_ref, w_ref, *,
                label_smoothing: float):
    x = logits_ref[:].astype(jnp.float32)                  # [bb, C]
    m = jnp.max(x, axis=-1, keepdims=True)
    lse = m + jnp.log(jnp.sum(jnp.exp(x - m), axis=-1, keepdims=True))
    logp = x - lse
    onehot, target = _targets(x, labels_ref[:], label_smoothing)
    nll = -jnp.sum(target * logp, axis=-1, keepdims=True)  # [bb, 1]
    w = jnp.sum(onehot * cw_ref[:], axis=-1, keepdims=True)
    w = w * mask_ref[:]
    wnll_ref[:] = w * nll
    w_ref[:] = w


def _bwd_kernel(logits_ref, labels_ref, cw_ref, mask_ref, scale_ref, out_ref,
                *, label_smoothing: float):
    x = logits_ref[:].astype(jnp.float32)
    m = jnp.max(x, axis=-1, keepdims=True)
    e = jnp.exp(x - m)
    p = e / jnp.sum(e, axis=-1, keepdims=True)
    onehot, target = _targets(x, labels_ref[:], label_smoothing)
    w = jnp.sum(onehot * cw_ref[:], axis=-1, keepdims=True) * mask_ref[:]
    # scale carries g / Σw (computed outside the kernel).
    out_ref[:] = ((p - target) * (w * scale_ref[0, 0])).astype(out_ref.dtype)


def _pad_batch(t, to):
    pad = to - t.shape[0]
    return t if pad == 0 else jnp.pad(t, ((0, pad),) + ((0, 0),) *
                                      (t.ndim - 1))


def _col_spec(block_b):
    return pl.BlockSpec((block_b, 1), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)


@functools.partial(jax.jit, static_argnames=("label_smoothing", "block_b",
                                             "interpret"))
def _fwd_persample(logits, labels, cw, mask, label_smoothing, block_b,
                   interpret):
    """Per-sample (w·nll, w) columns, [B, 1] each. Local / per-shard."""
    b, c = logits.shape
    block_b = min(block_b, -(-b // 8) * 8) if b < block_b else block_b
    bp = -(-b // block_b) * block_b
    wnll, w = pl.pallas_call(
        functools.partial(_fwd_kernel, label_smoothing=label_smoothing),
        out_shape=(jax.ShapeDtypeStruct((bp, 1), jnp.float32),
                   jax.ShapeDtypeStruct((bp, 1), jnp.float32)),
        grid=(bp // block_b,),
        in_specs=[
            pl.BlockSpec((block_b, c), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            _col_spec(block_b),
            pl.BlockSpec((1, c), lambda i: (0, 0), memory_space=pltpu.VMEM),
            _col_spec(block_b),
        ],
        out_specs=(_col_spec(block_b), _col_spec(block_b)),
        interpret=interpret,
    )(_pad_batch(logits, bp),
      _pad_batch(labels.astype(jnp.int32)[:, None], bp),
      cw[None, :],
      _pad_batch(mask.astype(jnp.float32)[:, None], bp))  # pads masked out
    return wnll[:b], w[:b]


@functools.partial(jax.jit, static_argnames=("label_smoothing", "block_b",
                                             "interpret"))
def _bwd_grads(logits, labels, cw, mask, scale, label_smoothing, block_b,
               interpret):
    """d logits [B, C]; ``scale`` is [1, 1] carrying g / Σw. Local/per-shard."""
    b, c = logits.shape
    block_b = min(block_b, -(-b // 8) * 8) if b < block_b else block_b
    bp = -(-b // block_b) * block_b
    grad = pl.pallas_call(
        functools.partial(_bwd_kernel, label_smoothing=label_smoothing),
        out_shape=jax.ShapeDtypeStruct((bp, c), logits.dtype),
        grid=(bp // block_b,),
        in_specs=[
            pl.BlockSpec((block_b, c), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            _col_spec(block_b),
            pl.BlockSpec((1, c), lambda i: (0, 0), memory_space=pltpu.VMEM),
            _col_spec(block_b),
            pl.BlockSpec((1, 1), lambda i: (0, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((block_b, c), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(_pad_batch(logits, bp), _pad_batch(labels.astype(jnp.int32)[:, None], bp),
      cw[None, :], _pad_batch(mask.astype(jnp.float32)[:, None], bp), scale)
    return grad[:b]


def _shard_batch(mesh: Optional[Mesh], b: int) -> bool:
    if mesh is None or "data" not in mesh.axis_names:
        return False
    n_data = mesh.shape["data"]
    return n_data > 1 and b % n_data == 0


def _canonicalize(logits, labels, class_weights, mask):
    b, c = logits.shape
    cw = (jnp.ones((c,), jnp.float32) if class_weights is None
          else jnp.asarray(class_weights, jnp.float32))
    m = jnp.ones((b,), jnp.float32) if mask is None else jnp.asarray(
        mask, jnp.float32)
    return cw, m


def _persample(logits, labels, cw, m, label_smoothing, block_b, interpret,
               mesh):
    if _shard_batch(mesh, logits.shape[0]):
        return jax.shard_map(
            lambda lg, lb, c_, ms: _fwd_persample(lg, lb, c_, ms,
                                                  label_smoothing, block_b,
                                                  interpret),
            mesh=mesh, in_specs=(P("data"), P("data"), P(), P("data")),
            out_specs=(P("data"), P("data")),
            check_vma=False,  # pallas out_shapes carry no vma annotations
        )(logits, labels, cw, m)
    return _fwd_persample(logits, labels, cw, m, label_smoothing, block_b,
                          interpret)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def fused_weighted_cross_entropy(logits, labels,
                                 class_weights: Optional[jnp.ndarray] = None,
                                 mask: Optional[jnp.ndarray] = None,
                                 label_smoothing: float = 0.0,
                                 block_b: int = 128,
                                 interpret: Optional[bool] = None,
                                 mesh: Optional[Mesh] = None):
    """Drop-in fused equivalent of ``weighted_cross_entropy`` (train/loss.py).

    Positional-only beyond ``mask`` (jax.custom_vjp restriction). ``mesh``
    keeps the kernel batch-parallel under a sharded jit (module docstring).
    """
    if interpret is None:
        from tpuic.kernels import default_interpret
        interpret = default_interpret()
    # Scope tag for the device-time waterfall (telemetry/profile.py).
    with jax.named_scope("fused_cross_entropy"):
        cw, m = _canonicalize(logits, labels, class_weights, mask)
        wnll, w = _persample(logits, labels, cw, m, label_smoothing,
                             block_b, interpret, mesh)
        return jnp.sum(wnll) / jnp.maximum(jnp.sum(w), 1e-12)


def _ce_fwd(logits, labels, class_weights, mask, label_smoothing, block_b,
            interpret, mesh):
    if interpret is None:
        from tpuic.kernels import default_interpret
        interpret = default_interpret()
    with jax.named_scope("fused_cross_entropy"):
        cw, m = _canonicalize(logits, labels, class_weights, mask)
        wnll, w = _persample(logits, labels, cw, m, label_smoothing,
                             block_b, interpret, mesh)
        sum_w = jnp.sum(w)
        loss = jnp.sum(wnll) / jnp.maximum(sum_w, 1e-12)
    return loss, (logits, labels, cw, m, sum_w)


def _ce_bwd(label_smoothing, block_b, interpret, mesh, res, g):
    logits, labels, cw, m, sum_w = res
    if interpret is None:
        from tpuic.kernels import default_interpret
        interpret = default_interpret()
    scale = (g / jnp.maximum(sum_w, 1e-12)).reshape(1, 1).astype(jnp.float32)
    if _shard_batch(mesh, logits.shape[0]):
        dlogits = jax.shard_map(
            lambda lg, lb, c_, ms, sc: _bwd_grads(lg, lb, c_, ms, sc,
                                                  label_smoothing, block_b,
                                                  interpret),
            mesh=mesh, in_specs=(P("data"), P("data"), P(), P("data"), P()),
            out_specs=P("data"),
            check_vma=False,  # pallas out_shapes carry no vma annotations
        )(logits, labels, cw, m, scale)
    else:
        dlogits = _bwd_grads(logits, labels, cw, m, scale, label_smoothing,
                             block_b, interpret)
    return dlogits, None, None, None


fused_weighted_cross_entropy.defvjp(_ce_fwd, _ce_bwd)
