"""Fused conv + folded-BN affine + ReLU as a Pallas TPU kernel.

The ResNet inference hot path is ``conv -> batch_norm -> relu`` repeated
~50 times.  At inference BN is a pure per-channel affine (running stats
are constants), yet the unfused graph writes the conv output to HBM,
reads it back for the scale/shift, writes again, reads again for the
ReLU — the elementwise/copy traffic the roofline waterfall
(telemetry/profile.py) books against the ``elementwise``/``copy``
classes.  This kernel keeps the whole block in VMEM:

- **conv as tap matmuls**: a KxK conv over an NHWC block is the sum over
  the K*K taps of ``[H_out*W_out, Cin] @ [Cin, Cout]`` matmuls — each
  tap feeds the 128x128 MXU as a plain GEMM (the same re-layout idea as
  the space-to-depth stem, models/resnet.py), accumulated in float32 in
  VMEM.
- **BN folded to an affine epilogue**: ``scale = gamma * rsqrt(var+eps)``
  and ``bias = beta - mean * scale`` are precomputed (``fold_bn``); the
  kernel applies ``y * scale + bias`` and the optional ReLU on the
  accumulator **before** the single output write.  One HBM write per
  block instead of conv-out + bn-out + relu-out.

Grid: one batch element per grid step — the weights and the affine stay
resident in VMEM across the grid, and per-image activations for the
ResNet stage sizes (<= 112x112x64 at 224px, <= 32x32x64 on CIFAR) fit
comfortably.  The batch dim is embarrassingly parallel, so under a
sharded jit GSPMD keeps the kernel batch-parallel like every other
per-sample Pallas call here (cross_entropy.py's discipline).

**Inference only**: training BN needs the *batch* statistics of the conv
output (a cross-batch reduction this per-image kernel cannot see), so
the train path keeps the unfused reference graph; the flag that wires
this kernel into the model zoo (ModelConfig.fused_conv_bn) applies to
``train=False`` calls only, and numerics parity against the unfused
reference is pinned in tests/test_kernels.py (atol 1e-4 in float32 —
the tap-matmul accumulation order differs from XLA's conv).

On CPU (CI) the kernel runs in Pallas interpret mode like every other
kernel in this package; on TPU it compiles via Mosaic.  Stride-2 taps
read through ``jax.lax.slice`` with strides on the VMEM-resident block.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Padding = Union[int, Sequence[Tuple[int, int]]]


def fold_bn(gamma, beta, mean, var, eps: float = 1e-5):
    """BN running stats -> the per-channel affine the kernel applies.

    Matches ``nn.BatchNorm(use_running_average=True)`` exactly:
    ``y = (x - mean) * gamma * rsqrt(var + eps) + beta``.
    Returns float32 ``(scale, bias)`` rows of shape [Cout]."""
    scale = (jnp.asarray(gamma, jnp.float32)
             * jax.lax.rsqrt(jnp.asarray(var, jnp.float32) + eps))
    bias = jnp.asarray(beta, jnp.float32) - jnp.asarray(mean,
                                                        jnp.float32) * scale
    return scale, bias


def _norm_padding(padding: Padding) -> Tuple[Tuple[int, int],
                                             Tuple[int, int]]:
    if isinstance(padding, int):
        return ((padding, padding), (padding, padding))
    (pt, pb), (pl_, pr) = padding
    return ((int(pt), int(pb)), (int(pl_), int(pr)))


def _kernel(x_ref, w_ref, scale_ref, bias_ref, out_ref, *, kh: int, kw: int,
            sh: int, sw: int, ho: int, wo: int, relu: bool):
    """One batch element: accumulate the K*K tap matmuls in f32, apply
    the folded-BN affine + optional ReLU, write once."""
    xb = x_ref[0]                                    # [Hp, Wp, Cin]
    cin = xb.shape[-1]
    cout = out_ref.shape[-1]
    acc = jnp.zeros((ho * wo, cout), jnp.float32)
    for ki in range(kh):
        for kj in range(kw):
            # Tap (ki, kj)'s receptive field: rows ki, ki+sh, ... — a
            # strided window over the VMEM-resident block (a value-level
            # lax.slice, not a memory gather).
            patch = jax.lax.slice(
                xb, (ki, kj, 0),
                (ki + (ho - 1) * sh + 1, kj + (wo - 1) * sw + 1, cin),
                (sh, sw, 1))                         # [ho, wo, Cin]
            acc += jnp.dot(patch.reshape(ho * wo, cin), w_ref[ki, kj],
                           preferred_element_type=jnp.float32)
    y = acc * scale_ref[0] + bias_ref[0]
    if relu:
        y = jnp.maximum(y, 0.0)
    out_ref[0] = y.reshape(ho, wo, cout).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("strides", "padding", "relu",
                                             "interpret", "out_dtype"))
def _fused(x, w, scale, bias, strides, padding, relu, interpret, out_dtype):
    b, h, w_in, cin = x.shape
    kh, kw, wcin, cout = w.shape
    if wcin != cin:
        raise ValueError(f"kernel expects Cin={wcin}, input has {cin}")
    sh, sw = strides
    (pt, pb), (pl_, pr) = padding
    ho = (h + pt + pb - kh) // sh + 1
    wo = (w_in + pl_ + pr - kw) // sw + 1
    if ho < 1 or wo < 1:
        raise ValueError(f"empty output for input {x.shape}, kernel "
                         f"{w.shape}, strides {strides}, padding {padding}")
    xp = jnp.pad(x, ((0, 0), (pt, pb), (pl_, pr), (0, 0)))
    hp, wp = xp.shape[1], xp.shape[2]
    # The grid walks the batch; weights + the affine rows use a constant
    # index map, so they stay VMEM-resident across all B steps.
    out = pl.pallas_call(
        functools.partial(_kernel, kh=kh, kw=kw, sh=sh, sw=sw, ho=ho,
                          wo=wo, relu=relu),
        out_shape=jax.ShapeDtypeStruct((b, ho, wo, cout), out_dtype),
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, hp, wp, cin), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((kh, kw, cin, cout), lambda i: (0, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, cout), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, cout), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, ho, wo, cout), lambda i: (i, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(xp, w, scale[None, :], bias[None, :])
    return out


def fused_conv_bn_relu(x, w, scale, bias, *,
                       strides: Union[int, Tuple[int, int]] = 1,
                       padding: Padding = 0, relu: bool = True,
                       interpret: Optional[bool] = None,
                       out_dtype=None):
    """``relu(conv(x, w) * scale + bias)`` in one VMEM pass.

    x: [B, H, W, Cin] NHWC; w: [kh, kw, Cin, Cout] (flax nn.Conv layout);
    scale/bias: [Cout] — the folded BN affine from :func:`fold_bn` (pass
    ``scale=ones, bias=zeros`` for a bare conv+ReLU).  ``relu=False``
    stops before the activation (the residual-add case).  Accumulation
    is float32 regardless of input dtype; output dtype defaults to
    ``x.dtype``."""
    if interpret is None:
        from tpuic.kernels import default_interpret
        interpret = default_interpret()
    if isinstance(strides, int):
        strides = (strides, strides)
    with jax.named_scope("fused_conv_bn_relu"):
        return _fused(x, w, jnp.asarray(scale, jnp.float32),
                      jnp.asarray(bias, jnp.float32),
                      (int(strides[0]), int(strides[1])),
                      _norm_padding(padding), bool(relu), bool(interpret),
                      jnp.dtype(out_dtype or x.dtype))


def fused_conv_bn_from_flax(x, kernel, bn_params, bn_stats, *,
                            strides: Union[int, Tuple[int, int]] = 1,
                            padding: Padding = 0, relu: bool = True,
                            eps: float = 1e-5,
                            interpret: Optional[bool] = None):
    """Convenience wrapper over flax variable dicts: ``kernel`` is the
    nn.Conv ``kernel`` leaf, ``bn_params``/``bn_stats`` the matching
    nn.BatchNorm ``{'scale','bias'}`` / ``{'mean','var'}`` dicts — the
    exact trees the ResNet blocks read in their fused-inference branch
    (models/resnet.py)."""
    scale, bias = fold_bn(bn_params["scale"], bn_params["bias"],
                          bn_stats["mean"], bn_stats["var"], eps)
    return fused_conv_bn_relu(x, kernel, scale, bias, strides=strides,
                              padding=padding, relu=relu,
                              interpret=interpret)
