"""Pallas TPU kernels for the hot ops (SURVEY.md §7 step 5).

Kernels compile to Mosaic on TPU; on CPU (CI, the 8-device mesh tests) they
run in Pallas interpret mode so the same kernel logic is exercised everywhere.
"""

from tpuic.kernels.conv_bn_relu import (fold_bn,  # noqa: F401
                                        fused_conv_bn_from_flax,
                                        fused_conv_bn_relu)
from tpuic.kernels.cross_entropy import fused_weighted_cross_entropy  # noqa: F401
from tpuic.kernels.flash_attention import flash_attention  # noqa: F401
from tpuic.kernels.optimizer_update import (default_opt_impl,  # noqa: F401
                                            lamb_leaf_update,
                                            lars_leaf_update)


def default_interpret() -> bool:
    """Interpret mode on anything that is not a real TPU backend."""
    import jax

    return jax.default_backend() not in ("tpu",)
