"""Fused optimizer-update kernels (LARS / LAMB trust-ratio variants).

The train step's optimizer update is the optax chain's stack of elementwise
transforms — for LARS: add_decayed_weights -> scale_by_trust_ratio ->
scale_by_learning_rate -> trace — each materializing an update-sized tree, so
params/grads/moments make several HBM round trips per step for arithmetic
that is one multiply-add deep. Here the whole per-leaf update is ONE Pallas
VMEM pass (the PR-10 waterfall's 'optimizer_update' elementwise+copy slice is
exactly this traffic):

- **LARS** (You et al., arXiv:1708.03888; optax.lars semantics, including
  update order wd -> trust -> -lr -> momentum trace): the trust-ratio
  norms ``||w||`` and ``||g + wd*w||`` are two reductions whose decayed
  direction XLA fuses into the reduce (never materialized to HBM), after
  which ONE kernel pass reads (g, w, m) and writes the new momentum
  buffer ``m' = (-lr * trust) * (g + wd*w) + mu * m`` — which IS the update
  (optax.trace applies momentum after lr scaling). The optax chain instead
  round-trips four update-sized temporaries (decay, trust-scale, lr-scale,
  trace) through memory.
- **LAMB** (You et al., arXiv:1904.00962; optax.lamb semantics: Adam moments
  with bias correction -> wd -> trust -> -lr): one kernel pass reads
  (g, w, m, v) and writes (m', v', u) where ``u`` is the decayed
  bias-corrected Adam direction; the trust ratio ``||w||/||u||`` and the
  final ``-lr * trust`` rescale are scalar jnp ops outside (XLA fuses the
  rescale into the apply-updates add).

Leaves are flattened and tiled to (rows, 128) f32 blocks (the VPU lane
width; min f32 tile is (8, 128) — pallas_guide.md). Zero padding is
self-consistent: padded g/w/m/v are 0, so padded outputs are 0 and norms are
computed on the unpadded leaf.

``impl='jnp'`` runs the identical math as one fused jnp expression — the
graceful CPU/interpreter fallback (and the GSPMD-friendly path: Pallas calls
are opaque to the partitioner, while the jnp form shards leaf-locally, which
is what makes the fused update compose with the PR-15 ZeRO sharding — each
device updates only its own moment shard). ``default_opt_impl()`` picks
'pallas' on TPU and 'jnp' elsewhere; tests force the Pallas interpreter on
CPU to pin kernel-logic parity against optax and the numpy references.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_DEF_BLOCK_ROWS = 256  # (256, 128) f32 = 128 KiB/operand per grid step


def default_opt_impl() -> str:
    """'pallas' on TPU, 'jnp' anywhere else (CPU CI, GSPMD-sharded jits)."""
    return "pallas" if jax.default_backend() == "tpu" else "jnp"


def _tile(x: jnp.ndarray, block_rows: int) -> Tuple[jnp.ndarray, int]:
    """Leaf -> zero-padded (rows, 128) f32 tile; rows % block_rows == 0."""
    n = x.size
    rows = -(-n // _LANES)
    rows = -(-rows // block_rows) * block_rows
    flat = jnp.ravel(x).astype(jnp.float32)
    flat = jnp.pad(flat, (0, rows * _LANES - n))
    return flat.reshape(rows, _LANES), n


def _untile(t: jnp.ndarray, n: int, shape, dtype) -> jnp.ndarray:
    return t.reshape(-1)[:n].reshape(shape).astype(dtype)


def _block_rows(rows: int) -> int:
    """Largest (multiple-of-8) block that tiles ``rows`` without waste."""
    return min(_DEF_BLOCK_ROWS, -(-rows // 8) * 8)


def _vec_spec(block_rows: int):
    return pl.BlockSpec((block_rows, _LANES), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)


def _smem_spec():
    return pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM)


def _scalar(x) -> jnp.ndarray:
    return jnp.asarray(x, jnp.float32).reshape(1, 1)


# -- LARS ----------------------------------------------------------------

def _lars_kernel(a_ref, g_ref, w_ref, m_ref, out_ref, *, weight_decay: float,
                 momentum: float):
    # m' = a * (g + wd*w) + mu*m, a = -lr * trust (traced scalar, SMEM).
    a = a_ref[0, 0]
    u = g_ref[:] + weight_decay * w_ref[:]
    out_ref[:] = a * u + momentum * m_ref[:]


def lars_leaf_update(w: jnp.ndarray, g: jnp.ndarray, m: jnp.ndarray, *,
                     lr, weight_decay: float, trust_coefficient: float,
                     momentum: float, impl: Optional[str] = None,
                     interpret: Optional[bool] = None,
                     block_rows: int = _DEF_BLOCK_ROWS) -> jnp.ndarray:
    """One leaf's fused LARS update: returns m' (== the update — optax's
    trace runs after lr scaling, so the momentum buffer IS the step)."""
    if impl is None:
        impl = default_opt_impl()
    wd = float(weight_decay)
    w32 = w.astype(jnp.float32)
    g32 = g.astype(jnp.float32)
    # XLA fuses the decayed direction into the norm reduction (and, on the
    # jnp path, into the update expression) — it never hits HBM here.
    u32 = g32 + wd * w32
    pn = jnp.sqrt(jnp.vdot(w32, w32))
    un = jnp.sqrt(jnp.vdot(u32, u32))
    trust = jnp.where((pn == 0.0) | (un == 0.0), 1.0,
                      trust_coefficient * pn / un)
    a = -jnp.asarray(lr, jnp.float32) * trust
    if impl == "jnp":
        out = a * u32 + momentum * m.astype(jnp.float32)
        return out.astype(m.dtype)
    if interpret is None:
        from tpuic.kernels import default_interpret
        interpret = default_interpret()
    gt, n = _tile(g, block_rows)
    wt, _ = _tile(w, block_rows)
    mt, _ = _tile(m, block_rows)
    br = _block_rows(gt.shape[0])
    out = pl.pallas_call(
        functools.partial(_lars_kernel, weight_decay=wd,
                          momentum=float(momentum)),
        out_shape=jax.ShapeDtypeStruct(gt.shape, jnp.float32),
        grid=(gt.shape[0] // br,),
        in_specs=[_smem_spec(), _vec_spec(br), _vec_spec(br), _vec_spec(br)],
        out_specs=_vec_spec(br),
        interpret=interpret,
    )(_scalar(a), gt, wt, mt)
    return _untile(out, n, m.shape, m.dtype)


# -- LAMB ----------------------------------------------------------------

def _lamb_kernel(c1_ref, c2_ref, g_ref, w_ref, m_ref, v_ref,
                 m_out, v_out, u_out, *, b1: float, b2: float, eps: float,
                 weight_decay: float):
    # Adam moments + bias correction + weight decay in one pass; c1/c2
    # carry the traced 1/(1 - b^t) debias factors (SMEM scalars).
    g = g_ref[:]
    m_new = b1 * m_ref[:] + (1.0 - b1) * g
    v_new = b2 * v_ref[:] + (1.0 - b2) * g * g
    mh = m_new * c1_ref[0, 0]
    vh = v_new * c2_ref[0, 0]
    m_out[:] = m_new
    v_out[:] = v_new
    u_out[:] = mh / (jnp.sqrt(vh) + eps) + weight_decay * w_ref[:]


def lamb_leaf_update(w: jnp.ndarray, g: jnp.ndarray, m: jnp.ndarray,
                     v: jnp.ndarray, count: jnp.ndarray, *, lr, b1: float,
                     b2: float, eps: float, weight_decay: float,
                     impl: Optional[str] = None,
                     interpret: Optional[bool] = None,
                     block_rows: int = _DEF_BLOCK_ROWS):
    """One leaf's fused LAMB update: (update, m', v').

    ``count`` is the number of PREVIOUS updates (optax ScaleByAdamState
    convention); debiasing uses t = count + 1.
    """
    if impl is None:
        impl = default_opt_impl()
    wd = float(weight_decay)
    t = (jnp.asarray(count, jnp.int32) + 1).astype(jnp.float32)
    c1 = 1.0 / (1.0 - jnp.power(b1, t))
    c2 = 1.0 / (1.0 - jnp.power(b2, t))
    w32 = w.astype(jnp.float32)
    g32 = g.astype(jnp.float32)
    if impl == "jnp":
        m_new = b1 * m.astype(jnp.float32) + (1.0 - b1) * g32
        v_new = b2 * v.astype(jnp.float32) + (1.0 - b2) * g32 * g32
        u = (m_new * c1) / (jnp.sqrt(v_new * c2) + eps) + wd * w32
    else:
        if interpret is None:
            from tpuic.kernels import default_interpret
            interpret = default_interpret()
        gt, n = _tile(g, block_rows)
        wt, _ = _tile(w, block_rows)
        mt, _ = _tile(m, block_rows)
        vt, _ = _tile(v, block_rows)
        br = _block_rows(gt.shape[0])
        sds = jax.ShapeDtypeStruct(gt.shape, jnp.float32)
        m_new, v_new, u = pl.pallas_call(
            functools.partial(_lamb_kernel, b1=float(b1), b2=float(b2),
                              eps=float(eps), weight_decay=wd),
            out_shape=(sds, sds, sds),
            grid=(gt.shape[0] // br,),
            in_specs=[_smem_spec(), _smem_spec(), _vec_spec(br),
                      _vec_spec(br), _vec_spec(br), _vec_spec(br)],
            out_specs=(_vec_spec(br), _vec_spec(br), _vec_spec(br)),
            interpret=interpret,
        )(_scalar(c1), _scalar(c2), gt, wt, mt, vt)
        m_new = _untile(m_new, n, m.shape, jnp.float32)
        v_new = _untile(v_new, n, v.shape, jnp.float32)
        u = _untile(u, n, w.shape, jnp.float32)
    pn = jnp.sqrt(jnp.vdot(w32, w32))
    un = jnp.sqrt(jnp.vdot(u, u))
    trust = jnp.where((pn == 0.0) | (un == 0.0), 1.0, pn / un)
    upd = ((-jnp.asarray(lr, jnp.float32) * trust) * u).astype(w.dtype)
    return upd, m_new.astype(m.dtype), v_new.astype(v.dtype)
