"""Flash attention (blockwise online-softmax) as a Pallas TPU kernel.

The reference has no attention op at all (SURVEY.md §2c: vision CNNs only);
attention enters this framework through the ViT backbone (BASELINE.md config
4) and the sequence-parallel path (tpuic/parallel/ring_attention.py). This
kernel is the per-device block primitive: the forward never materializes the
[N, N] probability matrix in HBM — only [block_q, block_k] tiles in VMEM —
and contractions are MXU-shaped with a float32 online softmax carried across
key blocks.

Backward is recompute-based (jax.custom_vjp): probabilities are rebuilt by
differentiating a dense float32-softmax form that matches the forward
kernel's numerics. This means the *backward* pass does materialize O(N²)
attention scores (standard dense memory); the flash memory win currently
applies to inference and to the forward residuals (q, k, v only — no saved
probabilities). A blockwise Pallas backward is the planned upgrade.

Sharding: a Pallas call is an opaque custom call — GSPMD/Shardy cannot
partition it and would all-gather batch-sharded operands onto every device.
Pass ``mesh`` (with a ``data`` axis) and the wrapper runs the kernel inside
``jax.shard_map`` over the batch axis, keeping the computation fully
batch-parallel; attention itself is per-sample so no collectives are needed.

Layout: [B, N, H, D] ("bqhd", matching models/vit.py einsums). N is padded to
the key-block size with masked (-inf) keys, so callers can pass any length
(ViT's 197 tokens included).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, scale: float,
                valid_len: int):
    """One (batch*head, q-block) program: online softmax over key blocks."""
    q = q_ref[0].astype(jnp.float32) * scale            # [bq, D]
    bq = q.shape[0]
    n_padded = k_ref.shape[1]
    d = q.shape[-1]

    m = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l = jnp.zeros((bq, 1), jnp.float32)
    acc = jnp.zeros((bq, d), jnp.float32)

    for j in range(n_padded // block_k):
        kj = k_ref[0, j * block_k:(j + 1) * block_k, :].astype(jnp.float32)
        vj = v_ref[0, j * block_k:(j + 1) * block_k, :].astype(jnp.float32)
        s = jax.lax.dot_general(q, kj, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # [bq, bk]
        kpos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1)
        s = jnp.where(kpos < valid_len, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.dot(p, vj,
                                    preferred_element_type=jnp.float32)
        m = m_new

    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _pad_seq(t: jnp.ndarray, to: int) -> jnp.ndarray:
    pad = to - t.shape[1]
    if pad == 0:
        return t
    return jnp.pad(t, ((0, 0), (0, pad), (0, 0)))


@functools.partial(jax.jit, static_argnames=("block_q", "block_k",
                                             "interpret"))
def _flash_fwd(q, k, v, block_q: int, block_k: int, interpret: bool):
    """q,k,v: [B, N, H, D] -> out [B, N, H, D]. Single-device (or per-shard)."""
    b, n, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    n_pad_q = -(-n // block_q) * block_q
    n_pad_k = -(-n // block_k) * block_k
    n_padded = max(n_pad_q, n_pad_k)

    def fold(t):  # [B,N,H,D] -> [B*H, N_padded, D]
        t = jnp.transpose(t, (0, 2, 1, 3)).reshape(b * h, n, d)
        return _pad_seq(t, n_padded)

    qf, kf, vf = fold(q), fold(k), fold(v)
    grid = (b * h, n_padded // block_q)
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, block_k=block_k, scale=scale,
                          valid_len=n),
        out_shape=jax.ShapeDtypeStruct((b * h, n_padded, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n_padded, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n_padded, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=4 * b * h * n_padded * n_padded * d,
            bytes_accessed=3 * b * h * n_padded * d * q.dtype.itemsize,
            transcendentals=b * h * n_padded * n_padded),
    )(qf, kf, vf)
    out = out[:, :n].reshape(b, h, n, d)
    return jnp.transpose(out, (0, 2, 1, 3))


def _dense_attention_f32(q, k, v):
    """Dense reference with the same numerics as the kernel: f32 scores, f32
    softmax, f32 p·v contraction, cast to input dtype at the end. Used for the
    recompute backward so the gradient is of the function the forward computed."""
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * (1.0 / (d ** 0.5))
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def _shard_batch(mesh: Optional[Mesh], b: int) -> bool:
    """True when the kernel should run under shard_map over the data axis."""
    if mesh is None or "data" not in mesh.axis_names:
        return False
    n_data = mesh.shape["data"]
    return n_data > 1 and b % n_data == 0


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, block_q: int = 128, block_k: int = 128,
                    interpret: Optional[bool] = None,
                    mesh: Optional[Mesh] = None):
    """Softmax attention, [B, N, H, D] in/out, no causal mask (ViT is
    bidirectional). ``interpret=None`` auto-selects interpret mode off-TPU;
    ``mesh`` keeps the kernel batch-parallel under a sharded jit (see module
    docstring)."""
    if interpret is None:
        from tpuic.kernels import default_interpret
        interpret = default_interpret()
    if _shard_batch(mesh, q.shape[0]):
        spec = P("data")
        return jax.shard_map(
            lambda a, b_, c: _flash_fwd(a, b_, c, block_q, block_k, interpret),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,  # pallas out_shapes carry no vma annotations
        )(q, k, v)
    return _flash_fwd(q, k, v, block_q, block_k, interpret)


def _vjp_fwd(q, k, v, block_q, block_k, interpret, mesh):
    out = flash_attention(q, k, v, block_q, block_k, interpret, mesh)
    return out, (q, k, v)


def _vjp_bwd(block_q, block_k, interpret, mesh, res, g):
    q, k, v = res
    # Recompute-based backward (see module docstring): plain jnp ops, which
    # GSPMD shards over the batch axis natively — no shard_map needed.
    _, pullback = jax.vjp(_dense_attention_f32, q, k, v)
    return pullback(g)


flash_attention.defvjp(_vjp_fwd, _vjp_bwd)
