"""Flash attention (blockwise online-softmax) as a Pallas TPU kernel.

The reference has no attention op at all (SURVEY.md §2c: vision CNNs only);
attention enters this framework through the ViT backbone (BASELINE.md config
4) and the sequence-parallel path (tpuic/parallel/ring_attention.py). This
kernel is the per-device block primitive: the forward never materializes the
[N, N] probability matrix in HBM — only [block_q, block_k] tiles in VMEM —
and contractions are MXU-shaped with a float32 online softmax carried across
key blocks.

Backward is blockwise Pallas too (jax.custom_vjp): the forward saves only
(q, k, v, o, logsumexp) — no probability matrix — and two backward kernels
rebuild [block_q, block_k] probability tiles in VMEM from the saved
logsumexp: a dq kernel gridded over query blocks and a dk/dv kernel gridded
over key blocks, both using the standard FlashAttention identity
ds = p * (dp - rowsum(do·o)). Peak HBM stays O(N·D) end to end.

Sharding: a Pallas call is an opaque custom call — GSPMD/Shardy cannot
partition it and would all-gather batch-sharded operands onto every device.
Pass ``mesh`` (with a ``data`` axis) and the wrapper runs the kernel inside
``jax.shard_map`` over the batch axis, keeping the computation fully
batch-parallel; attention itself is per-sample so no collectives are needed.

Layout: [B, N, H, D] ("bqhd", matching models/vit.py einsums). N is padded to
the key-block size with masked (-inf) keys, so callers can pass any length
(ViT's 197 tokens included).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30


# Lane width of the (block_q, LANES) f32 scratch that carries the online
# softmax m/l rows across key-block grid steps (TPU vregs are 128 lanes; a
# [bq, 1] scratch would not tile).
_LANES = 128

# Grid semantics for every kernel here: (batch*head, outer-block) are
# embarrassingly parallel; the innermost axis is the sequential reduction
# that the VMEM scratch accumulates across.
_DIM_SEMANTICS = ("parallel", "parallel", "arbitrary")


def _compiler_params(semantics=_DIM_SEMANTICS):
    try:
        return pltpu.CompilerParams(dimension_semantics=semantics)
    except (AttributeError, TypeError):  # older pallas naming
        return pltpu.TPUCompilerParams(dimension_semantics=semantics)


# Packed grids are (batch, head-pair, own-block, reduction).
def _compiler_params_4d():
    return _compiler_params(("parallel", "parallel", "parallel",
                             "arbitrary"))


def _dot_precision(dtype) -> jax.lax.Precision:
    """MXU precision for kernel contractions, by operand dtype.

    bf16 operands are a native single MXU pass — leave the default. f32
    operands MUST be HIGHEST: the default lowers f32 matmuls to ONE lossy
    bf16 pass (measured 5e-3 max error on chip, round-3 smoke), which
    would silently degrade f32 attention."""
    return (jax.lax.Precision.HIGHEST if dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)


def _f32_for(ref_dtype, x):
    """Softmax-side f32 view of a probability tile, cast back to the
    operand dtype only when the MXU pass is narrow anyway."""
    return x.astype(ref_dtype) if ref_dtype != jnp.float32 else x


def _fwd_tile(q_t, k_t, v_t, kpos, vl, m, l, acc, *, scale, prec, dt):
    """One (q-tile, k-tile) online-softmax update — the single copy of the
    forward tile math shared by the folded and lane-packed kernels.
    Returns (m_new, l_new, acc_new)."""
    s = jax.lax.dot_general(q_t, k_t, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32,
                            precision=prec) * scale          # [bq, bk]
    s = jnp.where(kpos < vl, s, _NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = acc * alpha + jnp.dot(_f32_for(dt, p), v_t,
                                    preferred_element_type=jnp.float32,
                                    precision=prec)
    return m_new, l_new, acc_new


def _finish_tile(m, l, acc, masked_sentinel):
    """(o_tile_f32, lse_row) from the final online-softmax state; fully
    masked rows get ``masked_sentinel`` (see _fwd_kernel docstring)."""
    o = acc / jnp.maximum(l, 1e-30)
    lse = jnp.where(m[:, 0] > _NEG_INF / 2,
                    m[:, 0] + jnp.log(jnp.maximum(l[:, 0], 1e-30)),
                    masked_sentinel)
    return o, lse


def _bwd_dq_tile(q_t, k_t, v_t, do_t, lse, delta, kpos, vl, *, scale, prec,
                 dt):
    """dq increment for one (q-tile, k-tile): ds @ k (the caller applies
    the final ``scale``). Shared by folded and packed dq kernels."""
    s = scale * jax.lax.dot_general(q_t, k_t, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32,
                                    precision=prec)
    s = jnp.where(kpos < vl, s, _NEG_INF)
    p = jnp.exp(s - lse)
    dp = jax.lax.dot_general(do_t, v_t, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32,
                             precision=prec)
    ds = p * (dp - delta)
    return jnp.dot(_f32_for(dt, ds), k_t, preferred_element_type=jnp.float32,
                   precision=prec)


def _bwd_dkv_tile(q_t, k_t, v_t, do_t, lse, delta, kpos, vl, *, scale, prec,
                  dt):
    """(dk_increment_unscaled, dv_increment) for one (k-tile, q-tile) —
    the caller applies ``scale`` to dk. Shared by folded and packed
    dk/dv kernels."""
    s = scale * jax.lax.dot_general(q_t, k_t, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32,
                                    precision=prec)
    s = jnp.where(kpos < vl, s, _NEG_INF)
    p = jnp.exp(s - lse)
    dv_inc = jax.lax.dot_general(_f32_for(dt, p), do_t,
                                 (((0,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32,
                                 precision=prec)
    dp = jax.lax.dot_general(do_t, v_t, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32,
                             precision=prec)
    ds = p * (dp - delta)
    dk_inc = jax.lax.dot_general(_f32_for(dt, ds), q_t,
                                 (((0,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32,
                                 precision=prec)
    return dk_inc, dv_inc


def _fwd_kernel(q_ref, k_ref, v_ref, valid_ref, o_ref, lse_ref, m_s, l_s,
                acc_s, *, block_k: int, scale: float, valid_len: int,
                n_k_blocks: int, masked_sentinel: float):
    """One (batch*head, q-block, k-block) program.

    The grid's innermost axis walks key blocks sequentially; (m, l, acc)
    live in VMEM scratch across those steps, so per-program VMEM is
    O(block_q·D + block_k·D) no matter how long the sequence is.

    ``valid_ref`` (SMEM scalar, optional) overrides the static
    ``valid_len`` — the ring-attention composition rotates key blocks, so
    the number of real keys in THIS call is only known at trace time.
    ``masked_sentinel`` is the lse written for fully-masked query rows:
    0.0 for the single-call path (padded q rows; keeps the backward's
    exp(s - lse) finite under zero cotangents) and -1e30 for the ring
    path, where a fully-padded key block's lse must weigh ZERO in the
    cross-block logsumexp combination.
    """
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_s[...] = jnp.full_like(m_s, _NEG_INF)
        l_s[...] = jnp.zeros_like(l_s)
        acc_s[...] = jnp.zeros_like(acc_s)

    dt = q_ref.dtype
    prec = _dot_precision(dt)
    bq = q_ref.shape[1]
    kpos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (bq, block_k), 1)
    vl = valid_len if valid_ref is None else valid_ref[0]
    m_new, l_new, acc_new = _fwd_tile(
        q_ref[0], k_ref[0], v_ref[0], kpos, vl,
        m_s[:, :1], l_s[:, :1], acc_s[...], scale=scale, prec=prec, dt=dt)
    acc_s[...] = acc_new
    m_s[...] = jnp.broadcast_to(m_new, m_s.shape)
    l_s[...] = jnp.broadcast_to(l_new, l_s.shape)

    @pl.when(ki == n_k_blocks - 1)
    def _finish():
        o, lse = _finish_tile(m_s[:, :1], l_s[:, :1], acc_s[...],
                              masked_sentinel)
        o_ref[0] = o.astype(o_ref.dtype)
        if lse_ref is not None:
            # logsumexp per query row, the only softmax residual the backward
            # needs. lse blocks are [1, 1, block_q]: row vectors must
            # keep a unit second-minor dim — Mosaic requires the last two
            # block dims to be (mult of 8, mult of 128) OR equal to the
            # array dims, which a [1, block_q] block of a 2D array violates
            # (surfaced on real TPU, round-3 smoke; interpret mode did
            # not enforce it).
            lse_ref[0, 0] = lse


def _pad_seq(t: jnp.ndarray, to: int) -> jnp.ndarray:
    pad = to - t.shape[1]
    if pad == 0:
        return t
    return jnp.pad(t, ((0, 0), (0, pad), (0, 0)))


def _fold(t, b, h, n, d, n_padded):  # [B,N,H,D] -> [B*H, N_padded, D]
    t = jnp.transpose(t, (0, 2, 1, 3)).reshape(b * h, n, d)
    return _pad_seq(t, n_padded)


def _unfold(t, b, h, n, d):  # [B*H, N_padded, D] -> [B,N,H,D]
    t = t[:, :n].reshape(b, h, n, d)
    return jnp.transpose(t, (0, 2, 1, 3))


def _padded_len(n: int, block_q: int, block_k: int) -> int:
    return max(-(-n // block_q) * block_q, -(-n // block_k) * block_k)


def _resolve_blocks(n: int, block_q, block_k):
    """Fill ``None`` block sizes from the sequence length.

    Heuristic: among square block sizes {128, 256, 512}, take the
    LARGEST whose padded length stays within 10% of the best achievable —
    padding is pure waste (masked FLOPs + HBM on every padded key), but
    per-program grid overhead is why the old fixed 128x128 default was
    ~2x slower than dense at N=2048 (16x16 inner programs per batch*head,
    perf/pallas_smoke.json) — so small padding buys big blocks, large
    padding never does. Examples: 197 -> 256 (one k pass), 577 -> 128
    (padded 640; larger blocks pad >= 768), 1025 -> 128 (1152),
    2048 -> 512, 2305 -> 512 (2560, 5% over the 128-block 2432 but 16x
    fewer programs). VMEM at 512x512 blocks: ~1 MB f32 score tile, 128 KB
    per f32 operand tile (512x64), two (512,128) f32 m/l scratches at
    256 KB each — comfortably inside v5e VMEM.

    Powers of two ONLY: 384 was in the palette until the one chip hang
    ever observed hit exactly the one config that auto-picked 384x384
    blocks (N=1025; perf/long_seq.json rows — 128/256/512 configs all
    ran, the 384 child hung 900s and its kill wedged the tunnel).
    Non-power-of-two Mosaic tilings are the suspect; the palette sticks
    to {128, 256, 512} — worst case vs 384 is bounded by the same 10%
    padding rule.
    """
    if block_q is None or block_k is None:
        sizes = (128, 256, 512)
        best = min(-(-n // b) * b for b in sizes)
        auto = max(b for b in sizes if -(-n // b) * b <= 1.1 * best)
        block_q = auto if block_q is None else block_q
        block_k = auto if block_k is None else block_k
    return block_q, block_k


@functools.partial(jax.jit, static_argnames=("block_q", "block_k",
                                             "interpret", "with_lse",
                                             "masked_sentinel",
                                             "static_valid"))
def _flash_fwd(q, k, v, block_q: int, block_k: int, interpret: bool,
               with_lse: bool = False, valid=None,
               masked_sentinel: float = 0.0, static_valid=None):
    """q,k,v: [B, N, H, D] -> out [B, N, H, D] (and logsumexp [B*H, N_padded]
    when with_lse — the backward residual). Single-device (or per-shard).

    ``valid``: optional [1] int32 device scalar overriding the static key
    validity count (the ring composition's rotating block ownership).
    ``static_valid``: compile-time override for callers whose inputs carry
    MORE padding than the block rounding (ulysses pads tokens to the seq
    axis before the kernel sees them)."""
    b, n, h, d = q.shape
    valid_len = n if static_valid is None else static_valid
    scale = 1.0 / (d ** 0.5)
    n_padded = _padded_len(n, block_q, block_k)

    qf = _fold(q, b, h, n, d, n_padded)
    kf = _fold(k, b, h, n, d, n_padded)
    vf = _fold(v, b, h, n, d, n_padded)
    n_k_blocks = n_padded // block_k
    grid = (b * h, n_padded // block_q, n_k_blocks)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda i, j, ki: (i, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), lambda i, j, ki: (i, ki, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), lambda i, j, ki: (i, ki, 0),
                     memory_space=pltpu.VMEM),
    ]
    operands = [qf, kf, vf]
    if valid is not None:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        operands.append(valid.astype(jnp.int32))
    out_shape = [jax.ShapeDtypeStruct((b * h, n_padded, d), q.dtype)]
    # The o/lse blocks revisit the same tile across the (sequential)
    # innermost k axis; writes land on the final k step.
    out_specs = [pl.BlockSpec((1, block_q, d), lambda i, j, ki: (i, j, 0),
                              memory_space=pltpu.VMEM)]
    if with_lse:
        # [B*H, 1, N_padded]: the unit middle dim makes the block's last two
        # dims (1, block_q) = (full array dim, lane multiple) — TPU-legal.
        out_shape.append(jax.ShapeDtypeStruct((b * h, 1, n_padded),
                                              jnp.float32))
        out_specs.append(pl.BlockSpec((1, 1, block_q),
                                      lambda i, j, ki: (i, 0, j),
                                      memory_space=pltpu.VMEM))

    def kernel(q_ref, k_ref, v_ref, *rest):
        valid_ref, rest = ((rest[0], rest[1:]) if valid is not None
                           else (None, rest))
        o_ref = rest[0]
        lse_ref = rest[1] if with_lse else None
        scratch = rest[2:] if with_lse else rest[1:]
        _fwd_kernel(q_ref, k_ref, v_ref, valid_ref, o_ref, lse_ref, *scratch,
                    block_k=block_k, scale=scale, valid_len=valid_len,
                    n_k_blocks=n_k_blocks, masked_sentinel=masked_sentinel)

    res = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[pltpu.VMEM((block_q, _LANES), jnp.float32),
                        pltpu.VMEM((block_q, _LANES), jnp.float32),
                        pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_compiler_params(),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=4 * b * h * n_padded * n_padded * d,
            bytes_accessed=3 * b * h * n_padded * d * q.dtype.itemsize,
            transcendentals=b * h * n_padded * n_padded),
    )(*operands)
    out = _unfold(res[0], b, h, n, d)
    if with_lse:
        return out, res[1]
    return out


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, valid_ref,
                   dq_ref, acc_s, *, block_k: int, scale: float,
                   valid_len: int, n_k_blocks: int):
    """One (bh, q-block, k-block) program: dq = scale * Σ_j ds_j @ k_j,
    accumulated in VMEM scratch across the sequential k axis."""
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_s[...] = jnp.zeros_like(acc_s)

    dt = q_ref.dtype
    prec = _dot_precision(dt)
    bq = q_ref.shape[1]
    kpos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (bq, block_k), 1)
    vl = valid_len if valid_ref is None else valid_ref[0]
    acc_s[...] += _bwd_dq_tile(
        q_ref[0], k_ref[0], v_ref[0], do_ref[0],
        lse_ref[0, 0][:, None], delta_ref[0, 0][:, None], kpos, vl,
        scale=scale, prec=prec, dt=dt)

    @pl.when(ki == n_k_blocks - 1)
    def _finish():
        dq_ref[0] = (scale * acc_s[...]).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    valid_ref, dk_ref, dv_ref, dk_s, dv_s, *, block_q: int,
                    scale: float, valid_len: int, n_q_blocks: int):
    """One (bh, k-block, q-block) program: dk/dv accumulated in VMEM scratch
    across the sequential q axis."""
    qi_idx = pl.program_id(2)

    @pl.when(qi_idx == 0)
    def _init():
        dk_s[...] = jnp.zeros_like(dk_s)
        dv_s[...] = jnp.zeros_like(dv_s)

    dt = q_ref.dtype
    prec = _dot_precision(dt)
    bk = k_ref.shape[1]
    j = pl.program_id(1)
    kpos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (1, bk), 1)  # [1, bk]
    vl = valid_len if valid_ref is None else valid_ref[0]
    dk_inc, dv_inc = _bwd_dkv_tile(
        q_ref[0], k_ref[0], v_ref[0], do_ref[0],
        lse_ref[0, 0][:, None], delta_ref[0, 0][:, None], kpos, vl,
        scale=scale, prec=prec, dt=dt)
    dv_s[...] += dv_inc
    dk_s[...] += scale * dk_inc

    @pl.when(qi_idx == n_q_blocks - 1)
    def _finish():
        dk_ref[0] = dk_s[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_s[...].astype(dv_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_q", "block_k",
                                             "interpret", "static_valid"))
def _flash_bwd(q, k, v, o, lse, do, block_q: int, block_k: int,
               interpret: bool, valid=None, static_valid=None):
    """Blockwise backward: (dq, dk, dv), each [B, N, H, D]. lse is the folded
    [B*H, 1, N_padded] logsumexp saved by the forward. ``valid`` /
    ``static_valid`` as in :func:`_flash_fwd`."""
    b, n, h, d = q.shape
    valid_len = n if static_valid is None else static_valid
    scale = 1.0 / (d ** 0.5)
    n_padded = _padded_len(n, block_q, block_k)

    qf, kf, vf, of, dof = (_fold(t, b, h, n, d, n_padded)
                           for t in (q, k, v, o, do))
    # delta_i = rowsum(do_i * o_i): the softmax-jacobian correction term.
    # Kept [B*H, 1, N_padded] like lse (see the TPU block-shape note in
    # _fwd_kernel).
    delta = jnp.sum(dof.astype(jnp.float32) * of.astype(jnp.float32),
                    axis=-1)[:, None, :]
    n_q_blocks = n_padded // block_q
    n_k_blocks = n_padded // block_k

    # Index maps: axis 1 is the block this program OWNS (q-block for dq,
    # k-block for dk/dv); axis 2 is the sequential reduction axis.
    own = lambda bsz: pl.BlockSpec((1, bsz, d), lambda i, j, r: (i, j, 0),
                                   memory_space=pltpu.VMEM)
    red = lambda bsz: pl.BlockSpec((1, bsz, d), lambda i, j, r: (i, r, 0),
                                   memory_space=pltpu.VMEM)
    row_own = lambda bsz: pl.BlockSpec((1, 1, bsz),
                                       lambda i, j, r: (i, 0, j),
                                       memory_space=pltpu.VMEM)
    row_red = lambda bsz: pl.BlockSpec((1, 1, bsz),
                                       lambda i, j, r: (i, 0, r),
                                       memory_space=pltpu.VMEM)
    operands = [qf, kf, vf, dof, lse, delta]
    extra_specs = []
    if valid is not None:
        operands.append(valid.astype(jnp.int32))
        extra_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))

    def _dq_kernel(*refs):
        if valid is not None:
            *ins, valid_ref, dq_ref, acc_s = refs
        else:
            *ins, dq_ref, acc_s = refs
            valid_ref = None
        _bwd_dq_kernel(*ins, valid_ref, dq_ref, acc_s, block_k=block_k,
                       scale=scale, valid_len=valid_len,
                       n_k_blocks=n_k_blocks)

    dq = pl.pallas_call(
        _dq_kernel,
        out_shape=jax.ShapeDtypeStruct((b * h, n_padded, d), q.dtype),
        grid=(b * h, n_q_blocks, n_k_blocks),
        in_specs=[own(block_q), red(block_k), red(block_k), own(block_q),
                  row_own(block_q), row_own(block_q)] + extra_specs,
        out_specs=own(block_q),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_compiler_params(),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=5 * b * h * n_padded * n_padded * d,
            bytes_accessed=4 * b * h * n_padded * d * q.dtype.itemsize,
            transcendentals=b * h * n_padded * n_padded),
    )(*operands)

    def _dkv_kernel(*refs):
        if valid is not None:
            *ins, valid_ref, dk_ref, dv_ref, dk_s, dv_s = refs
        else:
            *ins, dk_ref, dv_ref, dk_s, dv_s = refs
            valid_ref = None
        _bwd_dkv_kernel(*ins, valid_ref, dk_ref, dv_ref, dk_s, dv_s,
                        block_q=block_q, scale=scale, valid_len=valid_len,
                        n_q_blocks=n_q_blocks)

    dk, dv = pl.pallas_call(
        _dkv_kernel,
        out_shape=[jax.ShapeDtypeStruct((b * h, n_padded, d), k.dtype),
                   jax.ShapeDtypeStruct((b * h, n_padded, d), v.dtype)],
        grid=(b * h, n_k_blocks, n_q_blocks),
        in_specs=[red(block_q), own(block_k), own(block_k), red(block_q),
                  row_red(block_q), row_red(block_q)] + extra_specs,
        out_specs=[own(block_k), own(block_k)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=_compiler_params(),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=5 * b * h * n_padded * n_padded * d,
            bytes_accessed=4 * b * h * n_padded * d * q.dtype.itemsize,
            transcendentals=b * h * n_padded * n_padded),
    )(*operands)

    return (_unfold(dq, b, h, n, d), _unfold(dk, b, h, n, d),
            _unfold(dv, b, h, n, d))


# -- lane-packed variant ----------------------------------------------------
#
# The folded layout above reshapes [B, N, H, 64] to [B*H, N, 64]: a minor
# dim of 64 under the TPU's (8, 128) tiled layout pads every lane row to
# 128, so each q/k/v/o HBM array allocates 2x its bytes (seen directly in
# the N=4097 OOM dump, PERF_ANALYSIS.md §10f), and the fold itself is a
# transpose copy. The packed variant keeps kernel I/O in the model's
# NATURAL [B, N, H*64] layout — [B, N, H, D] -> [B, N, H*D] is a free
# contiguous reshape, the minor dim is 128-aligned (no tiling waste, no
# transpose), and the grid gains a head-pair axis: each program loads one
# 128-lane block holding TWO heads and runs both 64-wide online softmaxes.
# lse/delta keep the legacy [B*H, 1, N_padded] layout via leading-dim-2
# blocks (the (8,128) rule constrains only the last two block dims), so
# residual formats are identical across variants. Dispatched automatically
# for head_dim 64 + even head count (the whole ViT zoo except vit-tiny)
# from BOTH the public flash_attention custom-vjp and the ring
# composition's per-step calls (tpuic/parallel/ring_attention.py — the
# identical lse format is what makes its cross-block combination
# layout-agnostic); TPUIC_FLASH_PACKED=0 disables everywhere.


def _use_packed(h: int, d: int) -> bool:
    import os
    if os.environ.get("TPUIC_FLASH_PACKED", "1") == "0":
        return False
    return d == 64 and h % 2 == 0


def _select_kernels(h: int, d: int):
    """(fwd, bwd) implementation pair for these head dims — the ONE place
    the packed-vs-folded choice is made (public custom-vjp fwd/bwd and
    both ring_attention impls all call this; fwd and bwd must never come
    from different variants: their lse padding/layout contract is shared
    but their dispatch predicate must match)."""
    if _use_packed(h, d):
        return _flash_fwd_packed, _flash_bwd_packed
    return _flash_fwd, _flash_bwd


def _fwd_kernel_packed(q_ref, k_ref, v_ref, valid_ref, o_ref, lse_ref,
                       m0_s, l0_s, m1_s, l1_s, acc0_s, acc1_s, *,
                       block_k: int, d: int, scale: float, valid_len: int,
                       n_k_blocks: int, masked_sentinel: float):
    """One (batch, head-pair, q-block, k-block) program: two 64-wide heads
    share the 128-lane operand block; each keeps its own online-softmax
    state. Math per head is identical to :func:`_fwd_kernel`."""
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        for m_s, l_s, acc_s in ((m0_s, l0_s, acc0_s), (m1_s, l1_s, acc1_s)):
            m_s[...] = jnp.full_like(m_s, _NEG_INF)
            l_s[...] = jnp.zeros_like(l_s)
            acc_s[...] = jnp.zeros_like(acc_s)

    dt = q_ref.dtype
    prec = _dot_precision(dt)
    q2, k2, v2 = q_ref[0], k_ref[0], v_ref[0]     # [bq|bk, 2d]
    bq = q2.shape[0]
    kpos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (bq, block_k), 1)
    vl = valid_len if valid_ref is None else valid_ref[0]

    for h_i, (m_s, l_s, acc_s) in enumerate(((m0_s, l0_s, acc0_s),
                                             (m1_s, l1_s, acc1_s))):
        lo = h_i * d
        m_new, l_new, acc_new = _fwd_tile(
            q2[:, lo:lo + d], k2[:, lo:lo + d], v2[:, lo:lo + d], kpos, vl,
            m_s[:, :1], l_s[:, :1], acc_s[...], scale=scale, prec=prec,
            dt=dt)
        acc_s[...] = acc_new
        m_s[...] = jnp.broadcast_to(m_new, m_s.shape)
        l_s[...] = jnp.broadcast_to(l_new, l_s.shape)

    @pl.when(ki == n_k_blocks - 1)
    def _finish():
        halves = []
        for h_i, (m_s, l_s, acc_s) in enumerate(((m0_s, l0_s, acc0_s),
                                                 (m1_s, l1_s, acc1_s))):
            o, lse = _finish_tile(m_s[:, :1], l_s[:, :1], acc_s[...],
                                  masked_sentinel)
            halves.append(o)
            if lse_ref is not None:
                lse_ref[h_i, 0] = lse
        o_ref[0] = jnp.concatenate(halves, axis=-1).astype(o_ref.dtype)


def _pack(t, b, n, h, d, n_padded):  # [B,N,H,D] -> [B, N_padded, H*D]
    return _pad_seq(t.reshape(b, n, h * d), n_padded)


def _unpack(t, b, h, n, d):  # [B, N_padded, H*D] -> [B,N,H,D]
    return t[:, :n].reshape(b, n, h, d)


@functools.partial(jax.jit, static_argnames=("block_q", "block_k",
                                             "interpret", "with_lse",
                                             "masked_sentinel",
                                             "static_valid"))
def _flash_fwd_packed(q, k, v, block_q: int, block_k: int, interpret: bool,
                      with_lse: bool = False, valid=None,
                      masked_sentinel: float = 0.0, static_valid=None):
    """Packed-layout forward: same contract as :func:`_flash_fwd` (lse, when
    requested, in the identical [B*H, 1, N_padded] layout)."""
    b, n, h, d = q.shape
    hp = h // 2
    valid_len = n if static_valid is None else static_valid
    scale = 1.0 / (d ** 0.5)
    n_padded = _padded_len(n, block_q, block_k)

    qp = _pack(q, b, n, h, d, n_padded)
    kp = _pack(k, b, n, h, d, n_padded)
    vp = _pack(v, b, n, h, d, n_padded)
    n_k_blocks = n_padded // block_k
    grid = (b, hp, n_padded // block_q, n_k_blocks)
    pair = lambda bsz, row: pl.BlockSpec(
        (1, bsz, 2 * d), lambda bi, hi, j, ki, _r=row: (bi, (j, ki)[_r], hi),
        memory_space=pltpu.VMEM)
    in_specs = [pair(block_q, 0), pair(block_k, 1), pair(block_k, 1)]
    operands = [qp, kp, vp]
    if valid is not None:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        operands.append(valid.astype(jnp.int32))
    out_shape = [jax.ShapeDtypeStruct((b, n_padded, h * d), q.dtype)]
    out_specs = [pair(block_q, 0)]
    if with_lse:
        # Legacy lse layout; this program owns rows (b*h + 2*hi, +1) of
        # dim 0 — a leading block dim of 2, index b*hp + hi in block
        # units. Last two block dims stay (1, block_q): TPU-legal.
        out_shape.append(jax.ShapeDtypeStruct((b * h, 1, n_padded),
                                              jnp.float32))
        out_specs.append(pl.BlockSpec((2, 1, block_q),
                                      lambda bi, hi, j, ki: (bi * hp + hi,
                                                             0, j),
                                      memory_space=pltpu.VMEM))

    def kernel(q_ref, k_ref, v_ref, *rest):
        valid_ref, rest = ((rest[0], rest[1:]) if valid is not None
                           else (None, rest))
        o_ref = rest[0]
        lse_ref = rest[1] if with_lse else None
        scratch = rest[2:] if with_lse else rest[1:]
        _fwd_kernel_packed(q_ref, k_ref, v_ref, valid_ref, o_ref, lse_ref,
                           *scratch, block_k=block_k, d=d, scale=scale,
                           valid_len=valid_len, n_k_blocks=n_k_blocks,
                           masked_sentinel=masked_sentinel)

    res = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[pltpu.VMEM((block_q, _LANES), jnp.float32),
                        pltpu.VMEM((block_q, _LANES), jnp.float32),
                        pltpu.VMEM((block_q, _LANES), jnp.float32),
                        pltpu.VMEM((block_q, _LANES), jnp.float32),
                        pltpu.VMEM((block_q, d), jnp.float32),
                        pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_compiler_params_4d(),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=4 * b * h * n_padded * n_padded * d,
            bytes_accessed=3 * b * h * n_padded * d * q.dtype.itemsize,
            transcendentals=b * h * n_padded * n_padded),
    )(*operands)
    out = _unpack(res[0], b, h, n, d)
    if with_lse:
        return out, res[1]
    return out


def _bwd_dq_kernel_packed(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          valid_ref, dq_ref, acc0_s, acc1_s, *, block_k: int,
                          d: int, scale: float, valid_len: int,
                          n_k_blocks: int):
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        acc0_s[...] = jnp.zeros_like(acc0_s)
        acc1_s[...] = jnp.zeros_like(acc1_s)

    dt = q_ref.dtype
    prec = _dot_precision(dt)
    q2, k2, v2, do2 = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
    bq = q2.shape[0]
    kpos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (bq, block_k), 1)
    vl = valid_len if valid_ref is None else valid_ref[0]

    for h_i, acc_s in enumerate((acc0_s, acc1_s)):
        lo = h_i * d
        acc_s[...] += _bwd_dq_tile(
            q2[:, lo:lo + d], k2[:, lo:lo + d], v2[:, lo:lo + d],
            do2[:, lo:lo + d], lse_ref[h_i, 0][:, None],
            delta_ref[h_i, 0][:, None], kpos, vl, scale=scale, prec=prec,
            dt=dt)

    @pl.when(ki == n_k_blocks - 1)
    def _finish():
        dq_ref[0] = jnp.concatenate(
            [scale * acc0_s[...], scale * acc1_s[...]],
            axis=-1).astype(dq_ref.dtype)


def _bwd_dkv_kernel_packed(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                           valid_ref, dkv_ref, dk0_s, dv0_s, dk1_s, dv1_s,
                           *, block_q: int, d: int, scale: float,
                           valid_len: int, n_q_blocks: int):
    qi_idx = pl.program_id(3)

    @pl.when(qi_idx == 0)
    def _init():
        for s_ in (dk0_s, dv0_s, dk1_s, dv1_s):
            s_[...] = jnp.zeros_like(s_)

    dt = q_ref.dtype
    prec = _dot_precision(dt)
    q2, k2, v2, do2 = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
    bk = k2.shape[0]
    j = pl.program_id(2)
    kpos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (1, bk), 1)
    vl = valid_len if valid_ref is None else valid_ref[0]

    for h_i, (dk_s, dv_s) in enumerate(((dk0_s, dv0_s), (dk1_s, dv1_s))):
        lo = h_i * d
        dk_inc, dv_inc = _bwd_dkv_tile(
            q2[:, lo:lo + d], k2[:, lo:lo + d], v2[:, lo:lo + d],
            do2[:, lo:lo + d], lse_ref[h_i, 0][:, None],
            delta_ref[h_i, 0][:, None], kpos, vl, scale=scale, prec=prec,
            dt=dt)
        dv_s[...] += dv_inc
        dk_s[...] += scale * dk_inc

    @pl.when(qi_idx == n_q_blocks - 1)
    def _finish():
        # dk and dv ride ONE [., bk, 4d] output (dk pair | dv pair):
        # separate outputs would be fine too, this just keeps the store
        # count down.
        dkv_ref[0] = jnp.concatenate(
            [dk0_s[...], dk1_s[...], dv0_s[...], dv1_s[...]],
            axis=-1).astype(dkv_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_q", "block_k",
                                             "interpret", "static_valid"))
def _flash_bwd_packed(q, k, v, o, lse, do, block_q: int, block_k: int,
                      interpret: bool, valid=None, static_valid=None):
    """Packed-layout backward: same contract as :func:`_flash_bwd`."""
    b, n, h, d = q.shape
    hp = h // 2
    valid_len = n if static_valid is None else static_valid
    scale = 1.0 / (d ** 0.5)
    n_padded = _padded_len(n, block_q, block_k)

    qp, kp, vp, dop = (_pack(t, b, n, h, d, n_padded)
                       for t in (q, k, v, do))
    # delta in the legacy [B*H, 1, N_padded] layout, computed from the
    # unfolded tensors (no folded copies).
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    delta = _pad_seq(jnp.transpose(delta, (0, 2, 1)).reshape(b * h, n, 1),
                     n_padded)[..., 0][:, None, :]
    n_q_blocks = n_padded // block_q
    n_k_blocks = n_padded // block_k

    pair = lambda bsz, row: pl.BlockSpec(
        (1, bsz, 2 * d), lambda bi, hi, j, r, _r=row: (bi, (j, r)[_r], hi),
        memory_space=pltpu.VMEM)
    lse_own = pl.BlockSpec((2, 1, block_q),
                           lambda bi, hi, j, r: (bi * hp + hi, 0, j),
                           memory_space=pltpu.VMEM)
    lse_red = pl.BlockSpec((2, 1, block_q),
                           lambda bi, hi, j, r: (bi * hp + hi, 0, r),
                           memory_space=pltpu.VMEM)
    operands = [qp, kp, vp, dop, lse, delta]
    extra_specs = []
    if valid is not None:
        operands.append(valid.astype(jnp.int32))
        extra_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))

    def _dq_kernel(*refs):
        if valid is not None:
            *ins, valid_ref, dq_ref, acc0, acc1 = refs
        else:
            *ins, dq_ref, acc0, acc1 = refs
            valid_ref = None
        _bwd_dq_kernel_packed(*ins, valid_ref, dq_ref, acc0, acc1,
                              block_k=block_k, d=d, scale=scale,
                              valid_len=valid_len, n_k_blocks=n_k_blocks)

    dq = pl.pallas_call(
        _dq_kernel,
        out_shape=jax.ShapeDtypeStruct((b, n_padded, h * d), q.dtype),
        grid=(b, hp, n_q_blocks, n_k_blocks),
        in_specs=[pair(block_q, 0), pair(block_k, 1), pair(block_k, 1),
                  pair(block_q, 0), lse_own, lse_own] + extra_specs,
        out_specs=pair(block_q, 0),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32),
                        pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_compiler_params_4d(),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=5 * b * h * n_padded * n_padded * d,
            bytes_accessed=4 * b * h * n_padded * d * q.dtype.itemsize,
            transcendentals=b * h * n_padded * n_padded),
    )(*operands)

    def _dkv_kernel(*refs):
        if valid is not None:
            *ins, valid_ref, dkv_ref, dk0, dv0, dk1, dv1 = refs
        else:
            *ins, dkv_ref, dk0, dv0, dk1, dv1 = refs
            valid_ref = None
        _bwd_dkv_kernel_packed(*ins, valid_ref, dkv_ref, dk0, dv0, dk1, dv1,
                               block_q=block_q, d=d, scale=scale,
                               valid_len=valid_len, n_q_blocks=n_q_blocks)

    dkv_spec = pl.BlockSpec((1, block_k, 4 * d),
                            lambda bi, hi, j, r: (bi, j, hi),
                            memory_space=pltpu.VMEM)
    # The single dkv output must not quantize EITHER gradient: use the
    # widest of the two operand dtypes and cast the halves back after the
    # unscramble (mixed dtypes are rare; same-dtype calls pay nothing).
    dkv_dtype = jnp.result_type(k.dtype, v.dtype)
    dkv = pl.pallas_call(
        _dkv_kernel,
        out_shape=jax.ShapeDtypeStruct((b, n_padded, 2 * h * d), dkv_dtype),
        grid=(b, hp, n_k_blocks, n_q_blocks),
        in_specs=[pair(block_q, 1), pair(block_k, 0), pair(block_k, 0),
                  pair(block_q, 1), lse_red, lse_red] + extra_specs,
        out_specs=dkv_spec,
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=_compiler_params_4d(),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=5 * b * h * n_padded * n_padded * d,
            bytes_accessed=4 * b * h * n_padded * d * q.dtype.itemsize,
            transcendentals=b * h * n_padded * n_padded),
    )(*operands)
    # dkv: [B, N_padded, 2*H*D] laid out as per-pair [dk0|dk1|dv0|dv1].
    # Halves come back in their own operand dtypes (custom_vjp requires
    # cotangent dtype == primal dtype); dkv_dtype above guarantees the
    # cast never LOSES precision relative to the folded variant's
    # separate out_shapes.
    dkv = dkv[:, :n].reshape(b, n, hp, 4, d)
    dk = dkv[:, :, :, :2].reshape(b, n, h, d).astype(k.dtype)
    dv = dkv[:, :, :, 2:].reshape(b, n, h, d).astype(v.dtype)
    return _unpack(dq, b, h, n, d), dk, dv


def _shard_batch(mesh: Optional[Mesh], b: int) -> bool:
    """True when the kernel should run under shard_map over the data axis."""
    if mesh is None or "data" not in mesh.axis_names:
        return False
    n_data = mesh.shape["data"]
    return n_data > 1 and b % n_data == 0


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None,
                    mesh: Optional[Mesh] = None,
                    valid_len: Optional[int] = None):
    """Softmax attention, [B, N, H, D] in/out, no causal mask (ViT is
    bidirectional). ``block_q``/``block_k`` default to a length-adaptive
    size (``_resolve_blocks``); ``interpret=None`` auto-selects interpret
    mode off-TPU; ``mesh`` keeps the kernel batch-parallel under a sharded
    jit (see module docstring); ``valid_len`` masks keys beyond a static
    count when the inputs carry caller-side padding (ulysses)."""
    block_q, block_k = _resolve_blocks(q.shape[1], block_q, block_k)
    fwd, _ = _select_kernels(q.shape[2], q.shape[3])
    # Scope tag for the device-time waterfall (telemetry/profile.py):
    # the Pallas custom-call rolls up under 'flash_attention' instead of
    # an anonymous custom-call.
    with jax.named_scope("flash_attention"):
        return _batch_parallel(
            lambda interp, *ops: fwd(*ops, block_q, block_k, interp,
                                     static_valid=valid_len),
            mesh, interpret, 1, q, k, v)


def _batch_parallel(fn, mesh, interpret, n_out, *operands):
    """Run ``fn(interpret, *operands)`` per batch shard under shard_map when
    the mesh shards the batch, else directly. Pallas calls are opaque to
    GSPMD, so without this a sharded jit would all-gather the operands onto
    every device. check_vma=False: pallas out_shapes carry no vma
    annotations. All operands/outputs are batch-major."""
    if interpret is None:
        from tpuic.kernels import default_interpret
        interpret = default_interpret()
    if not _shard_batch(mesh, operands[0].shape[0]):
        return fn(interpret, *operands)
    spec = P("data")
    return jax.shard_map(
        lambda *ops: fn(interpret, *ops),
        mesh=mesh, in_specs=(spec,) * len(operands),
        out_specs=spec if n_out == 1 else (spec,) * n_out,
        check_vma=False,
    )(*operands)


def _vjp_fwd(q, k, v, block_q, block_k, interpret, mesh, valid_len=None):
    block_q, block_k = _resolve_blocks(q.shape[1], block_q, block_k)
    fwd, _ = _select_kernels(q.shape[2], q.shape[3])
    with jax.named_scope("flash_attention"):
        out, lse = _batch_parallel(
            lambda interp, *ops: fwd(*ops, block_q, block_k, interp,
                                     with_lse=True,
                                     static_valid=valid_len),
            mesh, interpret, 2, q, k, v)
    return out, (q, k, v, out, lse)


def _vjp_bwd(block_q, block_k, interpret, mesh, valid_len, res, g):
    q, k, v, out, lse = res
    # Same resolution as the forward: lse was padded with these blocks.
    block_q, block_k = _resolve_blocks(q.shape[1], block_q, block_k)
    _, bwd = _select_kernels(q.shape[2], q.shape[3])
    with jax.named_scope("flash_attention_bwd"):
        return _batch_parallel(
            lambda interp, *ops: bwd(*ops, block_q, block_k, interp,
                                     static_valid=valid_len),
            mesh, interpret, 3, q, k, v, out, lse, g)


flash_attention.defvjp(_vjp_fwd, _vjp_bwd)
