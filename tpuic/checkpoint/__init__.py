from tpuic.checkpoint.manager import CheckpointManager, lenient_restore  # noqa: F401
