from tpuic.checkpoint.loading import load_inference_variables  # noqa: F401
from tpuic.checkpoint.manager import CheckpointManager, lenient_restore  # noqa: F401
from tpuic.checkpoint.torch_convert import (  # noqa: F401
    convert_reference_checkpoint, convert_resnet, load_reference_checkpoint)
