"""Checkpointing: best/latest tracks, lenient restore, true resume.

Capability parity with reference train.py:131-188, redesigned for multi-host
TPU via Orbax (every host participates in saving its shards; the reference is
rank-0 ``torch.save`` of a replicated state_dict):

- **Tracks**: ``{ckpt_dir}/{name}/best`` saved whenever val accuracy improves
  (train.py:173-180) and ``{ckpt_dir}/{name}/latest`` every ``save_period``
  epochs (``epoch % period == 0``, matching train.py:183-188).
- **Payload**: params, batch_stats, opt_state, epoch, best_score — the
  reference saves {'epoch','best_score','state_dict'} (train.py:177-179) and
  silently loses optimizer state across restarts; here it round-trips.
- **Sharded + async saves**: state arrays are handed to Orbax as they live on
  device — under FSDP each host writes only its addressable shards, with no
  full-state host gather — and the write happens on a background thread
  (AsyncCheckpointer) so training continues during I/O.
- **Lenient restore** (``lenient_restore``): key-intersection copy exactly like
  train.py:143-148 — only leaves present in BOTH trees with matching shapes
  are taken from the checkpoint — so architecture drift degrades gracefully.
- **True resume**: the reference restores ``start_epoch`` but restarts its loop
  at 0 anyway (train.py:149-150 vs 161 — latent bug); here the trainer resumes
  from whichever track (latest/best) carries the highest epoch, so a crash
  long after the last val improvement doesn't replay dozens of epochs.
- **Atomic commit + integrity ladder** (docs/robustness.md): every save is
  staged to ``{track}.new`` and only *rotated* into ``{track}`` (previous
  save kept as ``{track}.prev``) after the async write finishes — a SIGKILL
  mid-write can never leave a half-checkpoint as ``latest``. At commit a
  manifest sidecar (``{track}.manifest.json``: per-file sizes + CRC32s,
  step/epoch) records what was written; ``restore_into`` verifies it and on
  any mismatch walks the fallback ladder newest -> other track -> their
  ``.prev`` rungs, logging which rung was taken (``last_restore_rung``).
  Checkpoints without a manifest (pre-ladder) restore unverified, as before.
"""

from __future__ import annotations

import json
import os
import shutil
import signal
import threading
import time
import zlib
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from tpuic.metrics.logging import host0_print
from tpuic.runtime import faults as _faults
from tpuic.telemetry.events import publish as _tm_publish


def _flatten(tree, prefix=()) -> Dict[Tuple, Any]:
    out = {}
    if hasattr(tree, "items"):  # dict and flax FrozenDict
        for k, v in tree.items():
            out.update(_flatten(v, prefix + (k,)))
    else:
        out[prefix] = tree
    return out


def _unflatten(flat: Dict[Tuple, Any]):
    root: Dict = {}
    for path, v in flat.items():
        d = root
        for k in path[:-1]:
            d = d.setdefault(k, {})
        d[path[-1]] = v
    return root


def lenient_restore(current: Dict, restored: Dict) -> Tuple[Dict, int, int]:
    """Key-intersection merge (reference train.py:143-148).

    Returns (merged tree, n_loaded, n_total_current). A leaf is taken from
    ``restored`` iff its path exists in both trees and shapes match.

    Leaves wrapped in flax AxisMetadata boxes (``with_logical_partitioning``
    kernels — the ViT/TP models) are compared and replaced by their
    ``.value`` with the box preserved, so sharding metadata survives a
    torch-init or cross-architecture merge.
    """
    cur = _flatten(current)
    res = _flatten(restored)
    loaded = 0
    merged = {}
    for path, leaf in cur.items():
        r = res.get(path)
        target = getattr(leaf, "value", leaf)   # unbox AxisMetadata
        rv = getattr(r, "value", r)
        if rv is not None and getattr(rv, "shape", None) == getattr(
                target, "shape", None):
            new = (np.asarray(rv).astype(target.dtype)
                   if hasattr(target, "dtype") else rv)
            merged[path] = (leaf.replace_boxed(new)
                            if hasattr(leaf, "replace_boxed") else new)
            loaded += 1
        else:
            merged[path] = leaf
    return _unflatten(merged), loaded, len(cur)


# Resume metadata introduced in round 4, enumerated ONCE: _payload writes
# these keys, _abstract_payload's legacy template deletes exactly these,
# and _read_resume_meta reads the geometry subset — a single list keeps
# the three sites (and the on-disk layout contract) from drifting.
RESUME_META_KEYS = ("step_in_epoch", "global_batch", "data_seed", "data_len")
GEOMETRY_META_KEYS = ("global_batch", "data_seed", "data_len")

_MANIFEST_VERSION = 1


def _dir_manifest(path: str) -> Dict[str, Any]:
    """{relpath: [size, crc32]} for every file under ``path``, in sorted
    order — the content fingerprint the integrity ladder verifies. CRC32
    (not a cryptographic hash) on purpose: the threat model is bit-rot and
    torn writes, not adversaries, and checkpoints are hundreds of MB."""
    files: Dict[str, Any] = {}
    for dirpath, dirnames, filenames in os.walk(path):
        dirnames.sort()
        for fn in sorted(filenames):
            fp = os.path.join(dirpath, fn)
            rel = os.path.relpath(fp, path).replace(os.sep, "/")
            crc = 0
            size = 0
            with open(fp, "rb") as f:
                while True:
                    chunk = f.read(1 << 20)
                    if not chunk:
                        break
                    crc = zlib.crc32(chunk, crc)
                    size += len(chunk)
            files[rel] = [size, crc]
    return files


def _atomic_json(path: str, obj) -> None:
    """tmp + rename so readers never see a half-written sidecar."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
    os.replace(tmp, path)


def _remove(path: str) -> None:
    """Remove a file or directory tree, tolerating absence."""
    if os.path.isdir(path):
        shutil.rmtree(path, ignore_errors=True)
    else:
        try:
            os.remove(path)
        except OSError:
            pass


class CheckpointManager:
    """best/latest checkpoint tracks under ``{ckpt_dir}/{name}``."""

    def __init__(self, ckpt_dir: str, name: str, save_period: int = 5,
                 async_commit: bool = False) -> None:
        self.root = os.path.abspath(os.path.join(ckpt_dir, name))
        self.save_period = save_period
        # Deferred commits (RunConfig.async_checkpoint): _save spawns a
        # background thread that drains the Orbax write and runs the
        # SAME stage -> manifest -> rotate commit, so the train loop's
        # goodput 'checkpoint' bucket sees ~0 blocking seconds. The
        # contract is unchanged — a commit can only become visible
        # EARLIER, never differently: every reader still enters through
        # wait(), which joins the thread (re-raising anything it hit)
        # before looking at the tracks. Single-process only; multi-host
        # commits are collective (the _commit_barrier) and stay
        # synchronous.
        self._async_commit = bool(async_commit)
        self._commit_thread: Optional[threading.Thread] = None
        self._commit_error: Optional[BaseException] = None
        # Async: save() hands Orbax the (possibly sharded) on-device arrays
        # and returns; serialization + write happen on a background thread.
        try:
            self._ckptr = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
        except Exception:  # pragma: no cover — very old orbax
            self._ckptr = ocp.PyTreeCheckpointer()
        # The one save whose async write may still be in flight: its
        # (track, sidecar metadata). wait() commits it — manifest, then
        # the .new -> track rotation — so a reader that waited always sees
        # either the previous complete checkpoint or the new complete one.
        self._pending: Optional[Dict[str, Any]] = None
        if jax.process_index() == 0:
            os.makedirs(self.root, exist_ok=True)

    # -- save ---------------------------------------------------------------
    def _payload(self, state, epoch: int, best_score: float,
                 gather: bool = False, step_in_epoch: int = -1,
                 global_batch: int = -1, data_seed: int = -1,
                 data_len: int = -1):
        """Checkpoint pytree. ``gather=False`` keeps arrays wherever they
        live (sharded jax.Arrays stay sharded — each host saves only its
        addressable shards); ``gather=True`` materializes numpy on host
        (used as a restore template)."""
        if gather:
            to_host = lambda t: jax.tree.map(np.asarray, jax.device_get(t))  # noqa: E731
        else:
            to_host = lambda t: t  # noqa: E731
        # NOTE: the "meta" key set below is FROZEN. Orbax's fast-path
        # restore requires an exact structure match, and restore_into
        # enumerates historical layouts as whole templates (current +
        # pre-step_in_epoch legacy) — every new key here would strand
        # today's checkpoints on the host-gather lenient path. Add future
        # run metadata to the sidecar JSON (_save), which has no
        # structure-match constraint, not here.
        payload = {
            "params": to_host(state.params),
            "batch_stats": to_host(state.batch_stats),
            "opt_state": to_host(state.opt_state),
            "meta": {"epoch": np.int64(epoch),
                     "best_score": np.float64(best_score),
                     # >= 0: completed steps of epoch ``epoch`` at a
                     # preemption flush; resume continues that epoch at
                     # this step (== steps_per_epoch: training done, only
                     # val pending). -1: normal end-of-epoch save.
                     "step_in_epoch": np.int64(step_in_epoch),
                     # Loader geometry at a step_in_epoch flush: the
                     # epoch permutation is keyed by (seed, n_samples) and
                     # sliced by global_batch, so a resume differing in ANY
                     # of the three cannot reuse the step offset (it would
                     # skip the wrong samples) and falls back to replaying
                     # the epoch. -1: not recorded.
                     "global_batch": np.int64(global_batch),
                     "data_seed": np.int64(data_seed),
                     "data_len": np.int64(data_len),
                     "step": np.asarray(jax.device_get(state.step))},
        }
        if getattr(state, "ema_params", None) is not None:
            payload["ema_params"] = to_host(state.ema_params)
        return payload

    def wait(self) -> None:
        """Block until any in-flight async save has COMMITTED: the write
        finishes, the manifest is computed over the staged bytes, and the
        staged dir is rotated into its track (previous save -> .prev).

        Every reader (newest_track / restore_into) and every new save goes
        through here first, so a checkpoint becomes visible atomically or
        not at all. ``faults`` point ``ckpt_kill`` fires between the
        finished write and the rotation — the SIGKILL-mid-save simulation:
        the committed track must be untouched by the aborted save.

        With deferred commits, wait() first joins the background commit
        thread and re-raises anything it hit (an injected ``ckpt_kill``
        included — the crash window just moves to the next sync point);
        only one thread ever touches ``_pending``/``_ckptr`` because every
        entry point joins before proceeding."""
        t0 = time.perf_counter()
        thread, self._commit_thread = self._commit_thread, None
        if thread is not None:
            thread.join()
            err, self._commit_error = self._commit_error, None
            if err is not None:
                raise err
        self._drain_and_commit(t0, blocking=True)

    def _drain_and_commit(self, t0: float, blocking: bool = True) -> None:
        """Drain the in-flight Orbax write, then run the atomic commit
        (manifest over staged bytes -> rotation -> sidecar -> event).
        ``blocking=False`` marks a deferred commit running concurrently
        with compute — the goodput tracker then books its span outside
        the wall-clock 'checkpoint' bucket."""
        if hasattr(self._ckptr, "wait_until_finished"):
            self._ckptr.wait_until_finished()
        pending, self._pending = self._pending, None
        if pending is None:
            return
        if jax.process_index() != 0:
            # Single-writer rotation: every host wrote its shards above,
            # host 0 owns the filesystem commit (same single-writer rule
            # as the sidecars; multi-host runs share the checkpoint FS).
            # Hold at the commit barrier so no host reads the track
            # before host 0's rotation lands.
            self._commit_barrier()
            return
        track = pending["track"]
        path = os.path.join(self.root, track)
        new = path + ".new"
        if not os.path.isdir(new):
            # Staged save vanished (failed write) — nothing to commit, but
            # the other hosts still entered the barrier for this save.
            self._commit_barrier()
            return
        manifest = {"version": _MANIFEST_VERSION,
                    "epoch": pending["epoch"],
                    "step_in_epoch": pending["step_in_epoch"],
                    "step": pending["step"],
                    "files": _dir_manifest(new)}
        _atomic_json(new + ".manifest.json", manifest)
        if _faults.fire("ckpt_kill"):
            raise _faults.InjectedFault(
                f"injected kill before committing checkpoint '{track}'")
        # Rotation: previous committed save survives as {track}.prev (the
        # ladder's last rung). Plain renames — no data copies. The brief
        # window between the two renames can leave only .prev on disk; the
        # restore ladder includes .prev rungs precisely so that window is
        # recoverable too.
        prev = path + ".prev"
        for suffix in ("", ".manifest.json", ".meta.json"):
            _remove(prev + suffix)
        if os.path.isdir(path):
            os.rename(path, prev)
            for suffix in (".manifest.json", ".meta.json"):
                if os.path.exists(path + suffix):
                    os.replace(path + suffix, prev + suffix)
        os.rename(new, path)
        os.replace(new + ".manifest.json", path + ".manifest.json")
        # Resume sidecar: lets resume pick the newest track without a full
        # restore of both.
        _atomic_json(path + ".meta.json",
                     {k: pending[k] for k in
                      ("epoch", "best_score") + RESUME_META_KEYS})
        # Telemetry (docs/observability.md): the committed checkpoint as
        # a typed event — the goodput tracker books the blocking commit
        # span (async-write drain + manifest + rotation) against the
        # 'checkpoint' bucket.
        _tm_publish("checkpoint_commit", track=track,
                    epoch=int(pending["epoch"]), step=int(pending["step"]),
                    phase="commit", blocking=bool(blocking),
                    duration_s=round(time.perf_counter() - t0, 3))
        self._commit_barrier()

    @staticmethod
    def _commit_barrier() -> None:
        """Cross-host rendezvous after a commit rotation: wait() is
        collective (every host calls it once per save, same order — the
        discipline Orbax's own async commit already demands), so pairing a
        barrier on the pending-save path keeps hosts from reading a track
        host 0 is still renaming. Free on single-process runs. NOTE: a
        host-0 crash mid-rotation (or an armed 'ckpt_kill') strands the
        other hosts here until the scheduler reaps them — the same failure
        semantics as a host dying inside any other collective."""
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("tpuic_ckpt_commit")

    def _save(self, track: str, state, epoch: int, best_score: float,
              step_in_epoch: int = -1, global_batch: int = -1,
              data_seed: int = -1, data_len: int = -1) -> None:
        self.wait()  # one in-flight save at a time; also orders best/latest
        # Stage to {track}.new; wait() rotates it into {track} on commit.
        t0 = time.perf_counter()
        payload = self._payload(state, epoch, best_score,
                                step_in_epoch=step_in_epoch,
                                global_batch=global_batch,
                                data_seed=data_seed, data_len=data_len)
        self._ckptr.save(os.path.join(self.root, f"{track}.new"), payload,
                         force=True)
        # The staging span (host gather + async-save handoff) is
        # checkpoint cost too; the background write itself is free wall
        # time and is charged at the commit that drains it.
        _tm_publish("checkpoint_commit", track=track, epoch=int(epoch),
                    phase="stage",
                    duration_s=round(time.perf_counter() - t0, 3))
        self._pending = {"track": track, "epoch": int(epoch),
                         "best_score": float(best_score),
                         "step_in_epoch": int(step_in_epoch),
                         "global_batch": int(global_batch),
                         "data_seed": int(data_seed),
                         "data_len": int(data_len),
                         # reuse _payload's device_get — one sync per save
                         "step": int(payload["meta"]["step"])}
        if self._async_commit and jax.process_count() == 1:
            # Deferred commit: drain + manifest + rotation run concurrently
            # with the next train steps instead of stalling the loop at the
            # next natural wait(). A rank can only ever advertise a commit
            # EARLIER than the blocking path would have — never a rung the
            # ladder can't restore: until the rotation lands the track is
            # byte-identical to the previous committed save, and gang
            # committed_steps / fleet_resume_step read track manifests,
            # which this thread writes last-but-one before the renames.
            t1 = time.perf_counter()

            def _bg() -> None:
                try:
                    self._drain_and_commit(t1, blocking=False)
                except BaseException as e:  # re-raised at the next wait()
                    self._commit_error = e

            self._commit_thread = threading.Thread(
                target=_bg, name="tpuic-ckpt-commit", daemon=True)
            self._commit_thread.start()

    def save_best(self, state, epoch: int, best_score: float) -> None:
        """Reference train.py:173-180 — on val-accuracy improvement."""
        self._save("best", state, epoch, best_score)
        host0_print(f"[ckpt] best -> {self.root}/best "
                    f"(epoch {epoch}, score {best_score:.4f})")

    def maybe_save_latest(self, state, epoch: int, best_score: float) -> None:
        """Reference train.py:183-188 — every ``save_period`` epochs
        (``epoch % period == 0``, so epoch 0 saves, like the reference)."""
        if epoch % self.save_period == 0:
            self.save_latest(state, epoch, best_score)

    def save_latest(self, state, epoch: int, best_score: float,
                    step_in_epoch: int = -1, global_batch: int = -1,
                    data_seed: int = -1, data_len: int = -1) -> None:
        """Unconditional ``latest`` save (preemption flush; period ignored).

        ``step_in_epoch >= 0`` marks a PARTIAL epoch: ``epoch`` has that
        many completed steps and resume continues it step-exactly (the
        epoch permutation and every per-step/per-sample RNG stream are
        deterministic in (seed, epoch, index) / optimizer step, so the
        continued run is bitwise the uninterrupted one)."""
        self._save("latest", state, epoch, best_score,
                   step_in_epoch=step_in_epoch, global_batch=global_batch,
                   data_seed=data_seed, data_len=data_len)
        at = (f"epoch {epoch}" if step_in_epoch < 0
              else f"epoch {epoch}, step {step_in_epoch}")
        host0_print(f"[ckpt] latest -> {self.root}/latest ({at})")

    # -- restore ------------------------------------------------------------
    def _track_epoch(self, track: str) -> Optional[int]:
        """Saved epoch of a track, or None when absent/unreadable."""
        if not os.path.isdir(os.path.join(self.root, track)):
            return None
        try:
            with open(os.path.join(self.root, f"{track}.meta.json")) as f:
                return int(json.load(f)["epoch"])
        except (OSError, ValueError, KeyError):
            return -1  # present but no sidecar — restorable, epoch unknown

    def newest_track(self) -> Optional[str]:
        """The restorable track with the highest saved epoch.

        ``latest`` wins ties — a crash at epoch 90 with ``best`` from epoch
        40 resumes at 90 instead of replaying 50 epochs (the reference
        restores only ``best_model``, train.py:136).
        """
        self.wait()  # async saves finalize their directory on commit
        candidates = [(e, t) for t in ("latest", "best")
                      if (e := self._track_epoch(t)) is not None]
        if not candidates:
            return None
        return max(candidates, key=lambda p: p[0])[1]

    def _abstract_payload(self, state, legacy_meta: bool = False):
        """(template, restore_args) for a restore directly into the live
        state's shardings: every array leaf becomes a ShapeDtypeStruct whose
        sharding is the leaf's own, so Orbax hands back sharded jax.Arrays
        without ever materializing the full state on one host (FSDP-scale
        safe — VERDICT r2 weak #5).

        ``legacy_meta`` drops the ``step_in_epoch`` meta key so checkpoints
        written before that key existed still take this fast path (Orbax's
        PyTreeRestore requires the template structure to match the stored
        tree exactly)."""
        def abstract(leaf):
            if isinstance(leaf, jax.Array):
                return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype,
                                            sharding=leaf.sharding)
            return leaf  # host scalars in meta
        def args(leaf):
            if isinstance(leaf, jax.Array):
                return ocp.ArrayRestoreArgs(sharding=leaf.sharding,
                                            global_shape=leaf.shape,
                                            dtype=leaf.dtype)
            return ocp.RestoreArgs()
        payload = self._payload(state, 0, 0.0)
        if legacy_meta:
            for k in RESUME_META_KEYS:
                del payload["meta"][k]
        return (jax.tree.map(abstract, payload), jax.tree.map(args, payload))

    def _read_resume_meta(self, meta):
        """Parse the resume-relevant scalars out of a restored meta tree
        and publish them on the manager (single reader for BOTH restore
        branches, so they can never desynchronize). Returns
        (epoch, best_score, step_in_epoch)."""
        epoch = int(meta.get("epoch", 0))
        best = float(meta.get("best_score", 0.0))
        sie = int(meta.get("step_in_epoch", -1))
        self.last_restore_meta = (epoch, sie)
        self.last_restore_geometry = tuple(
            int(meta.get(k, -1)) for k in GEOMETRY_META_KEYS)
        return epoch, best, sie

    def _manifest_step(self, rung: str) -> Optional[int]:
        """The committed optimizer step recorded in a rung's manifest
        sidecar; None when the rung predates the manifest (or the
        sidecar is unreadable) — those rungs carry no fleet-comparable
        step."""
        try:
            with open(os.path.join(self.root,
                                   rung + ".manifest.json")) as f:
                step = json.load(f).get("step")
            return int(step) if step is not None else None
        except (OSError, ValueError, TypeError):
            return None

    def _apply_resume_cap(self, rungs, cap: Optional[int] = None):
        """Fleet-consistent resume (runtime/gang.py): when the gang
        supervisor passed ``TPUIC_RESUME_STEP`` — the newest step every
        rank's committed manifest agrees on — rungs ahead of it are
        refused, and the kept rungs are reordered newest-first below the
        cap, so this rank lands exactly on the fleet-agreed step instead
        of resuming ahead of peers that never committed it (a survivor's
        mid-teardown flush is deliberately newer than a crashed peer's
        last commit — the precise rung this filter exists to skip).

        ``cap``: an explicit fleet-agreed step wins over the env — the
        elastic degrade path (docs/parallelism.md): a SURVIVOR re-forms
        in-process from the membership record's step, no respawn and
        therefore no fresh env to carry it."""
        if cap is None:
            from tpuic.runtime.supervisor import ENV_RESUME_STEP
            raw = os.environ.get(ENV_RESUME_STEP, "")
            if not raw or not rungs:
                return rungs
            # A malformed supervisor env must fail LOUD.
            allowed = int(raw)
        else:
            if not rungs:
                return rungs
            allowed = int(cap)
        steps = {r: self._manifest_step(r) for r in rungs}
        kept = [r for r in rungs
                if steps[r] is None or steps[r] <= allowed]
        skipped = [r for r in rungs if r not in kept]
        if not kept:
            # Inconsistent with the supervisor's agreed-step math (it
            # only names steps at least one of this rank's rungs holds);
            # restore the OLDEST rung — closest to the fleet, never the
            # one furthest ahead — and say so.
            host0_print(
                f"[ckpt] fleet resume: EVERY rung is ahead of the "
                f"fleet-agreed step {allowed} "
                f"({ {r: steps[r] for r in rungs} }) — restoring the "
                "oldest available rung instead")
            return sorted(rungs, key=lambda r: (steps[r] is None,
                                                steps[r] or 0))
        if skipped:
            host0_print(
                f"[ckpt] fleet resume: skipping rung(s) ahead of the "
                f"fleet-agreed step {allowed}: "
                + ", ".join(f"{r}@{steps[r]}" for r in skipped))
        # Newest rung at-or-below the cap first; manifest-less rungs
        # (pre-ladder, step unknown) keep their ladder order at the end.
        known = [r for r in kept if steps[r] is not None]
        unknown = [r for r in kept if steps[r] is None]
        return sorted(known, key=lambda r: -steps[r]) + unknown

    def verify_track(self, track: str) -> Tuple[bool, str]:
        """Check a track's on-disk bytes against its commit manifest.

        Returns (ok, detail). Missing directory -> not ok. Missing
        manifest -> ok-but-unverified (pre-ladder checkpoint: nothing to
        check against, same trust level those checkpoints always had).
        Unreadable/corrupt manifest, or any file added, missing, resized,
        or failing its CRC -> not ok."""
        path = os.path.join(self.root, track)
        mpath = path + ".manifest.json"
        if not os.path.isdir(path):
            return False, "missing"
        if not os.path.exists(mpath):
            return True, "no manifest (pre-ladder checkpoint, unverified)"
        try:
            with open(mpath) as f:
                expected = json.load(f)["files"]
        except (OSError, ValueError, KeyError, TypeError) as e:
            return False, f"unreadable manifest: {e}"
        live = _dir_manifest(path)
        if live == expected:
            return True, f"verified {len(live)} files"
        for rel in sorted(set(expected) | set(live)):
            if rel not in live:
                return False, f"missing file {rel}"
            if rel not in expected:
                return False, f"unexpected file {rel}"
            if live[rel] != expected[rel]:
                return False, (f"checksum mismatch in {rel} "
                               f"(expected {expected[rel]}, got {live[rel]})")
        return False, "manifest mismatch"  # pragma: no cover — unreachable

    def restore_into(self, state, track: Optional[str] = None,
                     resume_cap: Optional[int] = None):
        """Verified restore of ``state`` through the integrity ladder.

        ``track=None`` starts at the newest of latest/best and falls back
        newest -> other track -> their ``.prev`` rotations on corruption
        (manifest mismatch) or a failed read, logging each rung skipped;
        an explicit ``track`` ladders only through that track and its
        ``.prev``. Returns (state, start_epoch, best_score);
        (state, 0, 0.0) when no checkpoint exists — mirroring the
        reference's probe at train.py:136. Raises RuntimeError when
        checkpoints exist but EVERY rung is corrupt — training silently
        restarting from scratch would be worse than stopping.
        ``last_restore_rung`` records the rung actually used.

        ``resume_cap``: explicit fleet-agreed step cap (the elastic
        degrade path — see ``_apply_resume_cap``); overrides any
        ``TPUIC_RESUME_STEP`` env. This capped restore is also where a
        resharding restore lands: a checkpoint written at R replicas
        (ZeRO-sharded optimizer state over the ``data`` axis) restores
        into whatever shardings the LIVE state carries — Orbax reads
        global arrays and lays them onto the R′-replica mesh's
        shardings, so R → R′ needs no conversion step
        (tests/test_elastic.py pins R=4 → R′∈{2,1} bitwise)."""
        self.wait()  # don't read a track an async save is still writing
        # (n_loaded, n_total) of the last restore's param-leaf merge; None
        # for the sharded fast path (exact structure = full load). Lets
        # callers (tpuic.predict) distinguish "architecture mismatch, zero
        # leaves matched" from a legitimate restore without changing the
        # return contract.
        self.last_restore_loaded = None
        # Completed steps of a partially-trained epoch (mid-epoch preemption
        # flush); None when the checkpoint is a normal end-of-epoch save.
        # Used by the Trainer together with the returned start_epoch.
        self.last_restore_step_in_epoch = None
        # (global_batch, data_seed, data_len) recorded at a mid-epoch
        # flush; None when absent (-1 entries: not recorded). The Trainer
        # refuses the step offset unless ALL match the live loader.
        self.last_restore_geometry = None
        # (saved_epoch, step_in_epoch) of whatever was read — for callers
        # that report provenance (predict) regardless of which restore
        # branch ran.
        self.last_restore_meta = None
        # The ladder rung the restore actually came from (None: nothing
        # restored). != the requested track when the ladder fell back.
        self.last_restore_rung = None
        if track is None:
            primary = self.newest_track() or "latest"
            other = "best" if primary == "latest" else "latest"
            rungs = [primary, other, primary + ".prev", other + ".prev"]
        else:
            rungs = [track, track + ".prev"]
        rungs = [t for t in rungs
                 if os.path.isdir(os.path.join(self.root, t))]
        from tpuic.runtime.supervisor import ENV_RESUME_STEP
        capped = (resume_cap is not None
                  or bool(os.environ.get(ENV_RESUME_STEP, "")))
        rungs = self._apply_resume_cap(rungs, cap=resume_cap)
        if capped and _faults.fire("rank_rejoin_flap"):
            # Flapping-replacement fault (docs/robustness.md): die INSIDE
            # the catch-up (fleet-capped) restore — but only on the rank
            # #PARAM names and only in a respawned life, so the original
            # ranks' spawn-time restores never trip it.
            target = _faults.param("rank_rejoin_flap")
            rank = int(os.environ.get("TPUIC_FLEET_RANK", "0") or 0)
            respawned = int(os.environ.get("TPUIC_RESTART", "0") or 0) > 0
            if respawned and rank == int(target or 0):
                os.kill(os.getpid(), signal.SIGKILL)
        if not rungs:
            return state, 0, 0.0
        failures = []
        for i, rung in enumerate(rungs):
            ok, detail = self.verify_track(rung)
            if not ok:
                host0_print(f"[ckpt] integrity: '{rung}' failed "
                            f"verification ({detail}) — trying next rung")
                failures.append(f"{rung}: {detail}")
                continue
            try:
                out = self._restore_track(state, rung)
            except Exception as e:
                host0_print(f"[ckpt] restore of '{rung}' failed "
                            f"({type(e).__name__}: {e}) — trying next rung")
                failures.append(f"{rung}: {type(e).__name__}: {e}")
                continue
            self.last_restore_rung = rung
            if i > 0:
                host0_print(f"[ckpt] integrity ladder: restored from rung "
                            f"'{rung}' (skipped {i}: "
                            + "; ".join(failures) + ")")
            return out
        # NonRetryable (runtime/supervisor.py exit-code contract): a
        # supervisor restart would walk the same corrupt rungs again —
        # report the poison instead of crash-looping on it.
        from tpuic.runtime.supervisor import NonRetryableError
        raise NonRetryableError(
            "no restorable checkpoint: every integrity-ladder rung failed "
            "(" + "; ".join(failures) + ")")

    def restore_exact(self, state, track: str):
        """Single-rung restore with NO ladder fallback — the hot-swap
        gate's read path (docs/serving.md, "Model lifecycle").

        ``restore_into`` ladders newest → other track → ``.prev`` on
        corruption, which is the right call for a crashed trainer but
        exactly wrong for a swap CANDIDATE: silently restoring the
        previous rotation would flip different weights into traffic
        than the operator named.  The caller verifies THIS rung
        (``verify_track``) first; any read failure here raises rather
        than falling back.  Returns (state, start_epoch, best_score)
        and sets the same ``last_restore_*`` attributes as
        ``restore_into``."""
        self.wait()
        self.last_restore_loaded = None
        self.last_restore_step_in_epoch = None
        self.last_restore_geometry = None
        self.last_restore_meta = None
        self.last_restore_rung = track
        return self._restore_track(state, track)

    def _restore_track(self, state, track: str):
        """Restore one (existing, verified-or-unverifiable) track.

        Two paths: an exact-structure checkpoint restores straight into the
        live shardings (no host gather — each host reads only its shards);
        anything else (architecture drift, partial checkpoints) falls back
        to a host-side key-intersection merge, the reference's semantics
        (train.py:143-148). Optimizer state is restored only on a FULL
        param match (a partial / cross-architecture load makes saved
        moments meaningless).
        """
        path = os.path.join(self.root, track)
        # Fast path: restore into the live shardings. Exact match required —
        # a cross-architecture checkpoint raises (shape/structure mismatch)
        # and drops to the lenient host-side path below. Tried twice:
        # current meta layout first, then the pre-step_in_epoch legacy
        # layout, so old checkpoints keep the no-host-gather path instead
        # of silently degrading to the lenient one.
        for legacy_meta in (False, True):
            try:
                template, restore_args = self._abstract_payload(
                    state, legacy_meta=legacy_meta)
                restored = self._ckptr.restore(
                    path, args=ocp.args.PyTreeRestore(
                        item=template, restore_args=restore_args))
            except Exception:
                continue
            meta = restored.get("meta", {})
            epoch, best, sie = self._read_resume_meta(meta)
            state = state.replace(params=restored["params"],
                                  batch_stats=restored["batch_stats"],
                                  opt_state=restored["opt_state"],
                                  step=np.asarray(meta.get("step", 0)))
            if "ema_params" in restored:
                state = state.replace(ema_params=restored["ema_params"])
            if sie >= 0:
                # Mid-epoch flush: continue THAT epoch at the saved step.
                self.last_restore_step_in_epoch = sie
                host0_print(f"[ckpt] restored (sharded) from {path} "
                            f"(epoch {epoch} at step {sie}, best {best:.4f})")
                return state, epoch, best
            host0_print(f"[ckpt] restored (sharded) from {path} "
                        f"(epoch {epoch}, best {best:.4f})")
            return state, epoch + 1, best
        # Lenient path: host-side key-intersection merge. Restoring against
        # a structure template keeps optax's opt_state pytree types
        # (NamedTuples) instead of raw nested lists; when even the template
        # doesn't fit, a raw restore salvages what intersects.
        template = self._payload(state, 0, 0.0, gather=True)
        try:
            restored = self._ckptr.restore(path, item=template)
        except Exception:
            restored = self._ckptr.restore(path)
        cur_params = jax.tree.map(np.asarray, jax.device_get(state.params))
        merged_params, n_loaded, n_total = lenient_restore(
            cur_params, restored.get("params", {}))
        cur_stats = jax.tree.map(np.asarray, jax.device_get(state.batch_stats))
        merged_stats, _, _ = lenient_restore(cur_stats,
                                             restored.get("batch_stats", {}))
        state = state.replace(params=merged_params, batch_stats=merged_stats)
        if getattr(state, "ema_params", None) is not None:
            if restored.get("ema_params"):
                cur_ema = jax.tree.map(np.asarray,
                                       jax.device_get(state.ema_params))
                merged_ema, _, _ = lenient_restore(cur_ema,
                                                   restored["ema_params"])
                state = state.replace(ema_params=merged_ema)
            else:
                # Pre-EMA checkpoint into an EMA run: reseed at the
                # restored params rather than keeping the random-init copy
                # (which validation would score for ~1/(1-d) updates).
                state = state.replace(
                    ema_params=jax.tree.map(np.copy, merged_params))
        meta = restored.get("meta", {})
        epoch, best, sie = self._read_resume_meta(meta)
        opt_ok = False
        if n_loaded == n_total:
            step = meta.get("step")
            if step is not None:
                state = state.replace(step=np.asarray(step))
            try:
                state = state.replace(opt_state=restored["opt_state"])
                opt_ok = step is not None
            except (KeyError, TypeError):
                host0_print("[ckpt] opt_state structure mismatch — optimizer "
                            "state reset")
        host0_print(f"[ckpt] restored {n_loaded}/{n_total} param leaves from "
                    f"{path} (epoch {epoch}, best {best:.4f})")
        self.last_restore_loaded = (n_loaded, n_total)
        if sie >= 0 and n_loaded == n_total and opt_ok:
            # Step-exact continuation only for a FULL restore including the
            # optimizer moments and step counter — continuing mid-epoch on
            # a reset optimizer would silently break the bitwise-resume
            # contract; replaying the epoch from its start is the honest
            # fallback there.
            self.last_restore_step_in_epoch = sie
            return state, epoch, best
        if sie >= 0 and n_loaded:
            # Mid-epoch checkpoint through the degraded path: REPLAY the
            # interrupted epoch (start at its step 0) — returning epoch+1
            # here would silently skip its untrained tail.
            return state, epoch, best
        return state, epoch + 1 if n_loaded else 0, best
