"""Bare-torch replicas of the reference's model families.

The reference builds ``Classifier(name, n)`` from torchvision /
efficientnet_pytorch pretrained backbones (nn/classifier.py:9-23) with an
MLP head (in->128->64->32->n, nn/classifier.py:26-34). Those packages are
not installed in this image; these replicas reproduce the exact upstream
*module naming* (so their ``state_dict`` keys match real checkpoints) and
forward semantics in bare torch. Used by:

- ``python -m tpuic.checkpoint.torch_convert <ckpt> --verify`` — load a
  reference checkpoint into the replica and into the converted tpuic model,
  and print the max logits delta (SURVEY.md §7 "Checkpoint compatibility");
- the converter parity tests (tests/test_torch_convert*.py).

Everything imports torch lazily so the rest of tpuic never needs it.
"""

from __future__ import annotations

import math


def _torch():
    import torch
    import torch.nn as tnn
    import torch.nn.functional as F
    return torch, tnn, F


def reference_mlp_head(in_features: int, num_classes: int):
    """nn/classifier.py:26-34: Sequential Linear/ReLU indices fc.0/2/4/6."""
    _, tnn, _ = _torch()
    return tnn.Sequential(
        tnn.Linear(in_features, 128), tnn.ReLU(),
        tnn.Linear(128, 64), tnn.ReLU(),
        tnn.Linear(64, 32), tnn.ReLU(),
        tnn.Linear(32, num_classes))


# ---------------------------------------------------------------------------
# ResNet (torchvision naming)
# ---------------------------------------------------------------------------

_RESNET_CFG = {
    "resnet18": ("basic", (2, 2, 2, 2)),
    "resnet34": ("basic", (3, 4, 6, 3)),
    "resnet50": ("bottleneck", (3, 4, 6, 3)),
    "resnet101": ("bottleneck", (3, 4, 23, 3)),
    "resnet152": ("bottleneck", (3, 8, 36, 3)),
}


def build_resnet(arch: str, num_classes: int = 7, mlp_head: bool = True):
    torch, tnn, F = _torch()
    # '-cifar' suffix: 3x3/s1 small stem, no maxpool — mirrors the flax
    # zoo's small_stem variant (tpuic/models/resnet.py) so the digits/CIFAR
    # convergence control trains the architecture tpuic actually ships.
    small_stem = arch.endswith("-cifar")
    kind, sizes = _RESNET_CFG[arch[:-len("-cifar")] if small_stem else arch]
    expansion = 1 if kind == "basic" else 4

    class BasicBlock(tnn.Module):
        def __init__(self, inp, out, stride=1):
            super().__init__()
            self.conv1 = tnn.Conv2d(inp, out, 3, stride, 1, bias=False)
            self.bn1 = tnn.BatchNorm2d(out)
            self.conv2 = tnn.Conv2d(out, out, 3, 1, 1, bias=False)
            self.bn2 = tnn.BatchNorm2d(out)
            self.relu = tnn.ReLU(inplace=True)
            self.downsample = None
            if stride != 1 or inp != out:
                self.downsample = tnn.Sequential(
                    tnn.Conv2d(inp, out, 1, stride, bias=False),
                    tnn.BatchNorm2d(out))

        def forward(self, x):
            idt = x if self.downsample is None else self.downsample(x)
            y = self.relu(self.bn1(self.conv1(x)))
            y = self.bn2(self.conv2(y))
            return self.relu(y + idt)

    class Bottleneck(tnn.Module):
        def __init__(self, inp, width, stride=1):
            super().__init__()
            out = width * 4
            self.conv1 = tnn.Conv2d(inp, width, 1, bias=False)
            self.bn1 = tnn.BatchNorm2d(width)
            self.conv2 = tnn.Conv2d(width, width, 3, stride, 1, bias=False)
            self.bn2 = tnn.BatchNorm2d(width)
            self.conv3 = tnn.Conv2d(width, out, 1, bias=False)
            self.bn3 = tnn.BatchNorm2d(out)
            self.relu = tnn.ReLU(inplace=True)
            self.downsample = None
            if stride != 1 or inp != out:
                self.downsample = tnn.Sequential(
                    tnn.Conv2d(inp, out, 1, stride, bias=False),
                    tnn.BatchNorm2d(out))

        def forward(self, x):
            idt = x if self.downsample is None else self.downsample(x)
            y = self.relu(self.bn1(self.conv1(x)))
            y = self.relu(self.bn2(self.conv2(y)))
            y = self.bn3(self.conv3(y))
            return self.relu(y + idt)

    class ResNet(tnn.Module):
        def __init__(self):
            super().__init__()
            self.conv1 = (tnn.Conv2d(3, 64, 3, 1, 1, bias=False) if small_stem
                          else tnn.Conv2d(3, 64, 7, 2, 3, bias=False))
            self.bn1 = tnn.BatchNorm2d(64)
            self.relu = tnn.ReLU(inplace=True)
            self.maxpool = (tnn.Identity() if small_stem
                            else tnn.MaxPool2d(3, 2, 1))
            widths = (64, 128, 256, 512)
            inp = 64
            for s, (w, n) in enumerate(zip(widths, sizes), start=1):
                blocks = []
                for i in range(n):
                    stride = 2 if s > 1 and i == 0 else 1
                    if kind == "basic":
                        blocks.append(BasicBlock(inp, w, stride))
                        inp = w
                    else:
                        blocks.append(Bottleneck(inp, w, stride))
                        inp = w * 4
                setattr(self, f"layer{s}", tnn.Sequential(*blocks))
            feat = 512 * expansion
            self.fc = (reference_mlp_head(feat, num_classes) if mlp_head
                       else tnn.Linear(feat, num_classes))

        def forward(self, x):
            x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
            for s in (1, 2, 3, 4):
                x = getattr(self, f"layer{s}")(x)
            x = x.mean(dim=(2, 3))
            return self.fc(x)

    return ResNet()


# ---------------------------------------------------------------------------
# Inception-v3 (torchvision naming)
# ---------------------------------------------------------------------------

def build_inception(num_classes: int = 7, aux: bool = True,
                    mlp_head: bool = True):
    torch, tnn, F = _torch()

    class BasicConv2d(tnn.Module):
        def __init__(self, inp, out, **kw):
            super().__init__()
            self.conv = tnn.Conv2d(inp, out, bias=False, **kw)
            self.bn = tnn.BatchNorm2d(out, eps=0.001)

        def forward(self, x):
            return F.relu(self.bn(self.conv(x)))

    class InceptionA(tnn.Module):
        def __init__(self, inp, pool_features):
            super().__init__()
            self.branch1x1 = BasicConv2d(inp, 64, kernel_size=1)
            self.branch5x5_1 = BasicConv2d(inp, 48, kernel_size=1)
            self.branch5x5_2 = BasicConv2d(48, 64, kernel_size=5, padding=2)
            self.branch3x3dbl_1 = BasicConv2d(inp, 64, kernel_size=1)
            self.branch3x3dbl_2 = BasicConv2d(64, 96, kernel_size=3, padding=1)
            self.branch3x3dbl_3 = BasicConv2d(96, 96, kernel_size=3, padding=1)
            self.branch_pool = BasicConv2d(inp, pool_features, kernel_size=1)

        def forward(self, x):
            b1 = self.branch1x1(x)
            b5 = self.branch5x5_2(self.branch5x5_1(x))
            b3 = self.branch3x3dbl_3(
                self.branch3x3dbl_2(self.branch3x3dbl_1(x)))
            bp = self.branch_pool(F.avg_pool2d(x, 3, stride=1, padding=1))
            return torch.cat([b1, b5, b3, bp], 1)

    class InceptionB(tnn.Module):
        def __init__(self, inp):
            super().__init__()
            self.branch3x3 = BasicConv2d(inp, 384, kernel_size=3, stride=2)
            self.branch3x3dbl_1 = BasicConv2d(inp, 64, kernel_size=1)
            self.branch3x3dbl_2 = BasicConv2d(64, 96, kernel_size=3, padding=1)
            self.branch3x3dbl_3 = BasicConv2d(96, 96, kernel_size=3, stride=2)

        def forward(self, x):
            return torch.cat([
                self.branch3x3(x),
                self.branch3x3dbl_3(
                    self.branch3x3dbl_2(self.branch3x3dbl_1(x))),
                F.max_pool2d(x, 3, stride=2)], 1)

    class InceptionC(tnn.Module):
        def __init__(self, inp, c7):
            super().__init__()
            self.branch1x1 = BasicConv2d(inp, 192, kernel_size=1)
            self.branch7x7_1 = BasicConv2d(inp, c7, kernel_size=1)
            self.branch7x7_2 = BasicConv2d(c7, c7, kernel_size=(1, 7),
                                           padding=(0, 3))
            self.branch7x7_3 = BasicConv2d(c7, 192, kernel_size=(7, 1),
                                           padding=(3, 0))
            self.branch7x7dbl_1 = BasicConv2d(inp, c7, kernel_size=1)
            self.branch7x7dbl_2 = BasicConv2d(c7, c7, kernel_size=(7, 1),
                                              padding=(3, 0))
            self.branch7x7dbl_3 = BasicConv2d(c7, c7, kernel_size=(1, 7),
                                              padding=(0, 3))
            self.branch7x7dbl_4 = BasicConv2d(c7, c7, kernel_size=(7, 1),
                                              padding=(3, 0))
            self.branch7x7dbl_5 = BasicConv2d(c7, 192, kernel_size=(1, 7),
                                              padding=(0, 3))
            self.branch_pool = BasicConv2d(inp, 192, kernel_size=1)

        def forward(self, x):
            b7 = self.branch7x7_3(self.branch7x7_2(self.branch7x7_1(x)))
            bd = self.branch7x7dbl_1(x)
            for m in (self.branch7x7dbl_2, self.branch7x7dbl_3,
                      self.branch7x7dbl_4, self.branch7x7dbl_5):
                bd = m(bd)
            bp = self.branch_pool(F.avg_pool2d(x, 3, stride=1, padding=1))
            return torch.cat([self.branch1x1(x), b7, bd, bp], 1)

    class InceptionD(tnn.Module):
        def __init__(self, inp):
            super().__init__()
            self.branch3x3_1 = BasicConv2d(inp, 192, kernel_size=1)
            self.branch3x3_2 = BasicConv2d(192, 320, kernel_size=3, stride=2)
            self.branch7x7x3_1 = BasicConv2d(inp, 192, kernel_size=1)
            self.branch7x7x3_2 = BasicConv2d(192, 192, kernel_size=(1, 7),
                                             padding=(0, 3))
            self.branch7x7x3_3 = BasicConv2d(192, 192, kernel_size=(7, 1),
                                             padding=(3, 0))
            self.branch7x7x3_4 = BasicConv2d(192, 192, kernel_size=3, stride=2)

        def forward(self, x):
            b7 = self.branch7x7x3_1(x)
            for m in (self.branch7x7x3_2, self.branch7x7x3_3,
                      self.branch7x7x3_4):
                b7 = m(b7)
            return torch.cat([
                self.branch3x3_2(self.branch3x3_1(x)), b7,
                F.max_pool2d(x, 3, stride=2)], 1)

    class InceptionE(tnn.Module):
        def __init__(self, inp):
            super().__init__()
            self.branch1x1 = BasicConv2d(inp, 320, kernel_size=1)
            self.branch3x3_1 = BasicConv2d(inp, 384, kernel_size=1)
            self.branch3x3_2a = BasicConv2d(384, 384, kernel_size=(1, 3),
                                            padding=(0, 1))
            self.branch3x3_2b = BasicConv2d(384, 384, kernel_size=(3, 1),
                                            padding=(1, 0))
            self.branch3x3dbl_1 = BasicConv2d(inp, 448, kernel_size=1)
            self.branch3x3dbl_2 = BasicConv2d(448, 384, kernel_size=3,
                                              padding=1)
            self.branch3x3dbl_3a = BasicConv2d(384, 384, kernel_size=(1, 3),
                                               padding=(0, 1))
            self.branch3x3dbl_3b = BasicConv2d(384, 384, kernel_size=(3, 1),
                                               padding=(1, 0))
            self.branch_pool = BasicConv2d(inp, 192, kernel_size=1)

        def forward(self, x):
            b3 = self.branch3x3_1(x)
            b3 = torch.cat([self.branch3x3_2a(b3), self.branch3x3_2b(b3)], 1)
            bd = self.branch3x3dbl_2(self.branch3x3dbl_1(x))
            bd = torch.cat([self.branch3x3dbl_3a(bd),
                            self.branch3x3dbl_3b(bd)], 1)
            bp = self.branch_pool(F.avg_pool2d(x, 3, stride=1, padding=1))
            return torch.cat([self.branch1x1(x), b3, bd, bp], 1)

    class InceptionAux(tnn.Module):
        def __init__(self, inp, n):
            super().__init__()
            self.conv0 = BasicConv2d(inp, 128, kernel_size=1)
            self.conv1 = BasicConv2d(128, 768, kernel_size=5)
            self.fc = tnn.Linear(768, n)

        def forward(self, x):
            x = F.avg_pool2d(x, 5, stride=3)
            x = self.conv1(self.conv0(x))
            x = F.adaptive_avg_pool2d(x, (1, 1)).flatten(1)
            return self.fc(x)

    class InceptionV3(tnn.Module):
        """torchvision-named inception_v3 body + the reference's MLP head
        (+ the reference's replaced AuxLogits.fc, nn/classifier.py:22-23)."""

        def __init__(self):
            super().__init__()
            self.Conv2d_1a_3x3 = BasicConv2d(3, 32, kernel_size=3, stride=2)
            self.Conv2d_2a_3x3 = BasicConv2d(32, 32, kernel_size=3)
            self.Conv2d_2b_3x3 = BasicConv2d(32, 64, kernel_size=3, padding=1)
            self.Conv2d_3b_1x1 = BasicConv2d(64, 80, kernel_size=1)
            self.Conv2d_4a_3x3 = BasicConv2d(80, 192, kernel_size=3)
            self.Mixed_5b = InceptionA(192, 32)
            self.Mixed_5c = InceptionA(256, 64)
            self.Mixed_5d = InceptionA(288, 64)
            self.Mixed_6a = InceptionB(288)
            self.Mixed_6b = InceptionC(768, 128)
            self.Mixed_6c = InceptionC(768, 160)
            self.Mixed_6d = InceptionC(768, 160)
            self.Mixed_6e = InceptionC(768, 192)
            if aux:
                self.AuxLogits = InceptionAux(768, num_classes)
            self.Mixed_7a = InceptionD(768)
            self.Mixed_7b = InceptionE(1280)
            self.Mixed_7c = InceptionE(2048)
            self.fc = (reference_mlp_head(2048, num_classes)
                       if mlp_head else tnn.Linear(2048, num_classes))

        def forward(self, x):
            x = self.Conv2d_1a_3x3(x)
            x = self.Conv2d_2a_3x3(x)
            x = self.Conv2d_2b_3x3(x)
            x = F.max_pool2d(x, 3, stride=2)
            x = self.Conv2d_3b_1x1(x)
            x = self.Conv2d_4a_3x3(x)
            x = F.max_pool2d(x, 3, stride=2)
            for name in ("Mixed_5b", "Mixed_5c", "Mixed_5d", "Mixed_6a",
                         "Mixed_6b", "Mixed_6c", "Mixed_6d", "Mixed_6e",
                         "Mixed_7a", "Mixed_7b", "Mixed_7c"):
                x = getattr(self, name)(x)
            x = x.mean(dim=(2, 3))
            return self.fc(x)

    return InceptionV3()


# ---------------------------------------------------------------------------
# EfficientNet (efficientnet_pytorch naming, TF SAME padding)
# ---------------------------------------------------------------------------

# (expand, channels, repeats, stride, kernel) — the B0 base blocks.
_EFFNET_BASE_BLOCKS = ((1, 16, 1, 1, 3), (6, 24, 2, 2, 3), (6, 40, 2, 2, 5),
                       (6, 80, 3, 2, 3), (6, 112, 3, 1, 5), (6, 192, 4, 2, 5),
                       (6, 320, 1, 1, 3))
# (width_coefficient, depth_coefficient) per variant.
_EFFNET_COEF = {"b0": (1.0, 1.0), "b1": (1.0, 1.1),
                "b2": (1.1, 1.2), "b3": (1.2, 1.4),
                "b4": (1.4, 1.8), "b5": (1.6, 2.2),
                "b6": (1.8, 2.6), "b7": (2.0, 3.1)}


def _round_filters(filters: int, width: float, divisor: int = 8) -> int:
    filters *= width
    new = max(divisor, int(filters + divisor / 2) // divisor * divisor)
    if new < 0.9 * filters:
        new += divisor
    return int(new)


def _round_repeats(repeats: int, depth: float) -> int:
    return int(math.ceil(depth * repeats))


def build_efficientnet(variant: str = "b0", num_classes: int = 7,
                       mlp_head: bool = False):
    """efficientnet_pytorch-named EfficientNet with its single-Linear _fc.

    Note the reference's efficientnet branch is broken upstream
    (nn/classifier.py:17-18+27 sets ``.fc`` on a model whose attr is
    ``._fc``); the package's own ``_fc`` head is replicated, which the
    converter maps to ``head/out``. ``mlp_head=True`` replaces it with the
    reference-style MLP Sequential at attribute ``fc`` (keys fc.{0,2,..})
    — the layout tpuic's export emits for MLP-head efficientnet
    checkpoints, so --export-torch --verify has a loadable replica."""
    torch, tnn, F = _torch()
    width, depth = _EFFNET_COEF[variant]

    class SameConv2d(tnn.Conv2d):
        def forward(self, x):
            ih, iw = x.shape[-2:]
            kh, kw = self.weight.shape[-2:]
            sh, sw = self.stride
            ph = max((math.ceil(ih / sh) - 1) * sh + kh - ih, 0)
            pw = max((math.ceil(iw / sw) - 1) * sw + kw - iw, 0)
            x = F.pad(x, [pw // 2, pw - pw // 2, ph // 2, ph - ph // 2])
            return F.conv2d(x, self.weight, self.bias, self.stride, 0,
                            self.dilation, self.groups)

    def swish(x):
        return x * torch.sigmoid(x)

    class MBConv(tnn.Module):
        def __init__(self, inp, out, expand, kernel, stride):
            super().__init__()
            mid = inp * expand
            self.has_expand = expand != 1
            if self.has_expand:
                self._expand_conv = SameConv2d(inp, mid, 1, bias=False)
                self._bn0 = tnn.BatchNorm2d(mid, eps=1e-3)
            self._depthwise_conv = SameConv2d(mid, mid, kernel, stride=stride,
                                              groups=mid, bias=False)
            self._bn1 = tnn.BatchNorm2d(mid, eps=1e-3)
            se_ch = max(1, int(inp * 0.25))
            self._se_reduce = SameConv2d(mid, se_ch, 1)
            self._se_expand = SameConv2d(se_ch, mid, 1)
            self._project_conv = SameConv2d(mid, out, 1, bias=False)
            self._bn2 = tnn.BatchNorm2d(out, eps=1e-3)
            self.skip = stride == 1 and inp == out

        def forward(self, x):
            y = x
            if self.has_expand:
                y = swish(self._bn0(self._expand_conv(y)))
            y = swish(self._bn1(self._depthwise_conv(y)))
            s = F.adaptive_avg_pool2d(y, 1)
            s = self._se_expand(swish(self._se_reduce(s)))
            y = torch.sigmoid(s) * y
            y = self._bn2(self._project_conv(y))
            return y + x if self.skip else y

    class EfficientNet(tnn.Module):
        def __init__(self):
            super().__init__()
            stem = _round_filters(32, width)
            self._conv_stem = SameConv2d(3, stem, 3, stride=2, bias=False)
            self._bn0 = tnn.BatchNorm2d(stem, eps=1e-3)
            blocks = []
            inp = stem
            for expand, ch, repeats, stride, kernel in _EFFNET_BASE_BLOCKS:
                out = _round_filters(ch, width)
                for r in range(_round_repeats(repeats, depth)):
                    blocks.append(MBConv(inp, out, expand, kernel,
                                         stride if r == 0 else 1))
                    inp = out
            self._blocks = tnn.ModuleList(blocks)
            head = _round_filters(1280, width)
            self._conv_head = SameConv2d(inp, head, 1, bias=False)
            self._bn1 = tnn.BatchNorm2d(head, eps=1e-3)
            if mlp_head:
                self.fc = reference_mlp_head(head, num_classes)
            else:
                self._fc = tnn.Linear(head, num_classes)

        def forward(self, x):
            x = swish(self._bn0(self._conv_stem(x)))
            for b in self._blocks:
                x = b(x)
            x = swish(self._bn1(self._conv_head(x)))
            x = F.adaptive_avg_pool2d(x, 1).flatten(1)
            return self.fc(x) if mlp_head else self._fc(x)

    return EfficientNet()


# ---------------------------------------------------------------------------
# ViT (torchvision vision_transformer naming: vit_b_16 / vit_l_16 / vit_s_16)
# ---------------------------------------------------------------------------

_VIT_CFG = {  # name -> (patch, hidden, depth, heads)
    "vit-b16": (16, 768, 12, 12),
    "vit-l16": (16, 1024, 24, 16),
    "vit-b32": (32, 768, 12, 12),
    "vit-l32": (32, 1024, 24, 16),
    "vit-s16": (16, 384, 12, 6),
    # test-scale (tpuic-only size; same module naming)
    "vit-tiny": (4, 64, 2, 4),
}


def build_vit(variant: str = "vit-b16", num_classes: int = 7,
              image_size: int = 224, mlp_head: bool = True):
    """torchvision ``VisionTransformer``-naming replica: conv_proj,
    class_token, encoder.pos_embedding, encoder.layers.encoder_layer_i
    (ln_1 / self_attention / ln_2 / mlp.{0,3}), encoder.ln, heads.head.
    ``self_attention`` is a real ``nn.MultiheadAttention`` so
    in_proj_weight/out_proj match upstream checkpoints exactly."""
    torch, tnn, F = _torch()
    patch, hidden, depth, heads = _VIT_CFG[variant]
    n_tokens = (image_size // patch) ** 2 + 1

    class EncoderBlock(tnn.Module):
        def __init__(self):
            super().__init__()
            self.ln_1 = tnn.LayerNorm(hidden, eps=1e-6)
            self.self_attention = tnn.MultiheadAttention(
                hidden, heads, batch_first=True)
            self.ln_2 = tnn.LayerNorm(hidden, eps=1e-6)
            # torchvision MLPBlock state-dict naming (>=0.12): Sequential
            # indices 0 (Linear), 1 (GELU), 2 (Dropout), 3 (Linear).
            self.mlp = tnn.Sequential(
                tnn.Linear(hidden, 4 * hidden), tnn.GELU(),
                tnn.Dropout(0.0), tnn.Linear(4 * hidden, hidden))

        def forward(self, x):
            y = self.ln_1(x)
            y, _ = self.self_attention(y, y, y, need_weights=False)
            x = x + y
            return x + self.mlp(self.ln_2(x))

    class Encoder(tnn.Module):
        def __init__(self):
            super().__init__()
            self.pos_embedding = tnn.Parameter(
                torch.empty(1, n_tokens, hidden).normal_(std=0.02))
            self.layers = tnn.Sequential()
            for i in range(depth):
                self.layers.add_module(f"encoder_layer_{i}", EncoderBlock())
            self.ln = tnn.LayerNorm(hidden, eps=1e-6)

        def forward(self, x):
            return self.ln(self.layers(x + self.pos_embedding))

    class VisionTransformer(tnn.Module):
        def __init__(self):
            super().__init__()
            self.conv_proj = tnn.Conv2d(3, hidden, patch, patch)
            self.class_token = tnn.Parameter(torch.zeros(1, 1, hidden))
            self.encoder = Encoder()
            self.heads = tnn.Sequential()
            self.heads.add_module(
                "head", reference_mlp_head(hidden, num_classes) if mlp_head
                else tnn.Linear(hidden, num_classes))

        def forward(self, x):
            b = x.shape[0]
            x = self.conv_proj(x)                     # [B, D, H', W']
            x = x.reshape(b, hidden, -1).permute(0, 2, 1)   # [B, N, D]
            x = torch.cat([self.class_token.expand(b, -1, -1), x], dim=1)
            x = self.encoder(x)
            return self.heads(x[:, 0])

    return VisionTransformer()


def build_reference_model(arch: str, num_classes: int = 7,
                          mlp_head: bool = True, image_size: int = 224):
    """Replica of the reference ``Classifier(name, n)`` for a backbone name
    (nn/classifier.py:8-34). arch: resnet18/34/50/101/152, inceptionv3,
    efficientnet-b{0..7}, vit-{b16,l16,s16,tiny}. ``mlp_head`` selects the
    reference MLP head vs the family's plain single-Linear head
    (torchvision fc / efficientnet_pytorch _fc) — pass what _infer_head
    detected so --verify builds a replica that can actually load the
    checkpoint. ``image_size`` only matters for ViT (pos-embedding length);
    CNNs ignore it."""
    if (arch in _RESNET_CFG
            or (arch.endswith("-cifar")
                and arch[:-len("-cifar")] in _RESNET_CFG)):
        return build_resnet(arch, num_classes, mlp_head=mlp_head)
    if arch.startswith("inception"):
        return build_inception(num_classes, mlp_head=mlp_head)
    if arch.startswith("efficientnet"):
        variant = arch.rsplit("-", 1)[-1] if "-" in arch else "b0"
        return build_efficientnet(variant, num_classes, mlp_head=mlp_head)
    if arch in _VIT_CFG:
        return build_vit(arch, num_classes, image_size=image_size,
                         mlp_head=mlp_head)
    raise ValueError(f"no torch replica for arch '{arch}'")
