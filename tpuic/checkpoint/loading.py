"""Checkpoint -> inference variables (shared by tpuic.predict and
tpuic.serve).

Restoring weights for *inference* has stricter rules than the trainer's
lenient resume, and they used to live inline in predict.py; the serving
engine needs the identical behavior, so they live here once:

- a typo'd ``--ckpt-dir``/track is a hard error, never a confident run
  on fresh-init noise;
- a partial key-intersection merge (a training-time feature for
  architecture evolution) is a hard error too — fresh-init leaves in
  the forward mean the wrong ``--model``/``--num-classes``;
- EMA-trained checkpoints serve their EMA weights
  (``state.inference_params`` — the weights 'best' was selected on);
- the returned tree is device-resident (one up-front transfer; host
  leaves would be re-uploaded on every jitted/compiled call).
"""

from __future__ import annotations

import os


def load_inference_variables(cfg, *, track: str = "best", log=print):
    """Build ``cfg.model`` and restore its inference variables.

    ``cfg.run.init_from`` (a torch checkpoint) wins over the
    CheckpointManager track.  Returns ``(model, variables)`` with
    ``variables = {'params': ..., 'batch_stats': ...}`` on device.
    """
    import jax

    from tpuic.checkpoint.manager import CheckpointManager
    from tpuic.models import create_model_from_config
    from tpuic.train.optimizer import make_optimizer
    from tpuic.train.state import create_train_state

    mcfg = cfg.model
    model = create_model_from_config(mcfg)
    state = create_train_state(
        model, make_optimizer(cfg.optim), jax.random.key(0),
        (1, cfg.data.resize_size, cfg.data.resize_size, 3),
        ema=cfg.optim.ema_decay > 0)

    if cfg.run.init_from:
        from tpuic.checkpoint.torch_convert import init_state_from_torch
        state = init_state_from_torch(state, cfg.run.init_from, mcfg.name,
                                      log=log)
    else:
        mgr = CheckpointManager(cfg.run.ckpt_dir, mcfg.name)
        if not os.path.isdir(os.path.join(mgr.root, track)):
            # restore_into would silently return the fresh init — a typo'd
            # ckpt dir must not produce confident predictions of noise.
            raise FileNotFoundError(
                f"no '{track}' checkpoint under {mgr.root}")
        state, next_epoch, best = mgr.restore_into(state, track=track)
        loaded = mgr.last_restore_loaded  # None = exact sharded restore
        if loaded is not None and loaded[0] < loaded[1]:
            raise ValueError(
                f"checkpoint {mgr.root}/{track} restored only "
                f"{loaded[0]}/{loaded[1]} leaves into model '{mcfg.name}' — "
                "wrong --model or --num-classes for this checkpoint?")
        # last_restore_meta carries the SAVED (epoch, step_in_epoch)
        # regardless of which restore branch ran (next_epoch is
        # saved_epoch+1 for end-of-epoch checkpoints but the same epoch
        # for mid-epoch preemption flushes — not invertible here).
        meta = getattr(mgr, "last_restore_meta", None)
        if meta is not None:
            saved_epoch, sie = meta
            saved_at = (f"epoch {saved_epoch} step {sie}" if sie >= 0
                        else f"epoch {saved_epoch}")
        else:
            saved_at = f"epoch {max(0, next_epoch - 1)}"
        log(f"[load] restored {mcfg.name}/{track} (saved at "
            f"{saved_at}, best {best:.2f})")

    variables = jax.device_put(
        {"params": state.inference_params,
         "batch_stats": state.batch_stats})
    return model, variables
