"""Checkpoint -> inference variables (shared by tpuic.predict and
tpuic.serve).

Restoring weights for *inference* has stricter rules than the trainer's
lenient resume, and they used to live inline in predict.py; the serving
engine needs the identical behavior, so they live here once:

- a typo'd ``--ckpt-dir``/track is a hard error, never a confident run
  on fresh-init noise;
- a partial key-intersection merge (a training-time feature for
  architecture evolution) is a hard error too — fresh-init leaves in
  the forward mean the wrong ``--model``/``--num-classes``;
- EMA-trained checkpoints serve their EMA weights
  (``state.inference_params`` — the weights 'best' was selected on);
- the returned tree is device-resident (one up-front transfer; host
  leaves would be re-uploaded on every jitted/compiled call).
"""

from __future__ import annotations

import os


def load_inference_variables(cfg, *, track: str = "best", log=print):
    """Build ``cfg.model`` and restore its inference variables.

    ``cfg.run.init_from`` (a torch checkpoint) wins over the
    CheckpointManager track.  Returns ``(model, variables)`` with
    ``variables = {'params': ..., 'batch_stats': ...}`` on device.
    """
    import jax

    from tpuic.checkpoint.manager import CheckpointManager
    from tpuic.models import create_model_from_config
    from tpuic.train.optimizer import make_optimizer
    from tpuic.train.state import create_train_state

    mcfg = cfg.model
    model = create_model_from_config(mcfg)
    state = create_train_state(
        model, make_optimizer(cfg.optim), jax.random.key(0),
        (1, cfg.data.resize_size, cfg.data.resize_size, 3),
        ema=cfg.optim.ema_decay > 0)

    if cfg.run.init_from:
        from tpuic.checkpoint.torch_convert import init_state_from_torch
        state = init_state_from_torch(state, cfg.run.init_from, mcfg.name,
                                      log=log)
    else:
        mgr = CheckpointManager(cfg.run.ckpt_dir, mcfg.name)
        if not os.path.isdir(os.path.join(mgr.root, track)):
            # restore_into would silently return the fresh init — a typo'd
            # ckpt dir must not produce confident predictions of noise.
            raise FileNotFoundError(
                f"no '{track}' checkpoint under {mgr.root}")
        state, next_epoch, best = mgr.restore_into(state, track=track)
        loaded = mgr.last_restore_loaded  # None = exact sharded restore
        if loaded is not None and loaded[0] < loaded[1]:
            raise ValueError(
                f"checkpoint {mgr.root}/{track} restored only "
                f"{loaded[0]}/{loaded[1]} leaves into model '{mcfg.name}' — "
                "wrong --model or --num-classes for this checkpoint?")
        # last_restore_meta carries the SAVED (epoch, step_in_epoch)
        # regardless of which restore branch ran (next_epoch is
        # saved_epoch+1 for end-of-epoch checkpoints but the same epoch
        # for mid-epoch preemption flushes — not invertible here).
        meta = getattr(mgr, "last_restore_meta", None)
        if meta is not None:
            saved_epoch, sie = meta
            saved_at = (f"epoch {saved_epoch} step {sie}" if sie >= 0
                        else f"epoch {saved_epoch}")
        else:
            saved_at = f"epoch {max(0, next_epoch - 1)}"
        log(f"[load] restored {mcfg.name}/{track} (saved at "
            f"{saved_at}, best {best:.2f})")

    variables = jax.device_put(
        {"params": state.inference_params,
         "batch_stats": state.batch_stats})
    return model, variables


def variables_digest(variables) -> str:
    """Content digest of an inference variables tree (8 hex chars) —
    the model-identity tag the serve tier's ready-file/ping protocol
    and hot-swap ledger carry.  One pinned implementation, shared with
    the engine (tpuic/serve/engine.py) so a digest computed at load
    time and one computed by a serving engine always agree."""
    from tpuic.serve.engine import _tree_digest
    return _tree_digest(variables)


def load_candidate_variables(cfg, *, track: str = "best", log=print):
    """Gate-grade load of a hot-swap CANDIDATE (docs/serving.md,
    "Model lifecycle: hot-swap, canary, rollback").

    Stricter than :func:`load_inference_variables` in exactly the ways
    a weight flip under live traffic demands:

    - **No integrity-ladder fallback.**  ``restore_into`` walks
      newest → other track → ``.prev`` on corruption — right for a
      crashed trainer, wrong for a swap: silently flipping the previous
      rotation into traffic would serve weights the operator never
      named.  Only the REQUESTED rung is considered.
    - **The CRC/manifest check is mandatory.**  A candidate without a
      committed manifest (or failing its per-file CRCs) raises a typed
      :class:`~tpuic.serve.admission.SwapRejected` with cause
      ``swap_corrupt`` — the refusal verdict the swap control line
      returns to the rollout driver, so a bad artifact can never reach
      traffic.  (Legacy manifest-less checkpoints still *boot* a server
      via ``load_inference_variables``; they just cannot hot-swap in.)
    - The incumbent is never touched: everything restores into a fresh
      state tree, so a failed (or corrupt-rung) load leaves a serving
      engine's variables bit-identical (tests/test_lifecycle.py).

    Fault point ``swap_corrupt`` (runtime/faults.py): when armed, the
    candidate's largest payload file is corrupted *after* it is located
    but *before* verification — the bit-rot-between-producer-and-gate
    shape the CRC gate exists to catch.

    Returns ``(model, variables, digest)`` with ``variables`` on
    device and ``digest`` the :func:`variables_digest` identity tag.
    """
    import jax

    from tpuic.checkpoint.manager import CheckpointManager
    from tpuic.models import create_model_from_config
    from tpuic.runtime import faults as _faults
    from tpuic.serve.admission import SwapRejected
    from tpuic.train.optimizer import make_optimizer
    from tpuic.train.state import create_train_state

    mcfg = cfg.model
    mgr = CheckpointManager(cfg.run.ckpt_dir, mcfg.name)
    path = os.path.join(mgr.root, track)
    if not os.path.isdir(path):
        raise SwapRejected(
            f"swap candidate missing: no '{track}' checkpoint under "
            f"{mgr.root}", cause="swap_corrupt")
    if _faults.fire("swap_corrupt"):
        victim, size = None, -1
        for dirpath, _, filenames in os.walk(path):
            for fn in filenames:
                fp = os.path.join(dirpath, fn)
                if os.path.getsize(fp) > size:
                    victim, size = fp, os.path.getsize(fp)
        if victim is not None:
            _faults.corrupt_file(victim)
            log(f"[swap] fault 'swap_corrupt': corrupted "
                f"{os.path.relpath(victim, path)} pre-verification")
    if not os.path.exists(path + ".manifest.json"):
        raise SwapRejected(
            f"swap candidate {mgr.root}/{track} has no commit manifest "
            "— the swap gate requires CRC-verifiable bytes (recommit "
            "with a current CheckpointManager)", cause="swap_corrupt")
    ok, detail = mgr.verify_track(track)
    if not ok:
        raise SwapRejected(
            f"swap candidate {mgr.root}/{track} failed the integrity "
            f"gate: {detail}", cause="swap_corrupt")

    model = create_model_from_config(mcfg)
    state = create_train_state(
        model, make_optimizer(cfg.optim), jax.random.key(0),
        (1, cfg.data.resize_size, cfg.data.resize_size, 3),
        ema=cfg.optim.ema_decay > 0)
    try:
        state, _, best = mgr.restore_exact(state, track)
    except Exception as e:
        # Verified bytes that still fail to restore (structure drift,
        # torn orbax metadata the CRC can't see): same typed refusal —
        # the candidate cannot reach traffic either way.
        raise SwapRejected(
            f"swap candidate {mgr.root}/{track} failed to restore: "
            f"{type(e).__name__}: {e}", cause="swap_corrupt") from e
    loaded = mgr.last_restore_loaded
    if loaded is not None and loaded[0] < loaded[1]:
        raise ValueError(
            f"swap candidate {mgr.root}/{track} restored only "
            f"{loaded[0]}/{loaded[1]} leaves into model '{mcfg.name}' — "
            "wrong model/num_classes for this checkpoint")
    variables = {"params": state.inference_params,
                 "batch_stats": state.batch_stats}
    digest = variables_digest(variables)
    log(f"[swap] candidate {mcfg.name}/{track} verified "
        f"({detail}; best {best:.2f}, digest {digest})")
    return model, jax.device_put(variables), digest
