"""Torch state_dict -> Flax pytree checkpoint converter.

The reference saves ``{'epoch', 'best_score', 'state_dict'}`` with DDP's
``module.`` prefix (train.py:177-179), where the model is
``Classifier(name, num_classes)`` — a torchvision backbone whose ``fc`` was
replaced by a 4-layer MLP (``fc.0/2/4/6`` Linear indices of the Sequential at
nn/classifier.py:26-34), all hung off an ``encoder`` attribute
(nn/classifier.py:11-27). This module converts those checkpoints — or plain
torchvision ``resnet{18,34,50,101}`` state_dicts — into this framework's
``{'params': ..., 'batch_stats': ...}`` trees so pretrained-weight parity can
be verified (SURVEY.md §7 "Checkpoint compatibility").

Layout translation rules (torch -> flax):

- conv weight  OIHW -> HWIO  (transpose 2,3,1,0)
- linear weight (out,in) -> kernel (in,out)  (transpose)
- BatchNorm  weight/bias/running_mean/running_var ->
  scale/bias (params) + mean/var (batch_stats); num_batches_tracked dropped.

Name translation (torchvision resnet -> tpuic ResNet, see models/resnet.py):

- ``conv1``/``bn1`` stem keep their names
- ``layer{s}.{i}.<leaf>`` -> ``layer{s}_{i}/<leaf>``
- ``layer{s}.{i}.downsample.0`` -> ``downsample_conv``; ``.downsample.1`` ->
  ``downsample_bn``
- ``fc.0/2/4/6`` (the reference's MLP head) -> ``head/fc0,fc1,fc2,out``;
  a plain torchvision ``fc`` (single Linear) -> ``head/out`` when shapes fit.

The output trees are plain nested dicts compatible with
``tpuic.checkpoint.manager.lenient_restore`` — unmapped or shape-mismatched
leaves are simply absent and fall back to the fresh initialization, matching
the reference's lenient partial load (train.py:143-148).
"""

from __future__ import annotations

import os
import re
from typing import Any, Dict, Mapping, Tuple

import numpy as np


def _set(tree: Dict, path: Tuple[str, ...], value: np.ndarray) -> None:
    d = tree
    for k in path[:-1]:
        d = d.setdefault(k, {})
    d[path[-1]] = value


def strip_prefixes(state_dict: Mapping[str, Any]) -> Dict[str, np.ndarray]:
    """Drop DDP's ``module.`` and the reference's ``encoder.`` wrappers."""
    out = {}
    for k, v in state_dict.items():
        for pre in ("module.", "encoder."):
            if k.startswith(pre):
                k = k[len(pre):]
        out[k] = np.asarray(v.detach().cpu().numpy()
                            if hasattr(v, "detach") else v)
    return out


def _conv(w: np.ndarray) -> np.ndarray:
    return np.transpose(w, (2, 3, 1, 0))  # OIHW -> HWIO


def _linear(w: np.ndarray) -> np.ndarray:
    return np.transpose(w)  # (out, in) -> (in, out)


# torchvision resnet leaf name within a block -> tpuic module name
_RESNET_LEAF = {
    "conv1": "conv1", "conv2": "conv2", "conv3": "conv3",
    "bn1": "bn1", "bn2": "bn2", "bn3": "bn3",
    "downsample.0": "downsample_conv", "downsample.1": "downsample_bn",
}

def _head_fc_mapping(keys) -> Dict[str, str]:
    """Sequential Linear index -> tpuic head module, derived from the
    checkpoint's own ``fc.N.*`` keys: hidden layers in order become
    fc0..fcK-1, the LAST Linear is 'out'. For the reference head
    (nn/classifier.py:26-34) this yields {0: fc0, 2: fc1, 4: fc2, 6: out};
    nonstandard head_widths (any even-index spacing) map consistently, so
    export -> convert round-trips for every head shape."""
    idxs = sorted({int(m.group(1)) for k in keys
                   if (m := re.match(r"(?:.*\.)?fc\.(\d+)\.(weight|bias)$",
                                     k))})
    return {str(i): (f"fc{n}" if n < len(idxs) - 1 else "out")
            for n, i in enumerate(idxs)}

_BLOCK_RE = re.compile(r"^layer(\d+)\.(\d+)\.(.+)$")


def convert_resnet(state_dict: Mapping[str, Any],
                   backbone_scope: str = "backbone",
                   head_scope: str = "head") -> Dict[str, Dict]:
    """Convert a torchvision-style resnet (or reference Classifier-over-resnet)
    state_dict into ``{'params': ..., 'batch_stats': ...}`` nested dicts.

    Unknown keys are skipped (collected in the returned tree under no path);
    use ``lenient_restore`` to merge into a live model state.
    """
    sd = strip_prefixes(state_dict)
    fc_map = _head_fc_mapping(sd)
    params: Dict = {}
    stats: Dict = {}

    def put_bn(scope: Tuple[str, ...], leaf: str, v: np.ndarray) -> None:
        if leaf == "weight":
            _set(params, scope + ("scale",), v)
        elif leaf == "bias":
            _set(params, scope + ("bias",), v)
        elif leaf == "running_mean":
            _set(stats, scope + ("mean",), v)
        elif leaf == "running_var":
            _set(stats, scope + ("var",), v)
        # num_batches_tracked intentionally dropped

    for key, v in sd.items():
        parts = key.rsplit(".", 1)
        if len(parts) != 2:
            continue
        name, leaf = parts

        # -- stem ------------------------------------------------------------
        if name == "conv1" and leaf == "weight":
            _set(params, (backbone_scope, "conv1", "kernel"), _conv(v))
            continue
        if name == "bn1":
            put_bn((backbone_scope, "bn1"), leaf, v)
            continue

        # -- stages ----------------------------------------------------------
        m = _BLOCK_RE.match(name)
        if m:
            stage, block, inner = m.group(1), m.group(2), m.group(3)
            mod = _RESNET_LEAF.get(inner)
            if mod is None:
                continue
            scope = (backbone_scope, f"layer{stage}_{block}", mod)
            if mod.startswith("conv") or mod == "downsample_conv":
                if leaf == "weight":
                    _set(params, scope + ("kernel",), _conv(v))
            else:
                put_bn(scope, leaf, v)
            continue

        # -- head ------------------------------------------------------------
        _put_head_fc(params, name, leaf, v, head_scope, fc_map)

    return {"params": params, "batch_stats": stats}


# ---------------------------------------------------------------------------
# Inception-v3 (torchvision naming; the reference's default backbone,
# nn/classifier.py:20-23). torchvision BasicConv2d children are `.conv`/`.bn`,
# exactly like tpuic's ConvBN (models/inception.py) — only block/branch names
# translate.
# ---------------------------------------------------------------------------

_INCEPTION_STEM = {
    "Conv2d_1a_3x3": "stem1", "Conv2d_2a_3x3": "stem2",
    "Conv2d_2b_3x3": "stem3", "Conv2d_3b_1x1": "stem4",
    "Conv2d_4a_3x3": "stem5",
}

# torchvision Mixed_* module -> inception block family (models/inception.py)
_INCEPTION_FAMILY = {
    "Mixed_5b": "A", "Mixed_5c": "A", "Mixed_5d": "A",
    "Mixed_6a": "B",
    "Mixed_6b": "C", "Mixed_6c": "C", "Mixed_6d": "C", "Mixed_6e": "C",
    "Mixed_7a": "D",
    "Mixed_7b": "E", "Mixed_7c": "E",
}

# per-family branch-name translation torchvision -> tpuic
_INCEPTION_BRANCH = {
    "A": {"branch1x1": "b1x1", "branch5x5_1": "b5_1", "branch5x5_2": "b5_2",
          "branch3x3dbl_1": "b3_1", "branch3x3dbl_2": "b3_2",
          "branch3x3dbl_3": "b3_3", "branch_pool": "bpool"},
    "B": {"branch3x3": "b3", "branch3x3dbl_1": "bd_1",
          "branch3x3dbl_2": "bd_2", "branch3x3dbl_3": "bd_3"},
    "C": {"branch1x1": "b1x1", "branch7x7_1": "b7_1", "branch7x7_2": "b7_2",
          "branch7x7_3": "b7_3", "branch7x7dbl_1": "bd_1",
          "branch7x7dbl_2": "bd_2", "branch7x7dbl_3": "bd_3",
          "branch7x7dbl_4": "bd_4", "branch7x7dbl_5": "bd_5",
          "branch_pool": "bpool"},
    "D": {"branch3x3_1": "b3_1", "branch3x3_2": "b3_2",
          "branch7x7x3_1": "b7_1", "branch7x7x3_2": "b7_2",
          "branch7x7x3_3": "b7_3", "branch7x7x3_4": "b7_4"},
    "E": {"branch1x1": "b1x1", "branch3x3_1": "b3_1",
          "branch3x3_2a": "b3_2a", "branch3x3_2b": "b3_2b",
          "branch3x3dbl_1": "bd_1", "branch3x3dbl_2": "bd_2",
          "branch3x3dbl_3a": "bd_3a", "branch3x3dbl_3b": "bd_3b",
          "branch_pool": "bpool"},
}


def _put_head_fc(params: Dict, name: str, leaf: str, v: np.ndarray,
                 head_scope: str, fc_map: Mapping[str, str]) -> bool:
    """Map an MLP head (``fc.N`` Sequential Linears, reference layout) or a
    plain single ``fc`` Linear onto the tpuic head scope. ``fc_map`` comes
    from ``_head_fc_mapping`` over the checkpoint's keys. Returns True when
    consumed."""
    if not (name == "fc" or name.startswith("fc.")):
        return False
    rest = name[2:].lstrip(".")
    target = fc_map.get(rest) if rest else "out"
    if target is None:
        return False
    if leaf == "weight":
        _set(params, (head_scope, target, "kernel"), _linear(v))
    elif leaf == "bias":
        _set(params, (head_scope, target, "bias"), v)
    return True


def convert_inception(state_dict: Mapping[str, Any],
                      backbone_scope: str = "backbone",
                      head_scope: str = "head") -> Dict[str, Dict]:
    """torchvision ``inception_v3`` (or reference Classifier-over-inception)
    state_dict -> ``{'params', 'batch_stats'}`` for tpuic InceptionV3.

    Covers the aux head (``AuxLogits.conv0/conv1/fc`` -> ``aux``), which the
    reference re-heads with a fresh Linear (nn/classifier.py:22-23). Unknown
    keys are skipped; merge with ``lenient_restore``.
    """
    sd = strip_prefixes(state_dict)
    fc_map = _head_fc_mapping(sd)
    params: Dict = {}
    stats: Dict = {}

    def put_convbn(scope: Tuple[str, ...], sub: str, leaf: str,
                   v: np.ndarray) -> None:
        if sub == "conv" and leaf == "weight":
            _set(params, scope + ("conv", "kernel"), _conv(v))
        elif sub == "bn":
            if leaf == "weight":
                _set(params, scope + ("bn", "scale"), v)
            elif leaf == "bias":
                _set(params, scope + ("bn", "bias"), v)
            elif leaf == "running_mean":
                _set(stats, scope + ("bn", "mean"), v)
            elif leaf == "running_var":
                _set(stats, scope + ("bn", "var"), v)

    for key, v in sd.items():
        parts = key.split(".")
        leaf = parts[-1]

        if parts[0] in _INCEPTION_STEM and len(parts) == 3:
            put_convbn((backbone_scope, _INCEPTION_STEM[parts[0]]),
                       parts[1], leaf, v)
            continue

        fam = _INCEPTION_FAMILY.get(parts[0])
        if fam is not None and len(parts) == 4:
            branch = _INCEPTION_BRANCH[fam].get(parts[1])
            if branch is None:
                continue
            put_convbn((backbone_scope, parts[0].lower().replace("_", ""),
                        branch), parts[2], leaf, v)
            continue

        if parts[0] == "AuxLogits":
            if parts[1] in ("conv0", "conv1") and len(parts) == 4:
                put_convbn((backbone_scope, "aux", parts[1]), parts[2],
                           leaf, v)
            elif parts[1] == "fc" and len(parts) == 3:
                if leaf == "weight":
                    _set(params, (backbone_scope, "aux", "fc", "kernel"),
                         _linear(v))
                elif leaf == "bias":
                    _set(params, (backbone_scope, "aux", "fc", "bias"), v)
            continue

        _put_head_fc(params, ".".join(parts[:-1]), leaf, v, head_scope,
                     fc_map)

    return {"params": params, "batch_stats": stats}


# ---------------------------------------------------------------------------
# EfficientNet (efficientnet_pytorch naming; reference nn/classifier.py:17-18
# — that branch is broken upstream, here the intended behavior works).
# ---------------------------------------------------------------------------

# block-internal leaf module translation efficientnet_pytorch -> tpuic MBConv
_EFFNET_BLOCK_CONV = {
    "_expand_conv": "expand_conv", "_depthwise_conv": "dw_conv",
    "_project_conv": "project_conv",
}
_EFFNET_BLOCK_BN = {"_bn0": "expand_bn", "_bn1": "dw_bn", "_bn2": "project_bn"}
_EFFNET_SE = {"_se_reduce": "reduce", "_se_expand": "expand"}


def _effnet_block_coords(variant: str):
    """Flat efficientnet_pytorch block index -> tpuic ``block{stage}_{rep}``."""
    from tpuic.models.efficientnet import (_BASE_BLOCKS, _SCALING,
                                           _round_repeats)
    _, depth_mult, _ = _SCALING[variant]
    coords = []
    for si, (_, _, repeats, _, _) in enumerate(_BASE_BLOCKS):
        for r in range(_round_repeats(repeats, depth_mult)):
            coords.append(f"block{si}_{r}")
    return coords


def detect_efficientnet_variant(state_dict: Mapping[str, Any]) -> str:
    """Infer b0..b7 from the checkpoint itself.

    The flat block count separates b0 (16) and b3 (26); b1 and b2 both have
    23 blocks, so they are disambiguated by the final block's projection
    width (320 vs 352 — width multipliers 1.0 vs 1.1)."""
    from tpuic.models.efficientnet import _SCALING, _round_filters

    sd = strip_prefixes(state_dict)
    idxs = [int(k.split(".")[1]) for k in sd if k.startswith("_blocks.")]
    if not idxs:
        raise ValueError("not an efficientnet_pytorch state_dict "
                         "(no _blocks.* keys)")
    n_blocks = max(idxs) + 1
    candidates = [v for v in _SCALING
                  if len(_effnet_block_coords(v)) == n_blocks]
    if not candidates:
        raise ValueError(f"no known efficientnet variant has {n_blocks} "
                         f"blocks (b0..b7 supported)")
    if len(candidates) > 1:
        proj = sd.get(f"_blocks.{n_blocks - 1}._project_conv.weight")
        if proj is not None:
            candidates = [v for v in candidates
                          if _round_filters(320, _SCALING[v][0])
                          == proj.shape[0]] or candidates
    return candidates[0]


def convert_efficientnet(state_dict: Mapping[str, Any], variant: str = "b3",
                         backbone_scope: str = "backbone",
                         head_scope: str = "head") -> Dict[str, Dict]:
    """efficientnet_pytorch state_dict -> ``{'params', 'batch_stats'}``.

    ``variant`` ('b0'..'b7') resolves the flat ``_blocks.{i}`` index into the
    tpuic ``block{stage}_{repeat}`` name (depth multiplier dependent). The
    package's ``_fc`` single Linear maps to ``head/out``; a reference-style
    MLP (``fc.0/2/4/6``) maps to the full head.
    """
    sd = strip_prefixes(state_dict)
    fc_map = _head_fc_mapping(sd)
    coords = _effnet_block_coords(variant)
    params: Dict = {}
    stats: Dict = {}

    def put_bn(scope: Tuple[str, ...], leaf: str, v: np.ndarray) -> None:
        if leaf == "weight":
            _set(params, scope + ("scale",), v)
        elif leaf == "bias":
            _set(params, scope + ("bias",), v)
        elif leaf == "running_mean":
            _set(stats, scope + ("mean",), v)
        elif leaf == "running_var":
            _set(stats, scope + ("var",), v)

    for key, v in sd.items():
        parts = key.split(".")
        leaf = parts[-1]

        if parts[0] == "_blocks" and len(parts) >= 4:
            idx = int(parts[1])
            if idx >= len(coords):
                continue
            block = coords[idx]
            mod = parts[2]
            if mod in _EFFNET_BLOCK_CONV and leaf == "weight":
                _set(params,
                     (backbone_scope, block, _EFFNET_BLOCK_CONV[mod],
                      "kernel"), _conv(v))
            elif mod in _EFFNET_BLOCK_BN:
                put_bn((backbone_scope, block, _EFFNET_BLOCK_BN[mod]),
                       leaf, v)
            elif mod in _EFFNET_SE:
                scope = (backbone_scope, block, "se", _EFFNET_SE[mod])
                if leaf == "weight":
                    _set(params, scope + ("kernel",), _conv(v))
                elif leaf == "bias":
                    _set(params, scope + ("bias",), v)
            continue

        if parts[0] == "_conv_stem" and leaf == "weight":
            _set(params, (backbone_scope, "stem_conv", "kernel"), _conv(v))
        elif parts[0] == "_bn0":
            put_bn((backbone_scope, "stem_bn"), leaf, v)
        elif parts[0] == "_conv_head" and leaf == "weight":
            _set(params, (backbone_scope, "head_conv", "kernel"), _conv(v))
        elif parts[0] == "_bn1":
            put_bn((backbone_scope, "head_bn"), leaf, v)
        elif parts[0] == "_fc":
            if leaf == "weight":
                _set(params, (head_scope, "out", "kernel"), _linear(v))
            elif leaf == "bias":
                _set(params, (head_scope, "out", "bias"), v)
        else:
            _put_head_fc(params, ".".join(parts[:-1]), leaf, v, head_scope,
                     fc_map)

    return {"params": params, "batch_stats": stats}


# ---------------------------------------------------------------------------
# Dispatch
# ---------------------------------------------------------------------------

# ---------------------------------------------------------------------------
# ViT (torchvision vision_transformer naming: vit_b_16 / vit_l_16 / ...)
# ---------------------------------------------------------------------------

# torchvision encoder-block leaf -> (tpuic module path, is_layernorm)
_VIT_LN = {"ln_1": "ln1", "ln_2": "ln2"}
# both torchvision MLP namings: >=0.12 Sequential indices, older linear_N
_VIT_MLP = {"mlp.0": "mlp_up", "mlp.3": "mlp_down",
            "mlp.linear_1": "mlp_up", "mlp.linear_2": "mlp_down"}

_VIT_LAYER_RE = re.compile(r"^layers\.encoder_layer_(\d+)\.(.+)$")


def convert_vit(state_dict: Mapping[str, Any],
                backbone_scope: str = "backbone",
                head_scope: str = "head") -> Dict[str, Dict]:
    """torchvision ``vit_{b,l}_16``-style state_dict -> tpuic ViT trees.

    Key facts of the mapping (torchvision VisionTransformer):
    - ``conv_proj`` is the patch embedding (OIHW -> HWIO);
    - ``class_token``/``encoder.pos_embedding`` carry the same
      (cls-first, row-major patches) layout as tpuic's ``cls``/``pos_embed``;
    - ``self_attention`` is ``nn.MultiheadAttention``: ``in_proj_weight``
      is the stacked [3D, D] with rows [q; k; v] — its transpose is exactly
      tpuic's fused ``qkv`` kernel [D, 3D] (models/vit.py splits columns in
      q,k,v order, and both sides split heads contiguously);
    - ``encoder.ln`` is the final LayerNorm (-> ``ln_final``);
    - ``heads.head`` maps onto the tpuic head scope (a single Linear lands
      on 'out' and is shape-skipped by lenient_restore unless it matches —
      the reference's re-head semantics; an MLP-head Sequential maps fully).
    ViT has no BatchNorm: ``batch_stats`` is returned empty.
    """
    # ViT keys legitimately carry an inner 'encoder.' scope
    # (encoder.pos_embedding, encoder.layers...). strip_prefixes removes
    # ONE leading wrapper per kind, so a reference-wrapped checkpoint
    # ('module.encoder.' + torchvision keys) still leaves that inner scope
    # on some keys — normalize it off here.
    sd = {}
    for k, v in strip_prefixes(state_dict).items():
        if k.startswith("encoder."):
            k = k[len("encoder."):]
        sd[k] = v
    head_keys = {k[len("heads.head."):]: k for k in sd
                 if k.startswith("heads.head.")}
    # Sequential head indices -> fc0..fcK-1/out (same rule as
    # _head_fc_mapping, derived from the head's own Linear indices).
    idxs = sorted({int(m.group(1)) for k in head_keys
                   if (m := re.match(r"(\d+)\.(weight|bias)$", k))})
    head_map = {str(i): (f"fc{n}" if n < len(idxs) - 1 else "out")
                for n, i in enumerate(idxs)}
    params: Dict = {}

    def put_ln(scope: Tuple[str, ...], leaf: str, v: np.ndarray) -> None:
        if leaf == "weight":
            _set(params, scope + ("scale",), v)
        elif leaf == "bias":
            _set(params, scope + ("bias",), v)

    for key, v in sd.items():
        if key == "class_token":
            _set(params, (backbone_scope, "cls"), v)
            continue
        if key == "conv_proj.weight":
            _set(params, (backbone_scope, "patch_embed", "kernel"), _conv(v))
            continue
        if key == "conv_proj.bias":
            _set(params, (backbone_scope, "patch_embed", "bias"), v)
            continue
        if key == "pos_embedding":
            _set(params, (backbone_scope, "pos_embed"), v)
            continue
        if key in ("ln.weight", "ln.bias"):
            put_ln((backbone_scope, "ln_final"), key.split(".")[1], v)
            continue
        m = _VIT_LAYER_RE.match(key)
        if m:
            block = (backbone_scope, f"block{m.group(1)}")
            inner, leaf = m.group(2).rsplit(".", 1)
            if inner in _VIT_LN:
                put_ln(block + (_VIT_LN[inner],), leaf, v)
            elif inner == "self_attention" and leaf == "in_proj_weight":
                _set(params, block + ("attn", "qkv", "kernel"), _linear(v))
            elif inner == "self_attention" and leaf == "in_proj_bias":
                _set(params, block + ("attn", "qkv", "bias"), v)
            elif inner == "self_attention.out_proj":
                if leaf == "weight":
                    _set(params, block + ("attn", "out", "kernel"),
                         _linear(v))
                elif leaf == "bias":
                    _set(params, block + ("attn", "out", "bias"), v)
            elif inner in _VIT_MLP:
                if leaf == "weight":
                    _set(params, block + (_VIT_MLP[inner], "kernel"),
                         _linear(v))
                elif leaf == "bias":
                    _set(params, block + (_VIT_MLP[inner], "bias"), v)
            continue
        if key.startswith("heads.head."):
            rest = key[len("heads.head."):]
            parts = rest.rsplit(".", 1)
            if len(parts) == 1:  # bare heads.head.{weight,bias}: one Linear
                target, leaf = "out", parts[0]
            else:
                target, leaf = head_map.get(parts[0]), parts[1]
            if target is None:
                continue
            if leaf == "weight":
                _set(params, (head_scope, target, "kernel"), _linear(v))
            elif leaf == "bias":
                _set(params, (head_scope, target, "bias"), v)

    return {"params": params, "batch_stats": {}}


def detect_vit_variant(state_dict: Mapping[str, Any]) -> str:
    """tpuic model name from the patch-embedding shape [D, 3, p, p]."""
    sd = strip_prefixes(state_dict)
    w = sd.get("conv_proj.weight")
    if w is None:
        raise ValueError("no conv_proj.weight in state_dict")
    hidden, _, patch, _ = w.shape
    names = {(768, 16): "vit-b16", (1024, 16): "vit-l16",
             (768, 32): "vit-b32", (1024, 32): "vit-l32",
             (384, 16): "vit-s16", (64, 4): "vit-tiny"}
    name = names.get((int(hidden), int(patch)))
    if name is None:
        raise ValueError(
            f"no tpuic ViT for hidden={hidden}, patch={patch} "
            f"(supported: {sorted(names.values())})")
    return name


def detect_arch(state_dict: Mapping[str, Any]) -> str:
    """Sniff the backbone family from state_dict key shapes."""
    for k in state_dict:
        k = k.replace("module.", "").replace("encoder.", "")
        if k.startswith("Mixed_") or k.startswith("Conv2d_1a"):
            return "inceptionv3"
        if k.startswith("_blocks.") or k.startswith("_conv_stem"):
            return "efficientnet"
        if k == "class_token" or k.startswith("conv_proj."):
            return "vit"
        if k.startswith("layer1.") or k == "conv1.weight":
            return "resnet"
    raise ValueError("could not detect backbone family from state_dict keys")


def detect_resnet_depth(state_dict: Mapping[str, Any]) -> str:
    """'resnet{18,34,50,101,152}' from block kind + layer3 block count."""
    flat = strip_prefixes(state_dict)
    bottleneck = any(k.startswith("layer1.0.conv3") for k in flat)
    blocks = {int(m.group(1)) for k in flat
              if (m := re.match(r"layer3\.(\d+)\.", k))}
    n3 = (max(blocks) + 1) if blocks else 0
    if bottleneck:
        if n3 >= 36:
            return "resnet152"
        return "resnet101" if n3 >= 23 else "resnet50"
    return "resnet34" if n3 >= 6 else "resnet18"


def convert_state_dict(state_dict: Mapping[str, Any],
                       arch: str = "auto", **kw) -> Dict[str, Dict]:
    """Convert any supported torch state_dict to tpuic trees.

    ``arch``: 'auto' | 'resnet*' | 'inceptionv3' | 'efficientnet-b{0..7}'
    | 'vit*'.
    """
    if arch == "auto":
        arch = detect_arch(state_dict)
    if arch.startswith("resnet"):
        return convert_resnet(state_dict, **kw)
    if arch.startswith("inception"):
        return convert_inception(state_dict, **kw)
    if arch.startswith("vit"):
        return convert_vit(state_dict, **kw)
    if arch.startswith("efficientnet"):
        # Bare 'efficientnet' (from auto-detection): the variant is derivable
        # from the checkpoint — guessing one would silently mis-key every
        # block and lenient_restore would skip the whole backbone.
        variant = (arch.rsplit("-", 1)[-1] if "-" in arch
                   else detect_efficientnet_variant(state_dict))
        return convert_efficientnet(state_dict, variant=variant, **kw)
    raise ValueError(f"unsupported arch '{arch}'")


def load_reference_checkpoint(path: str) -> Dict[str, Any]:
    """Load a reference ``torch.save`` checkpoint file (train.py:177-179).

    Returns ``{'epoch': int, 'best_score': float, 'state_dict': {...}}``; a
    bare state_dict file is wrapped with epoch=0/best_score=0.0.
    """
    import torch  # deferred: torch is only needed on the conversion path

    # weights_only: the payload is tensors + scalars; never unpickle code.
    payload = torch.load(path, map_location="cpu", weights_only=True)
    if isinstance(payload, dict) and "state_dict" in payload:
        return {"epoch": int(payload.get("epoch", 0)),
                "best_score": float(payload.get("best_score", 0.0)),
                "state_dict": payload["state_dict"]}
    return {"epoch": 0, "best_score": 0.0, "state_dict": payload}


def convert_reference_checkpoint(path: str,
                                 arch: str = "auto") -> Dict[str, Any]:
    """File -> ``{'params', 'batch_stats', 'epoch', 'best_score'}``."""
    payload = load_reference_checkpoint(path)
    tree = convert_state_dict(payload["state_dict"], arch=arch)
    tree["epoch"] = payload["epoch"]
    tree["best_score"] = payload["best_score"]
    return tree


def interpolate_pos_embed(pos: np.ndarray, n_target: int) -> np.ndarray:
    """Resize a ViT position embedding [1, N, D] to ``n_target`` tokens.

    Standard fine-tune-at-a-new-resolution recipe (the DeiT/ViT papers'
    bicubic resize, done bilinearly here): the cls row passes through and
    the patch grid is resized as a 2D image. Both token counts must be
    cls + a square grid."""
    import jax

    n_src = pos.shape[1]
    if n_src == n_target:
        return pos
    g_src = int(round((n_src - 1) ** 0.5))
    g_dst = int(round((n_target - 1) ** 0.5))
    if g_src * g_src + 1 != n_src or g_dst * g_dst + 1 != n_target:
        raise ValueError(f"non-square token grids: {n_src} -> {n_target}")
    cls, grid = pos[:, :1], pos[:, 1:]
    d = pos.shape[-1]
    grid = grid.reshape(1, g_src, g_src, d)
    grid = np.asarray(jax.image.resize(grid, (1, g_dst, g_dst, d),
                                       method="bilinear"))
    return np.concatenate([cls, grid.reshape(1, g_dst * g_dst, d)], axis=1)


def init_state_from_torch(state, path: str, model_name: str, log=print):
    """Convert a torch checkpoint and leniently merge it into ``state``.

    The shared --init-from path (Trainer and tpuic.predict): family
    auto-detected, unmapped/mismatched leaves keep their fresh init —
    the reference's partial load semantics (train.py:143-148). For
    ``*-s2d`` models a pretrained 7x7 stem kernel is re-indexed to the
    space-to-depth layout (models/resnet.py:s2d_stem_kernel) before the
    merge, since lenient_restore would otherwise shape-skip it silently.
    For ViT models whose training size differs from the checkpoint's, the
    position embedding is grid-interpolated (``interpolate_pos_embed``) —
    the reference trains at 299px while torchvision ViTs ship at 224px,
    and a shape-skip here would silently leave RANDOM pos embeddings.
    """
    import jax

    from tpuic.checkpoint.manager import lenient_restore

    tree = convert_reference_checkpoint(path)
    pe = tree.get("params", {}).get("backbone", {}).get("pos_embed")
    if model_name.startswith("vit") and pe is not None:
        live = state.params.get("backbone", {}).get("pos_embed")
        live = getattr(live, "value", live)
        shape = getattr(live, "shape", (1, 0, 0))
        n_target = shape[1]
        # Hidden dims must already agree — a cross-width checkpoint
        # (vit-b16 into vit-tiny) is NOT mergeable; interpolating it would
        # log success while lenient_restore still shape-skips the leaf.
        if (n_target and n_target != pe.shape[1]
                and shape[-1] == pe.shape[-1]):
            tree["params"]["backbone"]["pos_embed"] = interpolate_pos_embed(
                np.asarray(pe), n_target)
            log(f"[init] {path}: pos_embed interpolated "
                f"{pe.shape[1]} -> {n_target} tokens")
    if model_name.endswith("-s2d"):
        from tpuic.models.resnet import s2d_stem_kernel
        conv1 = tree.get("params", {}).get("backbone", {}).get("conv1")
        kshape = getattr((conv1 or {}).get("kernel"), "shape", None)
        if kshape is not None and kshape[0] == 7:
            conv1["kernel"] = np.asarray(
                s2d_stem_kernel(np.asarray(conv1["kernel"])))
        else:
            log(f"[init] {path}: no 7x7 stem kernel to convert for "
                f"{model_name} (found {kshape}); stem keeps fresh init")
    params, n, total = lenient_restore(
        jax.tree.map(np.asarray, jax.device_get(state.params)),
        tree["params"])
    stats, n_s, total_s = lenient_restore(
        jax.tree.map(np.asarray, jax.device_get(state.batch_stats)),
        tree["batch_stats"])
    log(f"[init] {path}: loaded {n}/{total} param and "
        f"{n_s}/{total_s} batch-stat leaves")
    state = state.replace(params=params, batch_stats=stats)
    if state.ema_params is not None:
        # Reseed the EMA at the merged (pretrained) weights — leaving it
        # at the random init would have validation score a near-random
        # network for ~1/(1-d) updates.
        state = state.replace(
            ema_params=jax.tree.map(np.copy, params))
    return state


# ---------------------------------------------------------------------------
# Inverse direction: tpuic Flax trees -> torch state_dict (resnet + inception families)
# ---------------------------------------------------------------------------

def _unbox(leaf):
    return np.asarray(getattr(leaf, "value", leaf))


def _conv_inv(w) -> np.ndarray:
    return np.transpose(_unbox(w), (3, 2, 0, 1))  # HWIO -> OIHW


_RESNET_LEAF_INV = {v: k for k, v in _RESNET_LEAF.items()}


def _put_bn_inv(sd: Dict[str, np.ndarray], tname: str,
                p: Mapping, s: Mapping) -> None:
    """Emit one BatchNorm's torch keys from tpuic params/stats subtrees
    (shared by every exporter; num_batches_tracked re-synthesized as 0)."""
    sd[f"{tname}.weight"] = _unbox(p["scale"])
    sd[f"{tname}.bias"] = _unbox(p["bias"])
    sd[f"{tname}.running_mean"] = _unbox(s["mean"])
    sd[f"{tname}.running_var"] = _unbox(s["var"])
    sd[f"{tname}.num_batches_tracked"] = np.asarray(0, np.int64)


def _export_head(head: Mapping[str, Any]) -> Dict[str, np.ndarray]:
    """tpuic head/{fc0..,out} -> fc.{0,2,4,...} Sequential keys (ReLUs take
    the odd slots), or the plain torchvision 'fc' for a single Linear."""
    sd: Dict[str, np.ndarray] = {}
    fcs = sorted((m for m in head if re.fullmatch(r"fc\d+", m)),
                 key=lambda m: int(m[2:]))
    for i, mod in enumerate(fcs):
        sd[f"fc.{2 * i}.weight"] = np.transpose(_unbox(head[mod]["kernel"]))
        sd[f"fc.{2 * i}.bias"] = _unbox(head[mod]["bias"])
    if "out" in head:
        out_name = f"fc.{2 * len(fcs)}" if fcs else "fc"
        sd[f"{out_name}.weight"] = np.transpose(_unbox(head["out"]["kernel"]))
        sd[f"{out_name}.bias"] = _unbox(head["out"]["bias"])
    return sd


def export_resnet(params: Mapping[str, Any], batch_stats: Mapping[str, Any],
                  prefix: str = "module.encoder.") -> Dict[str, np.ndarray]:
    """tpuic resnet {'params','batch_stats'} -> reference-layout state_dict.

    The exact inverse of ``convert_resnet`` (HWIO->OIHW convs, transposed
    linears, scale/bias->weight/bias, mean/var->running_mean/running_var,
    with num_batches_tracked=0 re-synthesized and DDP's ``module.encoder.``
    prefix re-applied by default — reference train.py:179 saves it). Lets a
    tpuic-trained model flow back into the reference's resume path
    (train.py:132-150) or any torchvision consumer.
    """
    bb = params.get("backbone", {})
    bs = batch_stats.get("backbone", {})
    head = params.get("head", {})
    if not any(n.startswith("layer") for n in bb):
        raise ValueError(
            "export_resnet: params['backbone'] has no 'layer*' modules — "
            f"not a resnet checkpoint (got {sorted(bb)[:6]}...); only the "
            "resnet family exports to the torch layout")
    sd: Dict[str, np.ndarray] = {}
    put_bn = lambda tname, p, s: _put_bn_inv(sd, tname, p, s)  # noqa: E731

    for name, sub in bb.items():
        if name == "conv1":
            sd["conv1.weight"] = _conv_inv(sub["kernel"])
        elif name == "bn1":
            put_bn("bn1", sub, bs["bn1"])
        elif name.startswith("layer"):
            stage, block = name[len("layer"):].split("_")
            for mod, leaves in sub.items():
                torch_mod = _RESNET_LEAF_INV.get(mod)
                if torch_mod is None:
                    continue
                tname = f"layer{stage}.{block}.{torch_mod}"
                if "kernel" in leaves:
                    sd[f"{tname}.weight"] = _conv_inv(leaves["kernel"])
                else:
                    put_bn(tname, leaves, bs[name][mod])
    sd.update(_export_head(head))
    return {prefix + k: v for k, v in sd.items()}


def export_inception(params: Mapping[str, Any],
                     batch_stats: Mapping[str, Any],
                     prefix: str = "module.encoder.") -> Dict[str, np.ndarray]:
    """tpuic InceptionV3 trees -> torchvision-layout state_dict (incl. the
    aux head) — the inverse of ``convert_inception``, covering the
    reference's DEFAULT backbone (train.py:122)."""
    bb = params.get("backbone", {})
    bs = batch_stats.get("backbone", {})
    if "mixed5b" not in bb:
        raise ValueError(
            "export_inception: params['backbone'] has no 'mixed5b' — not an "
            f"inception checkpoint (got {sorted(bb)[:6]}...)")
    stem_inv = {v: k for k, v in _INCEPTION_STEM.items()}
    block_inv = {k.lower().replace("_", ""): k for k in _INCEPTION_FAMILY}
    branch_inv = {fam: {v: k for k, v in m.items()}
                  for fam, m in _INCEPTION_BRANCH.items()}
    sd: Dict[str, np.ndarray] = {}

    def put_convbn(tname: str, p: Mapping, s: Mapping) -> None:
        sd[f"{tname}.conv.weight"] = _conv_inv(p["conv"]["kernel"])
        _put_bn_inv(sd, f"{tname}.bn", p["bn"], s["bn"])

    for name, sub in bb.items():
        if name in stem_inv:
            put_convbn(stem_inv[name], sub, bs[name])
        elif name in block_inv:
            tblock = block_inv[name]
            fam = _INCEPTION_FAMILY[tblock]
            for br, leaves in sub.items():
                tbranch = branch_inv[fam].get(br)
                if tbranch is not None:
                    put_convbn(f"{tblock}.{tbranch}", leaves, bs[name][br])
        elif name == "aux":
            for conv in ("conv0", "conv1"):
                if conv in sub:
                    put_convbn(f"AuxLogits.{conv}", sub[conv],
                               bs["aux"][conv])
            if "fc" in sub:
                sd["AuxLogits.fc.weight"] = np.transpose(
                    _unbox(sub["fc"]["kernel"]))
                sd["AuxLogits.fc.bias"] = _unbox(sub["fc"]["bias"])
    sd.update(_export_head(params.get("head", {})))
    return {prefix + k: v for k, v in sd.items()}


def export_efficientnet(params: Mapping[str, Any],
                        batch_stats: Mapping[str, Any],
                        prefix: str = "module.encoder."
                        ) -> Dict[str, np.ndarray]:
    """tpuic EfficientNet trees -> efficientnet_pytorch-layout state_dict.

    The inverse of ``convert_efficientnet``. The flat ``_blocks.{i}`` index
    is reconstructed by enumerating ``block{stage}_{repeat}`` names in
    (stage, repeat) order, so no variant knowledge is needed — any b0..b7
    tree round-trips. A single-Linear head exports as the package's
    ``_fc``; a reference-style MLP head exports as ``fc.{0,2,...}``.
    """
    bb = params.get("backbone", {})
    bs = batch_stats.get("backbone", {})
    if "stem_conv" not in bb or not any(n.startswith("block") for n in bb):
        raise ValueError(
            "export_efficientnet: params['backbone'] has no stem_conv/"
            f"block* modules — not an efficientnet checkpoint "
            f"(got {sorted(bb)[:6]}...)")
    conv_inv = {v: k for k, v in _EFFNET_BLOCK_CONV.items()}
    bn_inv = {v: k for k, v in _EFFNET_BLOCK_BN.items()}
    se_inv = {v: k for k, v in _EFFNET_SE.items()}
    sd: Dict[str, np.ndarray] = {}
    put_bn = lambda tname, p, s: _put_bn_inv(sd, tname, p, s)  # noqa: E731

    def coord_key(name: str) -> Tuple[int, int]:
        stage, rep = name[len("block"):].split("_")
        return int(stage), int(rep)

    blocks = sorted((n for n in bb if re.fullmatch(r"block\d+_\d+", n)),
                    key=coord_key)
    for i, name in enumerate(blocks):
        sub, stats = bb[name], bs.get(name, {})
        for mod, leaves in sub.items():
            if mod in conv_inv:
                sd[f"_blocks.{i}.{conv_inv[mod]}.weight"] = _conv_inv(
                    leaves["kernel"])
            elif mod in bn_inv:
                put_bn(f"_blocks.{i}.{bn_inv[mod]}", leaves, stats[mod])
            elif mod == "se":
                for part, tpart in se_inv.items():
                    sd[f"_blocks.{i}.{tpart}.weight"] = _conv_inv(
                        leaves[part]["kernel"])
                    sd[f"_blocks.{i}.{tpart}.bias"] = _unbox(
                        leaves[part]["bias"])
    sd["_conv_stem.weight"] = _conv_inv(bb["stem_conv"]["kernel"])
    put_bn("_bn0", bb["stem_bn"], bs["stem_bn"])
    sd["_conv_head.weight"] = _conv_inv(bb["head_conv"]["kernel"])
    put_bn("_bn1", bb["head_bn"], bs["head_bn"])
    head = params.get("head", {})
    if any(re.fullmatch(r"fc\d+", m) for m in head):
        sd.update(_export_head(head))      # reference MLP -> fc.{0,2,...}
    elif "out" in head:
        sd["_fc.weight"] = np.transpose(_unbox(head["out"]["kernel"]))
        sd["_fc.bias"] = _unbox(head["out"]["bias"])
    return {prefix + k: v for k, v in sd.items()}


def export_vit(params: Mapping[str, Any],
               batch_stats: Mapping[str, Any],
               prefix: str = "module.encoder.") -> Dict[str, np.ndarray]:
    """tpuic ViT trees -> torchvision vision_transformer-layout state_dict —
    the inverse of ``convert_vit`` (current >=0.12 ``mlp.{0,3}`` naming).
    ``batch_stats`` is accepted for dispatch symmetry; ViT has none."""
    del batch_stats
    bb = params.get("backbone", {})
    if "patch_embed" not in bb:
        raise ValueError(
            "export_vit: params['backbone'] has no 'patch_embed' — not a "
            f"ViT checkpoint (got {sorted(bb)[:6]}...)")
    sd: Dict[str, np.ndarray] = {}
    sd["class_token"] = _unbox(bb["cls"])
    sd["conv_proj.weight"] = _conv_inv(bb["patch_embed"]["kernel"])
    sd["conv_proj.bias"] = _unbox(bb["patch_embed"]["bias"])
    sd["encoder.pos_embedding"] = _unbox(bb["pos_embed"])
    sd["encoder.ln.weight"] = _unbox(bb["ln_final"]["scale"])
    sd["encoder.ln.bias"] = _unbox(bb["ln_final"]["bias"])
    ln_inv = {v: k for k, v in _VIT_LN.items()}
    mlp_inv = {"mlp_up": "mlp.0", "mlp_down": "mlp.3"}
    for name, sub in bb.items():
        if not name.startswith("block"):
            continue
        if "moe" in sub:
            # Switch-MoE experts/router have no torchvision layout —
            # exporting would silently drop every MoE MLP.
            raise ValueError(
                f"export_vit: {name} contains a Switch-MoE MLP; MoE ViTs "
                "(vit-*-moe) have no torch export target")
        t = f"encoder.layers.encoder_layer_{name[len('block'):]}"
        for mod, leaves in sub.items():
            if mod in ln_inv:
                sd[f"{t}.{ln_inv[mod]}.weight"] = _unbox(leaves["scale"])
                sd[f"{t}.{ln_inv[mod]}.bias"] = _unbox(leaves["bias"])
            elif mod == "attn":
                sd[f"{t}.self_attention.in_proj_weight"] = np.transpose(
                    _unbox(leaves["qkv"]["kernel"]))
                sd[f"{t}.self_attention.in_proj_bias"] = _unbox(
                    leaves["qkv"]["bias"])
                sd[f"{t}.self_attention.out_proj.weight"] = np.transpose(
                    _unbox(leaves["out"]["kernel"]))
                sd[f"{t}.self_attention.out_proj.bias"] = _unbox(
                    leaves["out"]["bias"])
            elif mod in mlp_inv:
                sd[f"{t}.{mlp_inv[mod]}.weight"] = np.transpose(
                    _unbox(leaves["kernel"]))
                sd[f"{t}.{mlp_inv[mod]}.bias"] = _unbox(leaves["bias"])
    # Head: _export_head emits fc.* keys ('fc.N.*' for the MLP Sequential,
    # bare 'fc.weight' for one Linear); torchvision's scope is heads.head.
    for k, v in _export_head(params.get("head", {})).items():
        sd["heads.head." + k[len("fc."):]] = v
    return {prefix + k: v for k, v in sd.items()}


def export_state_dict(params: Mapping[str, Any],
                      batch_stats: Mapping[str, Any],
                      prefix: str = "module.encoder.") -> Dict[str, np.ndarray]:
    """Auto-dispatch tpuic->torch export by sniffing the backbone tree."""
    bb = params.get("backbone", {})
    if any(n.startswith("layer") for n in bb):
        return export_resnet(params, batch_stats, prefix)
    if "mixed5b" in bb:
        return export_inception(params, batch_stats, prefix)
    if "stem_conv" in bb:
        return export_efficientnet(params, batch_stats, prefix)
    if "patch_embed" in bb:
        return export_vit(params, batch_stats, prefix)
    raise ValueError(
        "export_state_dict: unsupported backbone for torch export "
        f"(got {sorted(bb)[:6]}...); supported: resnet*, inceptionv3, "
        "efficientnet-b*, vit*")


# ---------------------------------------------------------------------------
# CLI:  python -m tpuic.checkpoint.torch_convert <ckpt> [--verify]
# ---------------------------------------------------------------------------

def _infer_head(state_dict: Mapping[str, Any]) -> Tuple[int, bool]:
    """(num_classes, mlp_head) from the checkpoint's own head keys."""
    flat = strip_prefixes(state_dict)
    fc_map = _head_fc_mapping(flat)
    out_idx = next((i for i, t in fc_map.items() if t == "out"), None)
    if out_idx is not None:       # Sequential MLP head (reference layout)
        return int(flat[f"fc.{out_idx}.bias"].shape[0]), len(fc_map) > 1
    for k in ("fc.bias", "_fc.bias"):   # plain torchvision / effnet _fc
        if k in flat:
            return int(flat[k].shape[0]), False
    # ViT scope (torchvision heads.head): Sequential MLP or one Linear.
    hh = sorted(int(m.group(1)) for k in flat
                if (m := re.match(r"heads\.head\.(\d+)\.bias$", k)))
    if hh:
        return int(flat[f"heads.head.{hh[-1]}.bias"].shape[0]), len(hh) > 1
    if "heads.head.bias" in flat:
        return int(flat["heads.head.bias"].shape[0]), False
    raise ValueError("cannot infer num_classes: no fc head keys found")


def main(argv=None) -> int:
    """Convert a reference/torchvision checkpoint; optionally verify parity.

    ``--verify`` loads the checkpoint BOTH into a bare-torch replica of the
    reference Classifier (tpuic/checkpoint/torch_ref.py — exact upstream
    module naming) and, through the converter + lenient restore, into the
    tpuic Flax model, then prints the max |Δlogits| on random inputs
    (SURVEY.md §7 "Checkpoint compatibility"; reference train.py:177-179).
    """
    import argparse
    import json

    ap = argparse.ArgumentParser(
        prog="python -m tpuic.checkpoint.torch_convert", description=__doc__)
    ap.add_argument("checkpoint", help="reference best_model/latest_model "
                    "file or a bare torch state_dict file; with "
                    "--export-torch, a tpuic Orbax checkpoint dir "
                    "(ckpt_dir/<model>/{best|latest})")
    ap.add_argument("--arch", default="auto",
                    help="backbone family (default: sniffed from keys)")
    ap.add_argument("--verify", action="store_true",
                    help="run torch replica vs converted Flax model and "
                    "print max logits delta")
    ap.add_argument("--export-torch", metavar="OUT", default="",
                    help="INVERSE direction: read a tpuic Orbax checkpoint "
                    "and write a reference-layout torch file (resnet, "
                    "inceptionv3, efficientnet families) to OUT; composes "
                    "with --verify")
    ap.add_argument("--image-size", type=int, default=128)
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--tol", type=float, default=1e-3,
                    help="--verify failure threshold on max |delta|")
    args = ap.parse_args(argv)

    if args.export_torch:
        import orbax.checkpoint as ocp
        import torch

        restored = ocp.PyTreeCheckpointer().restore(
            os.path.abspath(args.checkpoint))
        sd = export_state_dict(restored["params"], restored["batch_stats"])
        meta = restored.get("meta", {})

        def torchable(v):
            a = np.asarray(v)
            # ml_dtypes (bfloat16) numpy arrays are opaque to torch; their
            # dtype.kind is 'V' (void), not 'f'.
            if a.dtype.kind == "V" or (a.dtype.kind == "f"
                                       and a.dtype not in (np.float16,
                                                           np.float32,
                                                           np.float64)):
                a = a.astype(np.float32)
            return torch.as_tensor(a)

        torch.save({"epoch": int(meta.get("epoch", 0)),
                    "best_score": float(meta.get("best_score", 0.0)),
                    "state_dict": {k: torchable(v) for k, v in sd.items()}},
                   args.export_torch)
        print(json.dumps({"exported": args.export_torch,
                          "keys": len(sd),
                          "epoch": int(meta.get("epoch", 0))}))
        if not args.verify:
            return 0
        # --verify composes: fall through and validate the exported file
        # like any reference checkpoint.
        args.checkpoint = args.export_torch

    payload = load_reference_checkpoint(args.checkpoint)
    sd = payload["state_dict"]
    arch = args.arch if args.arch != "auto" else detect_arch(sd)
    if arch == "efficientnet":
        arch = f"efficientnet-{detect_efficientnet_variant(sd)}"
    elif arch == "resnet":
        arch = detect_resnet_depth(sd)
    elif arch == "vit":
        arch = detect_vit_variant(sd)
    tree = convert_state_dict(sd, arch=arch)
    n_params = len([1 for _ in _iter_leaves(tree["params"])])
    n_stats = len([1 for _ in _iter_leaves(tree["batch_stats"])])
    num_classes, mlp_head = _infer_head(sd)
    print(json.dumps({
        "checkpoint": args.checkpoint, "arch": arch,
        "epoch": payload["epoch"], "best_score": payload["best_score"],
        "num_classes": num_classes, "mlp_head": mlp_head,
        "param_leaves": n_params, "batch_stat_leaves": n_stats}))
    if not args.verify:
        return 0

    import torch

    import jax
    import jax.numpy as jnp

    from tpuic.checkpoint.manager import lenient_restore
    from tpuic.checkpoint.torch_ref import build_reference_model
    from tpuic.models import create_model

    size = args.image_size
    if arch.startswith("vit"):
        # The pos-embedding length fixes the ViT's image size: verify at
        # the checkpoint's own size, whatever --image-size says.
        flat = strip_prefixes(sd)
        pe = flat.get("pos_embedding", flat.get("encoder.pos_embedding"))
        patch = flat["conv_proj.weight"].shape[-1]
        if pe is not None:
            size = int(patch) * int(round((pe.shape[1] - 1) ** 0.5))
    replica = build_reference_model(arch, num_classes, mlp_head=mlp_head,
                                    image_size=size).eval()
    # strip_prefixes normalizes to numpy for the converter; torch's
    # load_state_dict wants tensors back.
    if arch.startswith("vit"):
        # ViT keys carry a REAL inner 'encoder.' scope the replica expects:
        # strip only the DDP wrapper, plus one 'encoder.' when the
        # checkpoint is reference-wrapped (no bare conv_proj at top level).
        raw = {(k[len("module."):] if k.startswith("module.") else k): v
               for k, v in sd.items()}
        if not any(k.startswith("conv_proj") for k in raw):
            raw = {(k[len("encoder."):] if k.startswith("encoder.")
                    else k): v for k, v in raw.items()}
        stripped = {k: torch.as_tensor(np.asarray(
            v.detach().cpu().numpy() if hasattr(v, "detach") else v))
            for k, v in raw.items()}
    else:
        stripped = {k: torch.as_tensor(np.asarray(v))
                    for k, v in strip_prefixes(sd).items()}
    missing, unexpected = replica.load_state_dict(stripped, strict=False)
    kw = {} if mlp_head else {"head_widths": ()}
    model = create_model(arch, num_classes, dtype="float32", **kw)
    variables = model.init(jax.random.key(0), jnp.zeros((1, size, size, 3)),
                           train=False)
    merged_p, n_loaded, n_total = lenient_restore(
        dict(variables["params"]), tree["params"])
    merged_s, n_s, n_s_total = lenient_restore(
        dict(variables.get("batch_stats", {})),  # ViT: no BN collection
        tree["batch_stats"])
    x = np.random.default_rng(0).normal(
        size=(args.batch, size, size, 3)).astype(np.float32)
    with torch.no_grad():
        want = replica(torch.from_numpy(
            np.transpose(x, (0, 3, 1, 2)))).numpy()
    got = np.asarray(model.apply({"params": merged_p,
                                  "batch_stats": merged_s},
                                 jnp.asarray(x), train=False))
    delta = float(np.abs(got - want).max())
    ok = (delta < args.tol and n_loaded == n_total and n_s == n_s_total
          and not missing)
    print(json.dumps({
        "verify": "ok" if ok else "FAIL",
        "max_logits_delta": delta,
        "params_mapped": f"{n_loaded}/{n_total}",
        "batch_stats_mapped": f"{n_s}/{n_s_total}",
        "replica_missing_keys": len(missing),
        "replica_unexpected_keys": len(unexpected)}))
    return 0 if ok else 1


def _iter_leaves(tree):
    for v in tree.values():
        if isinstance(v, dict):
            yield from _iter_leaves(v)
        else:
            yield v


if __name__ == "__main__":
    raise SystemExit(main())
