"""Torch state_dict -> Flax pytree checkpoint converter.

The reference saves ``{'epoch', 'best_score', 'state_dict'}`` with DDP's
``module.`` prefix (train.py:177-179), where the model is
``Classifier(name, num_classes)`` — a torchvision backbone whose ``fc`` was
replaced by a 4-layer MLP (``fc.0/2/4/6`` Linear indices of the Sequential at
nn/classifier.py:26-34), all hung off an ``encoder`` attribute
(nn/classifier.py:11-27). This module converts those checkpoints — or plain
torchvision ``resnet{18,34,50,101}`` state_dicts — into this framework's
``{'params': ..., 'batch_stats': ...}`` trees so pretrained-weight parity can
be verified (SURVEY.md §7 "Checkpoint compatibility").

Layout translation rules (torch -> flax):

- conv weight  OIHW -> HWIO  (transpose 2,3,1,0)
- linear weight (out,in) -> kernel (in,out)  (transpose)
- BatchNorm  weight/bias/running_mean/running_var ->
  scale/bias (params) + mean/var (batch_stats); num_batches_tracked dropped.

Name translation (torchvision resnet -> tpuic ResNet, see models/resnet.py):

- ``conv1``/``bn1`` stem keep their names
- ``layer{s}.{i}.<leaf>`` -> ``layer{s}_{i}/<leaf>``
- ``layer{s}.{i}.downsample.0`` -> ``downsample_conv``; ``.downsample.1`` ->
  ``downsample_bn``
- ``fc.0/2/4/6`` (the reference's MLP head) -> ``head/fc0,fc1,fc2,out``;
  a plain torchvision ``fc`` (single Linear) -> ``head/out`` when shapes fit.

The output trees are plain nested dicts compatible with
``tpuic.checkpoint.manager.lenient_restore`` — unmapped or shape-mismatched
leaves are simply absent and fall back to the fresh initialization, matching
the reference's lenient partial load (train.py:143-148).
"""

from __future__ import annotations

import re
from typing import Any, Dict, Mapping, Tuple

import numpy as np


def _set(tree: Dict, path: Tuple[str, ...], value: np.ndarray) -> None:
    d = tree
    for k in path[:-1]:
        d = d.setdefault(k, {})
    d[path[-1]] = value


def strip_prefixes(state_dict: Mapping[str, Any]) -> Dict[str, np.ndarray]:
    """Drop DDP's ``module.`` and the reference's ``encoder.`` wrappers."""
    out = {}
    for k, v in state_dict.items():
        for pre in ("module.", "encoder."):
            if k.startswith(pre):
                k = k[len(pre):]
        out[k] = np.asarray(v.detach().cpu().numpy()
                            if hasattr(v, "detach") else v)
    return out


def _conv(w: np.ndarray) -> np.ndarray:
    return np.transpose(w, (2, 3, 1, 0))  # OIHW -> HWIO


def _linear(w: np.ndarray) -> np.ndarray:
    return np.transpose(w)  # (out, in) -> (in, out)


# torchvision resnet leaf name within a block -> tpuic module name
_RESNET_LEAF = {
    "conv1": "conv1", "conv2": "conv2", "conv3": "conv3",
    "bn1": "bn1", "bn2": "bn2", "bn3": "bn3",
    "downsample.0": "downsample_conv", "downsample.1": "downsample_bn",
}

# the reference head's Sequential Linear indices (nn/classifier.py:26-34)
_HEAD_FC = {"0": "fc0", "2": "fc1", "4": "fc2", "6": "out"}

_BLOCK_RE = re.compile(r"^layer(\d+)\.(\d+)\.(.+)$")


def convert_resnet(state_dict: Mapping[str, Any],
                   backbone_scope: str = "backbone",
                   head_scope: str = "head") -> Dict[str, Dict]:
    """Convert a torchvision-style resnet (or reference Classifier-over-resnet)
    state_dict into ``{'params': ..., 'batch_stats': ...}`` nested dicts.

    Unknown keys are skipped (collected in the returned tree under no path);
    use ``lenient_restore`` to merge into a live model state.
    """
    sd = strip_prefixes(state_dict)
    params: Dict = {}
    stats: Dict = {}

    def put_bn(scope: Tuple[str, ...], leaf: str, v: np.ndarray) -> None:
        if leaf == "weight":
            _set(params, scope + ("scale",), v)
        elif leaf == "bias":
            _set(params, scope + ("bias",), v)
        elif leaf == "running_mean":
            _set(stats, scope + ("mean",), v)
        elif leaf == "running_var":
            _set(stats, scope + ("var",), v)
        # num_batches_tracked intentionally dropped

    for key, v in sd.items():
        parts = key.rsplit(".", 1)
        if len(parts) != 2:
            continue
        name, leaf = parts

        # -- stem ------------------------------------------------------------
        if name == "conv1" and leaf == "weight":
            _set(params, (backbone_scope, "conv1", "kernel"), _conv(v))
            continue
        if name == "bn1":
            put_bn((backbone_scope, "bn1"), leaf, v)
            continue

        # -- stages ----------------------------------------------------------
        m = _BLOCK_RE.match(name)
        if m:
            stage, block, inner = m.group(1), m.group(2), m.group(3)
            mod = _RESNET_LEAF.get(inner)
            if mod is None:
                continue
            scope = (backbone_scope, f"layer{stage}_{block}", mod)
            if mod.startswith("conv") or mod == "downsample_conv":
                if leaf == "weight":
                    _set(params, scope + ("kernel",), _conv(v))
            else:
                put_bn(scope, leaf, v)
            continue

        # -- head ------------------------------------------------------------
        if name.startswith("fc"):
            rest = name[2:].lstrip(".")
            target = _HEAD_FC.get(rest) if rest else "out"
            if target is None:
                continue
            if leaf == "weight":
                _set(params, (head_scope, target, "kernel"), _linear(v))
            elif leaf == "bias":
                _set(params, (head_scope, target, "bias"), v)
            continue

    return {"params": params, "batch_stats": stats}


def load_reference_checkpoint(path: str) -> Dict[str, Any]:
    """Load a reference ``torch.save`` checkpoint file (train.py:177-179).

    Returns ``{'epoch': int, 'best_score': float, 'state_dict': {...}}``; a
    bare state_dict file is wrapped with epoch=0/best_score=0.0.
    """
    import torch  # deferred: torch is only needed on the conversion path

    # weights_only: the payload is tensors + scalars; never unpickle code.
    payload = torch.load(path, map_location="cpu", weights_only=True)
    if isinstance(payload, dict) and "state_dict" in payload:
        return {"epoch": int(payload.get("epoch", 0)),
                "best_score": float(payload.get("best_score", 0.0)),
                "state_dict": payload["state_dict"]}
    return {"epoch": 0, "best_score": 0.0, "state_dict": payload}


def convert_reference_checkpoint(path: str) -> Dict[str, Any]:
    """File -> ``{'params', 'batch_stats', 'epoch', 'best_score'}``."""
    payload = load_reference_checkpoint(path)
    tree = convert_resnet(payload["state_dict"])
    tree["epoch"] = payload["epoch"]
    tree["best_score"] = payload["best_score"]
    return tree
