// Fused image-prep kernel for the host input pipeline.
//
// The reference does resize / rot90 / flips / color jitter / normalize as
// separate full-image passes in Python workers (dp/loader.py:39-91 via
// cv2 + numpy). At TPU pod scale the input pipeline is the bottleneck
// (SURVEY.md §7 "hard parts"), so this implements the whole per-sample chain
// as ONE gather loop over destination pixels: every geometry op is an index
// permutation, so resize+rot90+vflip+hflip collapse into a single source-index
// computation, and the color op + /255 + (x-mean)/std normalize are applied
// to each gathered pixel in registers. One pass, no intermediate images.
//
// Numeric parity contract (tests/test_native.py): bitwise-equal with the
// NumPy path in tpuic/data/transforms.py for geometry+normalize; the color
// ops match to float32 rounding.
//
// C ABI only (called via ctypes; no pybind11 in this image).

#include <cstdint>
#include <cmath>
#include <vector>

namespace {

// cv2.INTER_NEAREST source index: floor(dst * (src/dst)), clamped.
inline void nearest_map(int dst, int src, std::vector<int>& out) {
  out.resize(dst);
  const double scale = static_cast<double>(src) / dst;
  for (int i = 0; i < dst; ++i) {
    int v = static_cast<int>(i * scale);
    out[i] = v < src - 1 ? v : src - 1;
  }
}

// Inverse geometry: dst (i, j) -> coords in the resized (pre-augment) image.
// Forward chain (transforms.py augment): a = rot90^k(resized);
// b = vflip ? a[::-1] : a; out = hflip ? b[:, ::-1] : b.
inline void invert_geometry(int i, int j, int s, int rot_k, int vflip,
                            int hflip, int* ri, int* rj) {
  if (hflip) j = s - 1 - j;
  if (vflip) i = s - 1 - i;
  // Invert rot90^k: rot90 maps in[r, c] -> out[? ]: out[i, j] = in[j, s-1-i].
  // So in-coords of out (i, j) are (j, s-1-i); apply k times.
  for (int t = 0; t < (rot_k & 3); ++t) {
    int ni = j, nj = s - 1 - i;
    i = ni; j = nj;
  }
  *ri = i; *rj = j;
}

}  // namespace

extern "C" {

// src: uint8 HWC [h, w, 3] (contiguous). dst: float32 [s, s, 3].
// color_op: 0 none, 1 saturation, 2 brightness, 3 contrast (factor applies).
// mean3/std3: normalize constants in 0..1 space (transforms.py:94-101).
void tpuic_prep_image(const uint8_t* src, int h, int w, float* dst, int s,
                      int rot_k, int vflip, int hflip, int color_op,
                      float factor, const float* mean3, const float* std3) {
  std::vector<int> rows, cols;
  nearest_map(s, h, rows);
  nearest_map(s, w, cols);

  float gmean = 0.0f;  // global gray mean of the resized image (contrast op)
  if (color_op == 3) {
    double acc = 0.0;
    for (int i = 0; i < s; ++i) {
      const uint8_t* rp = src + static_cast<int64_t>(rows[i]) * w * 3;
      for (int j = 0; j < s; ++j) {
        const uint8_t* p = rp + cols[j] * 3;
        acc += p[0]; acc += p[1]; acc += p[2];
      }
    }
    gmean = static_cast<float>(acc / (static_cast<double>(s) * s * 3));
  }

  const float luma[3] = {0.299f, 0.587f, 0.114f};
  for (int i = 0; i < s; ++i) {
    for (int j = 0; j < s; ++j) {
      int ri, rj;
      invert_geometry(i, j, s, rot_k, vflip, hflip, &ri, &rj);
      const uint8_t* p =
          src + (static_cast<int64_t>(rows[ri]) * w + cols[rj]) * 3;
      float rgb[3] = {static_cast<float>(p[0]), static_cast<float>(p[1]),
                      static_cast<float>(p[2])};
      switch (color_op) {
        case 1: {  // saturation: blend with per-pixel luma gray
          float gray =
              rgb[0] * luma[0] + rgb[1] * luma[1] + rgb[2] * luma[2];
          for (int c = 0; c < 3; ++c) {
            float v = gray + (rgb[c] - gray) * factor;
            rgb[c] = v < 0.0f ? 0.0f : (v > 255.0f ? 255.0f : v);
          }
          break;
        }
        case 2: {  // brightness: scale
          for (int c = 0; c < 3; ++c) {
            float v = rgb[c] * factor;
            rgb[c] = v < 0.0f ? 0.0f : (v > 255.0f ? 255.0f : v);
          }
          break;
        }
        case 3: {  // contrast: blend with global gray mean
          for (int c = 0; c < 3; ++c) {
            float v = gmean + (rgb[c] - gmean) * factor;
            rgb[c] = v < 0.0f ? 0.0f : (v > 255.0f ? 255.0f : v);
          }
          break;
        }
        default: break;
      }
      float* d = dst + (static_cast<int64_t>(i) * s + j) * 3;
      // True division (not reciprocal-multiply): bitwise parity with
      // numpy's img/255.0 (transforms.py:100).
      for (int c = 0; c < 3; ++c) {
        d[c] = (rgb[c] / 255.0f - mean3[c]) / std3[c];
      }
    }
  }
}

int tpuic_dataprep_abi_version() { return 1; }

}  // extern "C"
