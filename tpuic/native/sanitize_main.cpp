// Sanitizer harness for the native data core (SURVEY.md §5 "race detection
// / sanitizers": absent in the reference; here the C++ decode + prep paths
// run under AddressSanitizer/UBSan in CI — tests/test_native_sanitize.py
// compiles this file together with decode.cpp and dataprep.cpp using
// -fsanitize=address,undefined and runs it against real encoded images,
// truncated prefixes, and garbage bytes).
//
//   sanitize_main <image file> [more files...]
//
// For each file: decode+resize to 64x64, run the fused prep pass over
// every augmentation branch, then re-decode every truncation prefix and a
// corrupted copy (all must fail cleanly, not crash). Exits 0 and prints
// "SANITIZE OK" when every path ran without a sanitizer report.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

extern "C" {
int tpuic_decode_resize(const uint8_t* data, int64_t len, int size,
                        uint8_t* out);
void tpuic_prep_image(const uint8_t* src, int h, int w, float* dst, int s,
                      int rot_k, int vflip, int hflip, int color_op,
                      float factor, const float* mean, const float* std_);
}

static std::vector<uint8_t> read_file(const char* path) {
  std::vector<uint8_t> buf;
  FILE* f = std::fopen(path, "rb");
  if (!f) return buf;
  std::fseek(f, 0, SEEK_END);
  long n = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  buf.resize(n > 0 ? static_cast<size_t>(n) : 0);
  if (n > 0 && std::fread(buf.data(), 1, buf.size(), f) != buf.size())
    buf.clear();
  std::fclose(f);
  return buf;
}

int main(int argc, char** argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s <image> [image...]\n", argv[0]);
    return 2;
  }
  const int S = 64;
  const float mean[3] = {0.485f, 0.456f, 0.406f};
  const float stdv[3] = {0.229f, 0.224f, 0.225f};
  std::vector<uint8_t> decoded(S * S * 3);
  std::vector<float> prepped(S * S * 3);

  for (int a = 1; a < argc; ++a) {
    std::vector<uint8_t> raw = read_file(argv[a]);
    if (raw.empty()) {
      std::fprintf(stderr, "unreadable: %s\n", argv[a]);
      return 2;
    }
    if (tpuic_decode_resize(raw.data(), (int64_t)raw.size(), S,
                            decoded.data()) != 0) {
      std::fprintf(stderr, "decode failed: %s\n", argv[a]);
      return 3;
    }
    // Every augmentation branch of the fused prep pass.
    for (int rot = 0; rot < 4; ++rot)
      for (int flip = 0; flip < 4; ++flip)
        for (int color = 0; color < 4; ++color)
          tpuic_prep_image(decoded.data(), S, S, prepped.data(), S, rot,
                           flip & 1, flip >> 1, color, 1.07f, mean, stdv);
    // Truncations: every prefix length must fail or succeed WITHOUT
    // touching memory out of bounds (rc is irrelevant; surviving is the
    // assertion).
    for (size_t cut = 0; cut < raw.size(); cut += 1 + raw.size() / 97)
      (void)tpuic_decode_resize(raw.data(), (int64_t)cut, S, decoded.data());
    // Bit corruption in the middle of the stream.
    std::vector<uint8_t> bad = raw;
    for (size_t i = bad.size() / 3; i < bad.size() && i < bad.size() / 3 + 64;
         ++i)
      bad[i] ^= 0xA5;
    (void)tpuic_decode_resize(bad.data(), (int64_t)bad.size(), S,
                              decoded.data());
  }
  // Pure garbage of several sizes.
  for (int n : {0, 1, 3, 16, 4096}) {
    std::vector<uint8_t> junk(n, 0x5A);
    (void)tpuic_decode_resize(junk.data(), n, S, decoded.data());
  }
  std::printf("SANITIZE OK\n");
  return 0;
}
