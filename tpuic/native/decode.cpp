// Native image decode + resize for the host input pipeline.
//
// The reference decodes with skimage.io.imread inside DataLoader worker
// processes (dp/loader.py:44, num_workers=6 at train.py:114). This host has
// ONE core (measured: nproc=1), so Python-side worker pools cannot scale
// decode; instead the decode itself is made cheap and is used primarily by
// the one-time pack step (tpuic/data/pack.py) that converts an ImageFolder
// tree into a memory-mapped uint8 cache served at memory bandwidth.
//
// - JPEG via libjpeg, using DCT scaled decode (scale_num/8): the decoder
//   emits the smallest IDCT scale that still covers the target size, so a
//   4000px photo resized to 224 decodes ~8x faster than full-resolution.
// - PNG via libpng (palette/gray/alpha all normalized to 8-bit RGB).
// - Final nearest-neighbor resize matches cv2.INTER_NEAREST semantics
//   (src index = floor(dst * src/dst), clamped) — identical to
//   tpuic/data/transforms.py:resize_nearest and dataprep.cpp.
//
// C ABI only (ctypes; no pybind11 in this image). Thread-safe: no globals;
// libjpeg/libpng error paths use setjmp per call.

#include <csetjmp>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <vector>

#include <jpeglib.h>
#include <png.h>

namespace {

// cv2.INTER_NEAREST source index map (parity with transforms.resize_nearest).
inline void nearest_map(int dst, int src, std::vector<int>& out) {
  out.resize(dst);
  const double scale = static_cast<double>(src) / dst;
  for (int i = 0; i < dst; ++i) {
    int v = static_cast<int>(i * scale);
    out[i] = v < src - 1 ? v : src - 1;
  }
}

// RGB HWC [h,w,3] -> nearest-resized [s,s,3].
void resize_nearest_rgb(const uint8_t* src, int h, int w, uint8_t* dst,
                        int s) {
  std::vector<int> rows, cols;
  nearest_map(s, h, rows);
  nearest_map(s, w, cols);
  for (int i = 0; i < s; ++i) {
    const uint8_t* rp = src + static_cast<int64_t>(rows[i]) * w * 3;
    uint8_t* dp = dst + static_cast<int64_t>(i) * s * 3;
    for (int j = 0; j < s; ++j) {
      const uint8_t* p = rp + cols[j] * 3;
      dp[j * 3 + 0] = p[0];
      dp[j * 3 + 1] = p[1];
      dp[j * 3 + 2] = p[2];
    }
  }
}

struct JpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jump;
};

void jpeg_err_exit(j_common_ptr cinfo) {
  JpegErr* err = reinterpret_cast<JpegErr*>(cinfo->err);
  longjmp(err->jump, 1);
}

// Decode JPEG bytes -> RGB rows, DCT-scaled to the smallest size >= target
// (or full size when target <= 0). Returns 0 on success.
int decode_jpeg(const uint8_t* data, int64_t len, int target,
                std::vector<uint8_t>& pixels, int* out_h, int* out_w) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  // All C++ objects with destructors are constructed BEFORE setjmp:
  // longjmp over a live object's construction point is UB and leaks its
  // buffer. `pixels` is caller-owned; `row` lives here, resized later.
  std::vector<uint8_t> row;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = jpeg_err_exit;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return 1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, data, static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return 1;
  }
  cinfo.out_color_space = JCS_RGB;
  if (target > 0) {
    // Pick num/8 so that min(h,w)*num/8 >= target, num in 1..8.
    const int src_min = cinfo.image_height < cinfo.image_width
                            ? cinfo.image_height
                            : cinfo.image_width;
    int num = 8;
    while (num > 1 &&
           static_cast<int64_t>(src_min) * (num - 1) / 8 >= target) {
      --num;
    }
    cinfo.scale_num = num;
    cinfo.scale_denom = 8;
  }
  jpeg_start_decompress(&cinfo);
  const int h = cinfo.output_height, w = cinfo.output_width;
  const int ch = cinfo.output_components;  // 3 for JCS_RGB
  pixels.resize(static_cast<int64_t>(h) * w * 3);
  row.resize(static_cast<int64_t>(w) * ch);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* rp = row.data();
    jpeg_read_scanlines(&cinfo, &rp, 1);
    uint8_t* dp =
        pixels.data() + static_cast<int64_t>(cinfo.output_scanline - 1) * w * 3;
    if (ch == 3) {
      std::memcpy(dp, row.data(), static_cast<size_t>(w) * 3);
    } else {  // grayscale broadcast (transforms.to_rgb semantics)
      for (int j = 0; j < w; ++j) {
        dp[j * 3 + 0] = dp[j * 3 + 1] = dp[j * 3 + 2] = row[j * ch];
      }
    }
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  *out_h = h;
  *out_w = w;
  return 0;
}

struct PngReadState {
  const uint8_t* data;
  int64_t len;
  int64_t pos;
};

void png_read_fn(png_structp png, png_bytep out, png_size_t count) {
  PngReadState* st = static_cast<PngReadState*>(png_get_io_ptr(png));
  if (st->pos + static_cast<int64_t>(count) > st->len) {
    png_error(png, "read past end");
  }
  std::memcpy(out, st->data + st->pos, count);
  st->pos += static_cast<int64_t>(count);
}

// Decode PNG bytes -> 8-bit RGB (palette expanded, 16-bit stripped, alpha
// dropped — reference keeps the first 3 channels, dp/loader.py:45).
int decode_png(const uint8_t* data, int64_t len, std::vector<uint8_t>& pixels,
               int* out_h, int* out_w) {
  if (len < 8 || png_sig_cmp(data, 0, 8)) return 1;
  png_structp png =
      png_create_read_struct(PNG_LIBPNG_VER_STRING, nullptr, nullptr, nullptr);
  if (!png) return 1;
  png_infop info = png_create_info_struct(png);
  if (!info) {
    png_destroy_read_struct(&png, nullptr, nullptr);
    return 1;
  }
  // Constructed BEFORE setjmp (same rule as decode_jpeg's `row`):
  // png_error longjmps out of png_read_image, and a vector whose lifetime
  // began after setjmp never runs its destructor on that path — every
  // corrupt PNG then leaks its row-pointer block (found by the ASan
  // harness, tests/test_native_sanitize.py).
  std::vector<png_bytep> rows;
  if (setjmp(png_jmpbuf(png))) {
    png_destroy_read_struct(&png, &info, nullptr);
    return 1;
  }
  PngReadState st{data, len, 0};
  png_set_read_fn(png, &st, png_read_fn);
  png_read_info(png, info);
  png_set_expand(png);          // palette->RGB, gray<8bit->8bit, tRNS->alpha
  png_set_strip_16(png);        // 16-bit -> 8-bit
  png_set_strip_alpha(png);     // drop alpha (keep first 3 channels)
  png_set_gray_to_rgb(png);     // gray -> RGB broadcast
  png_read_update_info(png, info);
  const int h = static_cast<int>(png_get_image_height(png, info));
  const int w = static_cast<int>(png_get_image_width(png, info));
  if (png_get_rowbytes(png, info) != static_cast<size_t>(w) * 3) {
    png_destroy_read_struct(&png, &info, nullptr);
    return 1;
  }
  pixels.resize(static_cast<int64_t>(h) * w * 3);
  rows.resize(h);
  for (int i = 0; i < h; ++i) {
    rows[i] = pixels.data() + static_cast<int64_t>(i) * w * 3;
  }
  png_read_image(png, rows.data());
  png_destroy_read_struct(&png, &info, nullptr);
  *out_h = h;
  *out_w = w;
  return 0;
}

}  // namespace

extern "C" {

// Decode (JPEG or PNG, sniffed from magic bytes) and nearest-resize to
// [size, size, 3] uint8. Returns 0 ok, nonzero on any failure (caller falls
// back to the PIL path).
int tpuic_decode_resize(const uint8_t* data, int64_t len, int size,
                        uint8_t* out) {
  if (len < 4 || size <= 0) return 1;
  std::vector<uint8_t> pixels;
  int h = 0, w = 0;
  int rc;
  if (data[0] == 0xFF && data[1] == 0xD8) {
    rc = decode_jpeg(data, len, size, pixels, &h, &w);
  } else if (data[0] == 0x89 && data[1] == 'P') {
    rc = decode_png(data, len, pixels, &h, &w);
  } else {
    return 2;  // unsupported container; caller uses PIL
  }
  if (rc != 0 || h <= 0 || w <= 0) return 1;
  resize_nearest_rgb(pixels.data(), h, w, out, size);
  return 0;
}

// Decode only (no resize): h/w returned via pointers; out must hold
// max_len bytes. Returns 0 ok, -1 buffer too small, else decode error.
int tpuic_decode(const uint8_t* data, int64_t len, uint8_t* out,
                 int64_t max_len, int* out_h, int* out_w) {
  if (len < 4) return 1;
  std::vector<uint8_t> pixels;
  int h = 0, w = 0;
  int rc;
  if (data[0] == 0xFF && data[1] == 0xD8) {
    rc = decode_jpeg(data, len, 0, pixels, &h, &w);
  } else if (data[0] == 0x89 && data[1] == 'P') {
    rc = decode_png(data, len, pixels, &h, &w);
  } else {
    return 2;
  }
  if (rc != 0) return rc;
  if (static_cast<int64_t>(pixels.size()) > max_len) return -1;
  std::memcpy(out, pixels.data(), pixels.size());
  *out_h = h;
  *out_w = w;
  return 0;
}

int tpuic_decode_abi_version() { return 1; }

}  // extern "C"
