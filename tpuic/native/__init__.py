"""Native (C++) host-side data core, bound via ctypes.

Two shared libraries, compiled on first use with the local toolchain and
loaded with ctypes (no pybind11 in this image):

- ``dataprep``: the fused resize+augment+normalize gather pass
  (dataprep.cpp) replacing the reference's multiple full-image numpy/cv2
  passes (dp/loader.py:39-91).
- ``decode``: libjpeg/libpng decode + nearest resize (decode.cpp) —
  JPEG decodes DCT-scaled, so the one-time pack step (tpuic/data/pack.py)
  that builds the memory-mapped uint8 cache runs at native speed. The host
  has ONE core (nproc=1, measured round 3), so the pipeline strategy is
  "decode once, serve from memmap", not worker pools.

Falls back cleanly: each binding is None when no compiler is available or
the build fails; callers then use PIL + the pure-NumPy transforms, which
are the numeric ground truth the kernels must match (tests/test_native.py).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
import threading
from typing import Optional, Sequence

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))

_lock = threading.Lock()


class _Lib:
    """Build-on-first-use ctypes library with an ABI version gate."""

    def __init__(self, src: str, soname: str, abi_symbol: str, abi: int,
                 link: Sequence[str] = ()) -> None:
        self.src = os.path.join(_HERE, src)
        self.path = os.path.join(_HERE, soname)
        self.abi_symbol = abi_symbol
        self.abi = abi
        self.link = list(link)
        self._lib = None
        self._tried = False

    def _build(self) -> Optional[str]:
        """Compile next to the source. Atomic via rename; the temp .so is
        always removed on failure (finally-block — ADVICE r1)."""
        for cxx in ("g++", "c++", "clang++"):
            tmp_path = None
            try:
                with tempfile.NamedTemporaryFile(
                        suffix=".so", dir=_HERE, delete=False) as tmp:
                    tmp_path = tmp.name
                r = subprocess.run(
                    [cxx, "-O3", "-shared", "-fPIC", "-std=c++17", self.src,
                     "-o", tmp_path] + self.link,
                    capture_output=True, timeout=120)
                if r.returncode == 0:
                    os.replace(tmp_path, self.path)
                    tmp_path = None
                    return self.path
            except (OSError, subprocess.TimeoutExpired):
                pass
            finally:
                if tmp_path is not None:
                    try:
                        os.unlink(tmp_path)
                    except OSError:
                        pass
        return None

    def _open(self, path: Optional[str]):
        """CDLL with the ABI gate. None path (failed build) returns None
        instead of CDLL(None) == the main program (ADVICE r1)."""
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
            if int(getattr(lib, self.abi_symbol)()) != self.abi:
                return None
            return lib
        except (OSError, AttributeError):
            return None

    def _fresh(self) -> bool:
        """On-disk .so exists and is newer than its source."""
        try:
            return (os.path.getmtime(self.path)
                    >= os.path.getmtime(self.src))
        except OSError:
            return False

    def load(self):
        with _lock:
            if self._lib is not None or self._tried:
                return self._lib
            self._tried = True
            lib = self._open(self.path if self._fresh() else self._build())
            if lib is None and os.path.exists(self.path):
                # Stale on-disk build (old ABI / wrong arch): rebuild once.
                lib = self._open(self._build())
            self._lib = lib
            return self._lib


_dataprep = _Lib("dataprep.cpp", "libtpuic_dataprep.so",
                 "tpuic_dataprep_abi_version", 1)
_decode = _Lib("decode.cpp", "libtpuic_decode.so",
               "tpuic_decode_abi_version", 1, link=["-ljpeg", "-lpng"])


def _load():
    lib = _dataprep.load()
    if lib is not None and not getattr(lib, "_sigs_set", False):
        lib.tpuic_prep_image.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_float), ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_float,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
        ]
        lib.tpuic_prep_image.restype = None
        lib._sigs_set = True
    return lib


def _load_decode():
    lib = _decode.load()
    if lib is not None and not getattr(lib, "_sigs_set", False):
        lib.tpuic_decode_resize.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64, ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint8),
        ]
        lib.tpuic_decode_resize.restype = ctypes.c_int
        lib.tpuic_decode.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
        ]
        lib.tpuic_decode.restype = ctypes.c_int
        lib._sigs_set = True
    return lib


def available() -> bool:
    return _load() is not None


def decode_available() -> bool:
    return _load_decode() is not None


COLOR_NONE, COLOR_SATURATION, COLOR_BRIGHTNESS, COLOR_CONTRAST = 0, 1, 2, 3


def prep_image(img: np.ndarray, size: int, *, rot_k: int = 0,
               vflip: bool = False, hflip: bool = False,
               color_op: int = COLOR_NONE, factor: float = 1.0,
               mean=None, std=None) -> Optional[np.ndarray]:
    """Fused resize+augment+normalize. img: HWC uint8 (C-contiguous).
    Returns [size, size, 3] float32, or None when the native core is
    unavailable (caller falls back to NumPy transforms)."""
    lib = _load()
    if lib is None:
        return None
    from tpuic.data.transforms import IMAGENET_MEAN, IMAGENET_STD
    img = np.ascontiguousarray(img, np.uint8)
    assert img.ndim == 3 and img.shape[2] == 3, img.shape
    mean = np.ascontiguousarray(
        IMAGENET_MEAN if mean is None else mean, np.float32)
    std = np.ascontiguousarray(
        IMAGENET_STD if std is None else std, np.float32)
    out = np.empty((size, size, 3), np.float32)
    lib.tpuic_prep_image(
        img.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        img.shape[0], img.shape[1],
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), size,
        int(rot_k) & 3, int(bool(vflip)), int(bool(hflip)), int(color_op),
        float(factor),
        mean.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        std.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
    return out


def decode_resize(data: bytes, size: int) -> Optional[np.ndarray]:
    """Decode JPEG/PNG bytes and nearest-resize to [size, size, 3] uint8.

    JPEGs decode DCT-scaled (smallest 1/8..8/8 scale covering ``size``).
    Returns None when the native decoder is unavailable or the container
    is unsupported/corrupt (caller falls back to PIL)."""
    lib = _load_decode()
    if lib is None:
        return None
    buf = np.frombuffer(data, np.uint8)
    out = np.empty((size, size, 3), np.uint8)
    rc = lib.tpuic_decode_resize(
        buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.c_int64(buf.size), int(size),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    return out if rc == 0 else None
