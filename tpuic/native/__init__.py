"""Native (C++) host-side data-prep core, bound via ctypes.

The reference delegates its host pipeline to cv2/skimage C code through
multiple full-image passes (dp/loader.py:39-91). Here the whole per-sample
chain (nearest resize -> rot90/flip geometry -> color jitter -> normalize) is
one fused C++ gather pass (dataprep.cpp), compiled on first use with the
local toolchain and loaded with ctypes (no pybind11 in this image). ctypes
releases the GIL during the call, so the Loader's thread pool gets real
parallelism out of it.

Falls back cleanly: ``prep_image`` is None when no compiler is available or
the build fails; callers (tpuic/data/folder.py) then use the pure-NumPy
transforms, which are the numeric ground truth the kernel must match
(tests/test_native.py).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
import threading
from typing import Optional

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "dataprep.cpp")
_LIB = os.path.join(_HERE, "libtpuic_dataprep.so")
_ABI = 1

_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> Optional[str]:
    """Compile the shared library next to the source. Atomic via rename."""
    for cxx in ("g++", "c++", "clang++"):
        try:
            with tempfile.NamedTemporaryFile(
                    suffix=".so", dir=_HERE, delete=False) as tmp:
                tmp_path = tmp.name
            r = subprocess.run(
                [cxx, "-O3", "-shared", "-fPIC", "-std=c++17", _SRC,
                 "-o", tmp_path],
                capture_output=True, timeout=120)
            if r.returncode == 0:
                os.replace(tmp_path, _LIB)
                return _LIB
            os.unlink(tmp_path)
        except (OSError, subprocess.TimeoutExpired):
            pass
    return None


def _load():
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        path = _LIB if os.path.exists(_LIB) else _build()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
            if lib.tpuic_dataprep_abi_version() != _ABI:
                lib = ctypes.CDLL(_build())  # stale build: recompile
                if lib.tpuic_dataprep_abi_version() != _ABI:
                    return None
            lib.tpuic_prep_image.argtypes = [
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_int, ctypes.c_int,
                ctypes.POINTER(ctypes.c_float), ctypes.c_int,
                ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.c_float,
                ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ]
            lib.tpuic_prep_image.restype = None
            _lib = lib
        except OSError:
            _lib = None
        return _lib


def available() -> bool:
    return _load() is not None


COLOR_NONE, COLOR_SATURATION, COLOR_BRIGHTNESS, COLOR_CONTRAST = 0, 1, 2, 3


def prep_image(img: np.ndarray, size: int, *, rot_k: int = 0,
               vflip: bool = False, hflip: bool = False,
               color_op: int = COLOR_NONE, factor: float = 1.0,
               mean=None, std=None) -> Optional[np.ndarray]:
    """Fused resize+augment+normalize. img: HWC uint8 (C-contiguous).
    Returns [size, size, 3] float32, or None when the native core is
    unavailable (caller falls back to NumPy transforms)."""
    lib = _load()
    if lib is None:
        return None
    from tpuic.data.transforms import IMAGENET_MEAN, IMAGENET_STD
    img = np.ascontiguousarray(img, np.uint8)
    assert img.ndim == 3 and img.shape[2] == 3, img.shape
    mean = np.ascontiguousarray(
        IMAGENET_MEAN if mean is None else mean, np.float32)
    std = np.ascontiguousarray(
        IMAGENET_STD if std is None else std, np.float32)
    out = np.empty((size, size, 3), np.float32)
    lib.tpuic_prep_image(
        img.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        img.shape[0], img.shape[1],
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), size,
        int(rot_k) & 3, int(bool(vflip)), int(bool(hflip)), int(color_op),
        float(factor),
        mean.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        std.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
    return out
