"""Canary rollout driver: staged traffic shift, SLO-burn auto-rollback.

The last unbuilt piece of the serve-at-fleet-scale story (ROADMAP item
3's stretch goal): weights change **under load** without dropping a
request.  This module drives the PR-12 router through a complete model
lifecycle (docs/serving.md, "Model lifecycle: hot-swap, canary,
rollback"):

1. **Gate** — the CANDIDATE is hot-swapped onto one canary replica via
   the router's control channel (``{"op": "swap", ...}`` —
   serve/__main__.py).  The replica's swap-time admission gates run
   *there*, pre-flip: the checkpoint CRC/manifest integrity ladder and
   the pinned-eval accuracy gate.  A typed refusal
   (:class:`~tpuic.serve.admission.SwapRejected`, cause
   ``swap_corrupt``/``swap_accuracy``) ends the rollout before ONE
   request ever saw the candidate.
2. **Canary** — the router's traffic split shifts a staged fraction
   (e.g. 5% → 50% → 100%) onto the canary while the driver watches two
   signals: a named SLO objective's **error-budget burn rate** over the
   canary's resolved latencies (``telemetry/slo.py``, reused verbatim —
   the same attainment/burn arithmetic the serve tier reports), and the
   canary's **typed-error ledger** (untyped errors on the canary are an
   immediate rollback; typed sheds are normal overload behavior).
3. **Promote** — every stage held healthy: the remaining replicas swap
   to the candidate (traffic is 100% on the canary while they flip, so
   promotion is also zero-drain), the candidate digest becomes THE
   fleet digest, and the split clears.
4. **Auto-rollback on burn** — sustained burn at/over the threshold
   (``rollback_after`` consecutive polls — hysteresis, one bad sample
   must not flap a rollout), a canary error, or a stage that times out
   without evidence: the candidate digest is **disallowed first** (the
   router's identity gate refuses the canary even if the swap-back
   fails), the split clears, and the canary hot-swaps BACK to the
   incumbent — rollback is itself a zero-drain swap.

Like the router it drives, this module is **stdlib-only** (the
supervisor-parent rule): ``telemetry/slo.py`` and the pinned quantile
helper import no jax/numpy, so the driver can outlive any backend
wedge its replicas hit.  Verdicts, stages, and rollbacks land as
``rollout`` events in the router ledger JSONL and as ``tpuic_rollout_*``
rows in the prom exposition (telemetry/prom.py).

CLI::

    python -m tpuic.serve.rollout \\
        --replica-cmd '...python -m tpuic.serve --synthetic-init ...' \\
        --replicas 2 --candidate '{"ckpt_dir": "cp2", "track": "best"}' \\
        --incumbent '{"ckpt_dir": "cp", "track": "best"}' \\
        --slo 'serve_latency:p99<=250ms' --stages 0.05,0.5,1.0

Client traffic rides stdin exactly like ``python -m tpuic.serve.router``
(the rollout needs live traffic: a stage without samples never
promotes — no evidence, no flip).  Exit code 0 = promoted; 2 = refused
/ rolled back / aborted (the verdict JSON lands on stdout either way).
"""

from __future__ import annotations

import json
import sys
import threading
import time
from typing import Dict, List, Optional

from tpuic.serve.admission import AdmissionError
from tpuic.serve.router import UP, Router
from tpuic.telemetry.events import Event
from tpuic.telemetry.slo import SLOTracker, parse_objective

VERDICTS = ("promoted", "rolled_back", "refused", "aborted")


class CanaryRollout:
    """One staged rollout of ``candidate`` across ``router``'s fleet.

    ``candidate`` / ``incumbent`` are swap-line payloads (everything but
    ``op``/``id`` of a ``{"op": "swap"}`` control line — e.g.
    ``{"ckpt_dir": ..., "track": ...}`` or ``{"synthetic_seed": N}``);
    the incumbent payload is what a rollback swaps BACK to, so it must
    describe the weights the fleet is serving now.

    ``objective`` is a ``telemetry/slo.py`` spec over ``serve_latency``
    (e.g. ``serve_latency:p99<=250ms``) scored on the CANARY's resolved
    latencies only — a 5% canary serving garbage moves fleet-wide p99
    by epsilon, and canary-scoped burn is the signal operators canary
    for.  Rollback triggers on ``rollback_after`` consecutive polls at
    burn >= ``burn_rollback``.

    A stage advances once it has been held ``hold_s`` seconds with at
    least ``min_samples`` canary samples in the window and burn below
    the rollback threshold; a stage exceeding ``stage_timeout_s``
    without advancing rolls back (**no evidence, no promote** — an idle
    fleet must not wave a candidate through).
    """

    def __init__(self, router: Router, candidate: Dict,
                 incumbent: Dict, *,
                 objective: str = "serve_latency:p99<=250ms",
                 stages=(0.05, 0.5, 1.0), hold_s: float = 5.0,
                 min_samples: int = 40, burn_rollback: float = 2.0,
                 rollback_after: int = 2, poll_s: float = 0.25,
                 stage_timeout_s: float = 120.0,
                 swap_timeout_s: float = 300.0,
                 canary: Optional[str] = None,
                 log=None) -> None:
        self.router = router
        self.candidate = {k: v for k, v in dict(candidate).items()
                          if k not in ("op", "id")}
        self.incumbent = {k: v for k, v in dict(incumbent).items()
                         if k not in ("op", "id")}
        self.objective = parse_objective(objective,
                                         allowed=("serve_latency",))
        self.stages = tuple(float(s) for s in stages)
        if not self.stages or any(not 0.0 < s <= 1.0
                                  for s in self.stages):
            raise ValueError(f"stages must be fractions in (0, 1], got "
                             f"{self.stages}")
        self.hold_s = float(hold_s)
        self.min_samples = max(1, int(min_samples))
        self.burn_rollback = float(burn_rollback)
        self.rollback_after = max(1, int(rollback_after))
        self.poll_s = max(0.02, float(poll_s))
        self.stage_timeout_s = float(stage_timeout_s)
        self.swap_timeout_s = float(swap_timeout_s)
        self.canary_name = canary
        self._log = log or (lambda m: print(f"[rollout] {m}",
                                            file=sys.stderr, flush=True))
        self._lock = threading.Lock()
        self._watching = False
        self._canary: Optional[str] = None
        self._canary_errors = 0
        self._last_burn: Optional[float] = None
        self._phase = "idle"
        self._stage_idx = -1
        self._stage_frac = 0.0
        self._verdict: Optional[dict] = None
        # slo.py reused verbatim: the same SLOTracker the serve tier
        # runs, fed canary-scoped serve_span events from the router's
        # outcome hook.  publish=no-op — reports land in OUR ledger.
        self._tracker = SLOTracker([self.objective],
                                   publish=lambda *a, **k: None)
        self._prev_hook = None

    # -- telemetry -----------------------------------------------------
    def _publish(self, action: str, **data) -> None:
        self.router._publish("rollout", action=action, **data)

    def state(self) -> dict:
        """JSON-able live state — the ``tpuic_rollout_*`` prom rows."""
        with self._lock:
            rep = self._tracker.report()["objectives"][0]
            return {
                "phase": self._phase,
                "stage_index": self._stage_idx,
                "stage_fraction": self._stage_frac,
                "canary": self._canary,
                "objective": self.objective.name,
                "burn_rate": rep["burn_rate"],
                "canary_window_samples": rep["window_samples"],
                "canary_errors": self._canary_errors,
                "verdict": (self._verdict or {}).get("verdict"),
            }

    # -- canary-scoped SLO feed ----------------------------------------
    def _hook(self, replica: str, kind: str,
              latency_s: Optional[float]) -> None:
        with self._lock:
            watching = self._watching and replica == self._canary
        if not watching:
            pass
        elif kind == "resolved" and latency_s is not None:
            self._tracker.on_event(Event(
                kind="serve_span", time=time.time(),
                data={"total_ms": 1000.0 * latency_s}))
        elif kind == "error":
            with self._lock:
                self._canary_errors += 1
        if self._prev_hook is not None:
            self._prev_hook(replica, kind, latency_s)

    # -- the rollout ----------------------------------------------------
    def run(self) -> dict:
        """Drive the full lifecycle; returns the verdict dict
        (``verdict`` in :data:`VERDICTS` plus attribution fields)."""
        self._prev_hook = self.router.outcome_hook
        self.router.outcome_hook = self._hook
        try:
            return self._run()
        finally:
            self.router.outcome_hook = self._prev_hook

    def _finish(self, verdict: dict) -> dict:
        with self._lock:
            self._phase = verdict["verdict"]
            self._verdict = verdict
        self._publish("done", **{k: v for k, v in verdict.items()
                                 if isinstance(v, (str, int, float,
                                                   bool, type(None)))})
        self._log(f"verdict: {json.dumps(verdict)}")
        return verdict

    def _pick_canary(self) -> Optional[str]:
        if self.canary_name:
            return self.canary_name
        for rep in self.router.replicas:
            if rep.state == UP:
                return rep.name
        return None

    def _swap(self, replica: str, payload: Dict) -> dict:
        return self.router.control_request(
            replica, {"op": "swap", **payload},
            timeout_s=self.swap_timeout_s)

    def _run(self) -> dict:
        canary = self._pick_canary()
        if canary is None:
            return self._finish({"verdict": "aborted",
                                 "reason": "no_up_replica"})
        with self._lock:
            self._canary = canary
            self._phase = "gating"
        # The identity gate MUST know the incumbent digest before the
        # canary flips: adopt-first-seen would otherwise crown the
        # CANDIDATE as the fleet digest (flagging every incumbent), and
        # a later rollback's disallow would empty the allowed set —
        # total outage.  Pongs carry it within one ping interval; no
        # digest after the grace window = no rollout (abort is
        # zero-impact: nothing was swapped, nothing was shifted).
        deadline = time.monotonic() + 10.0
        while (self.router.fleet_digest is None
               and time.monotonic() < deadline):
            time.sleep(0.05)
        incumbent_digest = self.router.fleet_digest
        if incumbent_digest is None:
            self._publish("refused", canary=canary, cause=None,
                          error="fleet digest unknown")
            return self._finish({
                "verdict": "aborted", "canary": canary,
                "reason": "no_fleet_digest"})
        self._publish("start", canary=canary,
                      objective=self.objective.name,
                      stages=list(self.stages),
                      incumbent_digest=incumbent_digest)
        self._log(f"canary {canary}: gating candidate "
                  f"{json.dumps(self.candidate)}")
        try:
            resp = self._swap(canary, self.candidate)
        except AdmissionError as e:
            # Typed refusal (swap_corrupt / swap_accuracy / a dying
            # canary): the candidate never reached traffic.
            self._publish("refused", canary=canary,
                          cause=getattr(e, "cause", None), error=str(e))
            return self._finish({
                "verdict": "refused", "canary": canary,
                "cause": getattr(e, "cause", None), "error": str(e)})
        except Exception as e:  # noqa: BLE001 — transport/timeout
            self._publish("refused", canary=canary, cause=None,
                          error=str(e))
            return self._finish({
                "verdict": "aborted", "canary": canary,
                "reason": "swap_failed", "error": str(e)})
        new_digest = str(resp.get("digest", ""))
        self.router.allow_digest(new_digest)
        with self._lock:
            self._watching = True
            self._phase = "canary"
        self._log(f"canary {canary}: candidate live (generation "
                  f"{resp.get('generation')}, digest {new_digest}, "
                  f"reused_executables={resp.get('reused_executables')})")

        promoted: List[str] = []
        for i, frac in enumerate(self.stages):
            with self._lock:
                self._stage_idx, self._stage_frac = i, frac
            self.router.set_traffic_split({canary}, frac)
            self._publish("stage", index=i, fraction=frac,
                          canary=canary)
            self._log(f"stage {i}: {100 * frac:g}% of traffic -> "
                      f"{canary}")
            t_stage = time.monotonic()
            streak = 0
            while True:
                time.sleep(self.poll_s)
                rep = self._tracker.report()["objectives"][0]
                burn = rep["burn_rate"]
                samples = rep["window_samples"]
                with self._lock:
                    errors = self._canary_errors
                    self._last_burn = burn
                if errors:
                    return self._rollback(
                        canary, new_digest, incumbent_digest, promoted,
                        reason="canary_errors", burn=burn,
                        errors=errors)
                if burn is not None and burn >= self.burn_rollback:
                    streak += 1
                    if streak >= self.rollback_after:
                        return self._rollback(
                            canary, new_digest, incumbent_digest,
                            promoted, reason="slo_burn", burn=burn,
                            samples=samples)
                else:
                    streak = 0
                held = time.monotonic() - t_stage
                if (held >= self.hold_s and samples >= self.min_samples
                        and burn is not None
                        and burn < self.burn_rollback):
                    break  # stage healthy: advance
                if held > self.stage_timeout_s:
                    # No evidence, no promote: an idle fleet must not
                    # wave a candidate through to 100%.
                    return self._rollback(
                        canary, new_digest, incumbent_digest, promoted,
                        reason="stage_timeout", burn=burn,
                        samples=samples)

        # Promote: traffic is 100% on the canary, so the remaining
        # replicas flip idle — promotion is zero-drain too.
        with self._lock:
            self._phase = "promoting"
        skipped: List[str] = []
        for rep in self.router.replicas:
            if rep.name == canary:
                continue
            if rep.state != UP:
                # Down/respawning mid-rollout: it cannot take a swap
                # line now, and when it comes back it boots the
                # INCUMBENT weights — handled below.
                skipped.append(rep.name)
                continue
            try:
                self._swap(rep.name, self.candidate)
                promoted.append(rep.name)
                self._log(f"promoted {rep.name}")
            except Exception as e:  # noqa: BLE001 — typed or transport
                self._publish("promote_failed", replica=rep.name,
                              error=str(e))
                return self._rollback(
                    canary, new_digest, incumbent_digest, promoted,
                    reason="promote_failed", failed_replica=rep.name,
                    error=str(e))
        self.router.set_fleet_digest(new_digest)
        if skipped and incumbent_digest:
            # A replica skipped here respawns on the BOOT (incumbent)
            # weights; with only the candidate digest authorized it
            # would rejoin permanently unroutable — silent capacity
            # loss behind a "promoted" verdict.  Keep the incumbent
            # digest authorized too: the fleet is explicitly, VISIBLY
            # heterogeneous (per-replica model_info rows + this ledger
            # event) until the operator re-swaps or respawns it,
            # instead of silently smaller.
            self.router.allow_digest(incumbent_digest)
            self._publish("promote_partial", skipped=skipped,
                          incumbent_digest=incumbent_digest)
            self._log(f"partial promotion: {skipped} not promoted "
                      f"(not up) — incumbent digest "
                      f"{incumbent_digest} stays authorized so they "
                      "rejoin routable; re-run the rollout (or swap "
                      "them) to converge")
        self.router.clear_traffic_split()
        rep = self._tracker.report()["objectives"][0]
        self._publish("promote", canary=canary, digest=new_digest,
                      promoted=promoted, skipped=skipped,
                      burn_rate=rep["burn_rate"],
                      samples=rep["window_samples"])
        return self._finish({
            "verdict": "promoted", "canary": canary,
            "digest": new_digest, "promoted": promoted,
            "skipped": skipped,
            "burn_rate": rep["burn_rate"],
            "canary_samples": rep["window_samples"]})

    def _rollback(self, canary: str, new_digest: str,
                  incumbent_digest: Optional[str], promoted: List[str],
                  *, reason: str, **attrib) -> dict:
        """Zero-drain rollback: disallow the candidate digest FIRST
        (the identity gate refuses the canary even if the swap-back
        fails), clear the split, then hot-swap every candidate-serving
        replica back to the incumbent."""
        with self._lock:
            self._phase = "rolling_back"
            self._watching = False
        self._publish("rollback", canary=canary, reason=reason,
                      digest=new_digest, promoted=promoted, **attrib)
        self._log(f"ROLLBACK ({reason}): disallowing {new_digest}, "
                  f"swapping {[canary] + promoted} back")
        if new_digest and new_digest != incumbent_digest:
            self.router.disallow_digest(new_digest)
        self.router.clear_traffic_split()
        swap_back_failed = []
        for name in [canary] + promoted:
            try:
                self._swap(name, self.incumbent)
            except Exception as e:  # noqa: BLE001
                # The identity gate already refuses this replica; it
                # serves nothing until an operator (or respawn) fixes
                # it — degraded capacity, never degraded answers.
                swap_back_failed.append(name)
                self._publish("rollback_swap_failed", replica=name,
                              error=str(e))
                self._log(f"rollback swap-back FAILED on {name}: {e} "
                          "(digest gate keeps it out of traffic)")
        return self._finish({
            "verdict": "rolled_back", "reason": reason,
            "canary": canary, "digest": new_digest,
            "swap_back_failed": swap_back_failed, **attrib})


# -- CLI ---------------------------------------------------------------------
def _parse_line_payload(spec: str, what: str) -> Dict:
    try:
        out = json.loads(spec)
        if not isinstance(out, dict):
            raise ValueError("not an object")
        return out
    except ValueError as e:
        raise SystemExit(f"rollout: --{what} must be a JSON object "
                         f"(swap-line payload): {e}")


def main(argv=None) -> int:
    """``python -m tpuic.serve.rollout`` — a router CLI that also
    drives one canary rollout (module docstring)."""
    import argparse
    import shlex

    p = argparse.ArgumentParser(
        description="Canary rollout driver over a replica fleet "
                    "(docs/serving.md, 'Model lifecycle')")
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--replica-cmd", default="",
                   help="replica command template (see "
                        "python -m tpuic.serve.router)")
    p.add_argument("--attach", action="append", default=[],
                   metavar="HOST:PORT[:PROMPORT]")
    p.add_argument("--state-dir", default="rollout-state")
    p.add_argument("--candidate", required=True,
                   help="swap-line payload JSON for the candidate, "
                        "e.g. '{\"ckpt_dir\": \"cp2\", \"track\": "
                        "\"best\"}' or '{\"synthetic_seed\": 1}'")
    p.add_argument("--incumbent", required=True,
                   help="swap-line payload JSON describing the weights "
                        "the fleet serves NOW — what a rollback swaps "
                        "back to")
    p.add_argument("--slo", default="serve_latency:p99<=250ms",
                   help="SLO objective spec scored on the canary's "
                        "resolved latencies (telemetry/slo.py grammar)")
    p.add_argument("--stages", default="0.05,0.5,1.0",
                   help="comma list of traffic fractions per stage")
    p.add_argument("--hold-s", type=float, default=5.0)
    p.add_argument("--min-samples", type=int, default=40)
    p.add_argument("--burn-rollback", type=float, default=2.0,
                   help="burn rate at/above which (for --rollback-after "
                        "consecutive polls) the rollout auto-rolls-back")
    p.add_argument("--rollback-after", type=int, default=2)
    p.add_argument("--poll-s", type=float, default=0.25)
    p.add_argument("--stage-timeout-s", type=float, default=120.0)
    p.add_argument("--canary", default="",
                   help="replica name to canary on (default: first up)")
    p.add_argument("--knee-rps", type=float, default=0.0)
    p.add_argument("--spill-inflight", type=int, default=0)
    p.add_argument("--spawn-timeout-s", type=float, default=300.0)
    p.add_argument("--drain-timeout", type=float, default=30.0)
    p.add_argument("--prom-port", type=int, default=0)
    p.add_argument("--prom-host", default="127.0.0.1")
    p.add_argument("--out", default="")
    args = p.parse_args(argv)

    candidate = _parse_line_payload(args.candidate, "candidate")
    incumbent = _parse_line_payload(args.incumbent, "incumbent")
    attach = []
    for spec in args.attach:
        parts = spec.split(":")
        if len(parts) < 2:
            raise SystemExit(f"rollout: bad --attach {spec!r}")
        attach.append((parts[0], int(parts[1]),
                       int(parts[2]) if len(parts) > 2 else None))
    cmd = shlex.split(args.replica_cmd) if args.replica_cmd else None
    if not cmd and not attach:
        raise SystemExit("rollout: need --replica-cmd and/or --attach")

    import signal

    from tpuic.runtime.preemption import PreemptionGuard
    from tpuic.runtime.supervisor import HeartbeatWriter
    from tpuic.serve.router import make_line_handler, pump_stdin
    from tpuic.telemetry.prom import PromServer, router_exposition
    guard = PreemptionGuard(signals=(signal.SIGTERM,)).install()
    heartbeat = HeartbeatWriter.from_env()

    router = Router(
        replica_cmd=cmd, n_replicas=args.replicas, attach=attach,
        state_dir=args.state_dir, knee_rps=args.knee_rps,
        spill_inflight=args.spill_inflight,
        spawn_timeout_s=args.spawn_timeout_s,
        drain_timeout_s=args.drain_timeout)
    router.start()
    rollout = CanaryRollout(
        router, candidate, incumbent, objective=args.slo,
        stages=[float(s) for s in args.stages.split(",") if s.strip()],
        hold_s=args.hold_s, min_samples=args.min_samples,
        burn_rollback=args.burn_rollback,
        rollback_after=args.rollback_after, poll_s=args.poll_s,
        stage_timeout_s=args.stage_timeout_s,
        canary=args.canary or None)

    prom_server = None
    if args.prom_port:
        prom_server = PromServer(
            args.prom_port,
            lambda: router_exposition(router.snapshot(),
                                      rollout=rollout.state()),
            host=args.prom_host)
        print(f"[rollout] prometheus /metrics on "
              f"{args.prom_host}:{prom_server.port}", file=sys.stderr)

    verdict_box: Dict = {}

    def _drive() -> None:
        try:
            verdict_box["verdict"] = rollout.run()
        except Exception as e:  # noqa: BLE001 — a crash is an abort
            verdict_box["verdict"] = {"verdict": "aborted",
                                      "reason": "driver_error",
                                      "error": str(e)}

    driver = threading.Thread(target=_drive, daemon=True,
                              name="tpuic-rollout")
    driver.start()

    out = open(args.out, "w") if args.out else sys.stdout
    out_lock = threading.Lock()
    handle = make_line_handler(router, out, out_lock)
    try:
        pump_stdin(handle, guard,
                   beat=(heartbeat.beat if heartbeat is not None
                         else None))
        # stdin closed: the rollout may still be mid-stage — let it
        # finish against whatever traffic is still in flight.
        driver.join(timeout=args.stage_timeout_s * (len(rollout.stages)
                                                    + 2))
    except KeyboardInterrupt:
        pass
    finally:
        guard.uninstall()
        router.drain(args.drain_timeout)
        router.close(drain=False)
        if prom_server is not None:
            prom_server.close()
        verdict = verdict_box.get("verdict") or {
            "verdict": "aborted", "reason": "interrupted"}
        with out_lock:
            out.write(json.dumps({"op": "rollout_verdict",
                                  **verdict}) + "\n")
            out.flush()
        print(f"[rollout] done: {json.dumps(router.snapshot())}",
              file=sys.stderr)
        if out is not sys.stdout:
            out.close()
    return 0 if verdict.get("verdict") == "promoted" else 2


if __name__ == "__main__":
    sys.exit(main())
