"""``python -m tpuic.serve`` — online inference driver, no network needed.

Two request sources, both feeding the same InferenceEngine:

- **stdin JSONL** (default): one request per line,
  ``{"id": "r1", "path": "img.png"}`` (``id`` optional, defaults to the
  path).  Responses stream to --out (default stdout) as JSONL:
  ``{"id", "pred", "prob", "topk": [[name, prob], ...]}``.
- **directory watch** (``--watch DIR``): polls DIR for new image files
  and classifies each once; ``--once`` processes the current contents
  and exits (the tier-1-testable mode).

Decode (PIL) of request N+1 overlaps the device call for batch N: the
driver only *submits* work and drains completed futures opportunistically
— the engine's batcher thread owns the device.

    python -m tpuic.serve --ckpt-dir dtmodel/cp --model auto < reqs.jsonl
    python -m tpuic.serve --ckpt-dir dtmodel/cp --watch incoming/ --once

A final stats line (queue wait, pad efficiency, bucket histogram,
latency percentiles, compile counts) goes to stderr on shutdown.

Graceful shutdown (docs/robustness.md): SIGTERM/SIGINT latch a
PreemptionGuard (the trainer's mechanism, runtime/preemption.py) instead
of killing the process mid-batch — the driver stops accepting requests,
drains everything in flight for up to ``--drain-timeout`` seconds
(stragglers get a per-request error line, never a silent drop), closes
the engine, and exits 0. A scheduler eviction loses zero accepted
requests that the device can finish inside the grace window.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from collections import deque
from concurrent.futures import TimeoutError as _FutTimeout

import numpy as np

from tpuic.serve.admission import AdmissionError  # stdlib-only import


def _load_image(path: str, size: int) -> np.ndarray:
    """Decode + resize EXACTLY like the training/predict pipeline
    (folder.py -> transforms.resize_nearest): the checkpoint's val
    accuracy was measured on nearest-resized pixels, and serving the
    same image through a different interpolation would silently shift
    predictions relative to `python -m tpuic.predict`."""
    from PIL import Image

    from tpuic.data.transforms import resize_nearest
    img = np.asarray(Image.open(path).convert("RGB"), np.uint8)
    return resize_nearest(img, size)


def _class_names(ckpt_dir: str, model: str, num_classes: int,
                 classes_file: str) -> dict:
    """index -> display name: --classes file (one name per line) wins,
    else the class_to_idx.json sidecar the Trainer writes, else indices."""
    names = {i: str(i) for i in range(num_classes)}
    if classes_file:
        with open(classes_file) as f:
            for i, line in enumerate(ln.strip() for ln in f):
                if line:
                    names[i] = line
        return names
    sidecar = os.path.join(ckpt_dir, model, "class_to_idx.json")
    try:
        with open(sidecar) as f:
            names.update({int(v): k for k, v in json.load(f).items()})
    except (OSError, ValueError):
        pass
    return names


def build_engine(args):
    """Checkpoint -> warmed InferenceEngine (shared predict loading rules)."""
    if args.compile_cache_dir:
        # Persistent XLA compilation cache: warmup's per-bucket AOT
        # compiles land on disk, so a server RESTART warms up from cache
        # instead of recompiling (same mechanism the test suite and
        # bench.py use).
        import jax
        cache = os.path.expanduser(args.compile_cache_dir)
        os.makedirs(cache, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

    from tpuic.checkpoint.loading import load_inference_variables
    from tpuic.config import (Config, DataConfig, ModelConfig, OptimConfig,
                              RunConfig)
    from tpuic.predict import resolve_model_auto
    from tpuic.serve import InferenceEngine

    model_name, num_classes, resize = args.model, args.num_classes, args.resize
    ema_decay = 0.0
    if model_name == "auto":
        saved = resolve_model_auto(args.ckpt_dir)
        model_name = saved["name"]
        num_classes = num_classes or saved["num_classes"]
        ema_decay = saved["ema_decay"]
        if resize is None:
            resize = saved["resize_size"]
        print(f"[serve] auto-resolved model '{model_name}' "
              f"(num_classes={num_classes}, resize={resize})",
              file=sys.stderr)
    elif not args.init_from:
        # Explicit --model: still honor THIS model's config.json sidecar
        # for ema_decay (same rule as tpuic.predict) — an EMA-trained
        # checkpoint must serve its EMA weights (the ones 'best' was
        # selected on), not silently fall back to the raw params.
        sidecar = os.path.join(args.ckpt_dir, model_name, "config.json")
        try:
            with open(sidecar) as f:
                ema_decay = float(
                    json.load(f).get("optim", {}).get("ema_decay", 0.0))
        except (OSError, ValueError, TypeError):
            # Absent or corrupt sidecar (non-atomic trainer write) falls
            # back to raw params, same as _class_names' fallback.
            pass
    if resize is None:
        resize = 299
    if num_classes <= 0:
        raise SystemExit("serve: --num-classes required (or --model auto "
                         "with a config.json sidecar)")
    cfg = Config(
        data=DataConfig(data_dir=".", resize_size=resize),
        model=ModelConfig(name=model_name, num_classes=num_classes),
        optim=OptimConfig(ema_decay=ema_decay),
        run=RunConfig(ckpt_dir=args.ckpt_dir, init_from=args.init_from),
    )
    model, variables = load_inference_variables(
        cfg, track=args.track, log=lambda *a: print("[serve]", *a,
                                                    file=sys.stderr))
    buckets = tuple(int(b) for b in args.buckets.split(","))
    # Raw uint8 in, normalize fused into the compiled forward (4x less
    # H2D than shipping float32 — the device_prep lesson).
    engine = InferenceEngine(
        model, variables, image_size=resize, input_dtype=np.uint8,
        normalize=True, mean=cfg.data.mean, std=cfg.data.std,
        buckets=buckets, max_wait_ms=args.max_wait_ms,
        queue_size=args.queue_size)
    t = engine.warmup()
    print(f"[serve] warmup compiled {len(t)} bucket executables: {t}",
          file=sys.stderr)
    return engine, resize, num_classes, model_name


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Dynamic-batching inference server (stdin JSONL or "
                    "directory watch)")
    p.add_argument("--ckpt-dir", default="dtmodel/cp")
    p.add_argument("--model", default="auto")
    p.add_argument("--num-classes", type=int, default=0)
    p.add_argument("--resize", type=int, default=None)
    p.add_argument("--track", default="best", choices=("best", "latest"))
    p.add_argument("--init-from", default="",
                   help="torch checkpoint instead of a tpuic one")
    p.add_argument("--buckets", default="1,8,32,128",
                   help="padding-bucket ladder (comma list)")
    p.add_argument("--max-wait-ms", type=float, default=5.0)
    p.add_argument("--queue-size", type=int, default=256)
    p.add_argument("--compile-cache-dir", default="~/.cache/tpuic/xla",
                   help="persistent XLA compile cache (restarts warm up "
                        "from disk); empty string disables")
    p.add_argument("--top-k", type=int, default=1)
    p.add_argument("--classes", default="",
                   help="optional file of class names, one per line")
    p.add_argument("--watch", default="",
                   help="watch this directory for images instead of stdin")
    p.add_argument("--poll-s", type=float, default=0.5)
    p.add_argument("--once", action="store_true",
                   help="with --watch: process current files, then exit")
    p.add_argument("--out", default="", help="output JSONL (default stdout)")
    p.add_argument("--drain-timeout", type=float, default=30.0,
                   help="on SIGTERM/SIGINT, wait up to this many seconds "
                        "for in-flight requests before failing stragglers "
                        "with an error line and exiting")
    p.add_argument("--prom-port", type=int, default=0,
                   help="serve a Prometheus /metrics endpoint on this "
                        "port (queue wait, pad efficiency, latency "
                        "percentiles from the shared meter; 0 disables)")
    p.add_argument("--prom-host", default="127.0.0.1",
                   help="interface for --prom-port (loopback by default "
                        "— the endpoint is unauthenticated; bind "
                        "0.0.0.0 only behind a firewall)")
    p.add_argument("--prom-dump", default="",
                   help="write the Prometheus text exposition to this "
                        "file on shutdown (and each poll tick under "
                        "--watch) — the textfile-collector transport")
    p.add_argument("--slo", default="",
                   help="latency SLOs, comma list of "
                        "'serve_latency:pQ<=Nms[@target]' specs "
                        "(telemetry/slo.py). Subscribing the tracker is "
                        "what switches per-request span events on; "
                        "attainment and error-budget burn land in the "
                        "Prometheus exposition and the final stats line")
    p.add_argument("--admission", action="store_true",
                   help="SLA-aware admission control (docs/serving.md): "
                        "request lines may carry priority/deadline_ms/"
                        "tenant; a full queue rejects with a typed, "
                        "cause-labeled error line instead of blocking "
                        "the accept loop, higher priority classes are "
                        "batched first (and evict lower ones from a "
                        "full queue), and expired deadlines shed at "
                        "pop time")
    p.add_argument("--quota", action="append", default=[],
                   metavar="TENANT=RPS",
                   help="per-tenant token-bucket quota in requests/sec "
                        "(repeatable, or one comma list); '*=RPS' sets "
                        "the shared free pool unconfigured tenants and "
                        "dry tenant buckets draw from. Implies "
                        "--admission")
    p.add_argument("--brownout-slo", default="",
                   help="name of one --slo objective (e.g. "
                        "serve_latency_p99) whose error-budget burn "
                        "rate drives brownout: past --brownout-tighten "
                        "the controller sheds one priority class per "
                        "SLO report, recovering hysteretically below "
                        "--brownout-recover. Implies --admission")
    p.add_argument("--brownout-tighten", type=float, default=2.0,
                   help="burn rate at/above which brownout tightens "
                        "one level")
    p.add_argument("--brownout-recover", type=float, default=1.0,
                   help="burn rate at/below which (after 3 consecutive "
                        "reports) brownout relaxes one level")
    args = p.parse_args(argv)
    if args.quota or args.brownout_slo:
        args.admission = True

    slo_tracker = None
    if args.slo:
        # Parse BEFORE the checkpoint load + AOT warmup — a typo'd
        # objective must fail the command line, not minutes in.
        from tpuic.telemetry.slo import SLOTracker, parse_objectives
        try:
            slo_tracker = SLOTracker(parse_objectives(
                args.slo, allowed=("serve_latency",)))
        except ValueError as e:
            raise SystemExit(f"serve: --slo: {e}")

    # Admission config parses up front too (same fail-fast rule): a
    # typo'd quota would read as "unlimited" exactly when you meant to
    # cap someone, and a brownout coupled to an objective --slo never
    # tracks would silently never tighten.
    admission_ctl = None
    if args.admission:
        from tpuic.serve.admission import (AdmissionController,
                                           BrownoutController, parse_quotas)
        try:
            quotas = parse_quotas(args.quota)
        except ValueError as e:
            raise SystemExit(f"serve: --quota: {e}")
        brownout = None
        if args.brownout_slo:
            known = ([o.name for o in slo_tracker.objectives]
                     if slo_tracker is not None else [])
            if args.brownout_slo not in known:
                raise SystemExit(
                    f"serve: --brownout-slo {args.brownout_slo!r} names "
                    f"no --slo objective (configured: "
                    f"{', '.join(known) or 'none'}) — brownout would "
                    "never see a burn rate")
            brownout = BrownoutController(
                args.brownout_slo, tighten_above=args.brownout_tighten,
                recover_below=args.brownout_recover)
        admission_ctl = AdmissionController(quotas, brownout=brownout)

    # Install the latch BEFORE the (potentially minutes-long) checkpoint
    # load + AOT warmup: an eviction during startup must also exit
    # cleanly, not dump a traceback from inside a compile.
    import signal

    from tpuic.runtime.preemption import PreemptionGuard
    guard = PreemptionGuard(signals=(signal.SIGTERM,)).install()

    if args.classes and not os.path.isfile(args.classes):
        # Validate BEFORE the checkpoint load + per-bucket AOT warmup —
        # a typo'd path must not cost minutes of startup first.
        raise SystemExit(f"serve: --classes file not found: {args.classes}")
    engine, size, num_classes, model_name = build_engine(args)
    names = _class_names(args.ckpt_dir, model_name, num_classes,
                         args.classes)

    # Prometheus exposition (telemetry/prom.py): counters come straight
    # from engine.stats — the shared LatencyMeter percentiles, pad
    # efficiency, bucket histogram, compile counts.
    from tpuic.telemetry.prom import (PromServer, serve_exposition,
                                      write_exposition)

    # Supervised liveness (runtime/supervisor.py, docs/robustness.md):
    # under `python -m tpuic.supervise` the parent sets the heartbeat
    # env; mirror engine activity (serve_batch events) into the file AND
    # tick it from the accept loop — an idle server with no requests is
    # alive, and the watchdog must see that, not a stale file. The
    # flight recorder (telemetry/flight.py) registers its SIGQUIT dump
    # FIRST so the faulthandler stack dump chains into it: the
    # supervisor's hang escalation then captures stacks + the event
    # timeline (serve_batch/admission/slo — memory samples are
    # scrape-side only here, see the sampler below) leading into the
    # wedge.
    from tpuic.runtime.supervisor import (HeartbeatWriter,
                                          install_stack_dump_handler)
    from tpuic.telemetry.flight import install_flight_recorder
    flight = install_flight_recorder()
    heartbeat = HeartbeatWriter.from_env()
    if heartbeat is not None or flight is not None:
        install_stack_dump_handler(chain=flight is not None)
    if heartbeat is not None:
        from tpuic.telemetry.events import bus as _bus
        _bus.subscribe(heartbeat)

    def _beat() -> None:
        if heartbeat is not None:
            heartbeat.beat()

    if slo_tracker is not None:
        # Attaching subscribes for 'serve_span' events, which is exactly
        # what turns the engine's per-request span publishing on
        # (engine._resolve checks bus.active("serve_span")).
        from tpuic.telemetry.events import bus as _slo_bus
        slo_tracker.attach(_slo_bus)

    if admission_ctl is not None:
        # Post-build attach (engine.admission is a public, settable
        # field): submit() now consults brownout + quotas up front.
        engine.admission = admission_ctl
        if admission_ctl.brownout is not None:
            # Brownout rides the same bus the SLO tracker publishes its
            # periodic reports on; its tighten/recover transitions come
            # back as 'admission' events (JSONL/TensorBoard sinks).
            from tpuic.telemetry.events import bus as _adm_bus
            admission_ctl.brownout.attach(_adm_bus)
        print(f"[serve] admission control on: "
              f"{json.dumps(admission_ctl.state())}", file=sys.stderr)

    # Device-memory accounting (telemetry/memory.py): sampled at scrape
    # time (each /metrics hit, each --prom-dump tick, and shutdown) —
    # the serve tier has no step boundary, and a scrape-time metadata
    # read is free of the request path entirely. Deliberately NOT
    # published to the bus: scrapes run in the PromServer thread at the
    # scraper's cadence, and the supervised-liveness heartbeat treats
    # any bus activity as proof of life — an external scraper must not
    # keep a wedged server looking alive to the watchdog.
    from tpuic.telemetry.memory import MemorySampler
    mem_sampler = MemorySampler(publish=lambda *a, **kw: None)

    def _prom_text() -> str:
        mem_sampler.sample()
        return serve_exposition(
            engine.stats.snapshot(),
            heartbeat_age_s=(heartbeat.age_s() if heartbeat is not None
                             else None),
            slo=(slo_tracker.report() if slo_tracker is not None
                 else None),
            admission=(admission_ctl.state() if admission_ctl is not None
                       else None),
            memory=mem_sampler.snapshot(),
            # Device-time attribution (telemetry/profile.py): the
            # largest bucket executable's roofline waterfall, scaled to
            # the span ledger's measured device phase — scrape-time
            # only, never on the request path.
            profile=engine.profile_waterfall())

    prom_server = None
    if args.prom_port:
        prom_server = PromServer(args.prom_port, _prom_text,
                                 host=args.prom_host)
        print(f"[serve] prometheus /metrics on "
              f"{args.prom_host}:{prom_server.port}", file=sys.stderr)
    # 'flood' injection point (runtime/faults.py): a synthetic
    # low-priority request storm from inside the process, at #PARAM
    # req/s — reproducible overload under the TPUIC_FAULTS grammar, so
    # the admission layer's shedding can be driven (and CI-soaked)
    # without an external load generator.  Storm futures retrieve their
    # own outcomes: sheds and rejections are the point, not log spam.
    from tpuic.runtime import faults as _faults
    import threading as _threading
    flood_stop = _threading.Event()
    if _faults.fire("flood"):
        flood_rate = _faults.param("flood")
        flood_rate = 50.0 if flood_rate is None else float(flood_rate)
        flood_img = np.zeros((1, size, size, 3), engine.input_dtype)

        def _flood() -> None:
            period = 1.0 / max(flood_rate, 1e-3)
            while not flood_stop.is_set() and not guard.triggered:
                try:
                    fut = engine.submit(flood_img, timeout=0,
                                        priority="low", tenant="_flood")
                    fut.add_done_callback(
                        lambda f: f.cancelled() or f.exception())
                except Exception:  # noqa: BLE001 — rejects ARE the test
                    pass
                flood_stop.wait(period)

        _threading.Thread(target=_flood, daemon=True,
                          name="tpuic-flood").start()
        print(f"[serve] fault 'flood' armed: synthetic low-priority "
              f"storm at {flood_rate:g} req/s", file=sys.stderr)

    k = max(1, min(args.top_k, num_classes))
    out = open(args.out, "w") if args.out else sys.stdout
    pending = deque()  # (id, Future) in submission order
    served = 0

    def emit(rid, probs, order) -> None:
        nonlocal served
        topk = [[names.get(int(order[0, j]), str(int(order[0, j]))),
                 round(float(probs[0, order[0, j]]), 6)]
                for j in range(k)]
        out.write(json.dumps({"id": rid, "pred": topk[0][0],
                              "prob": topk[0][1], "topk": topk}) + "\n")
        out.flush()
        served += 1

    def drain(block: bool, deadline: float = None) -> None:
        """Emit completed responses; ``block`` waits for stragglers, up to
        ``deadline`` (time.monotonic()). Past the deadline, requests the
        device DID finish still emit their results (in submission order);
        only genuinely unresolved ones get an explicit error line — never
        a silent drop, never a discarded finished result.

        The no-deadline blocking wait polls in short slices re-checking
        the SIGTERM latch: a plain ``fut.result()`` is resumed after
        signals (PEP 475), so a SIGTERM arriving while draining a wedged
        request at EOF would otherwise never be observed — the latch
        escalates the wait to a ``--drain-timeout`` deadline instead."""
        while pending and (block or pending[0][1].done()):
            rid, fut = pending.popleft()
            try:
                if block and deadline is None:
                    while not fut.done() and not guard.triggered:
                        try:
                            fut.result(timeout=0.5)
                        except (TimeoutError, _FutTimeout):
                            pass
                    if not fut.done() and guard.triggered:
                        # Escalate: persists for the remaining stragglers
                        # (``deadline`` is function-local).
                        deadline = (time.monotonic()
                                    + max(0.0, args.drain_timeout))
                if deadline is None:
                    probs, order = fut.result()
                else:
                    probs, order = fut.result(
                        timeout=max(0.0, deadline - time.monotonic()))
            except (TimeoutError, _FutTimeout):
                pending.appendleft((rid, fut))
                expired = list(pending)
                pending.clear()
                for srid, sfut in expired:
                    if sfut.done() and not sfut.cancelled():
                        try:
                            p, o = sfut.result()
                        except Exception as e:  # noqa: BLE001
                            out.write(json.dumps(
                                {"id": srid, "error": str(e)}) + "\n")
                        else:
                            emit(srid, p, o)
                        continue
                    sfut.cancel()  # not-yet-dispatched may still cancel
                    out.write(json.dumps({
                        "id": srid, "error": "drain timeout: engine "
                        "shutting down before this request finished"}) + "\n")
                out.flush()
                return
            except Exception as e:  # noqa: BLE001 — per-request error line
                rec = {"id": rid, "error": str(e)}
                if isinstance(e, AdmissionError):
                    # Typed verdict (a pop-time DeadlineExceeded shed,
                    # or an eviction): name the cause + class so the
                    # response stream carries the same labels the
                    # rejected_total counter does.
                    rec["cause"] = e.cause
                    rec["priority"] = e.priority
                out.write(json.dumps(rec) + "\n")
                out.flush()
                continue
            except BaseException:
                # KeyboardInterrupt/SystemExit mid-wait: this request is
                # already popped — put it back so the handler's follow-up
                # drain still owns it (never a silent drop).
                pending.appendleft((rid, fut))
                raise
            emit(rid, probs, order)

    def submit(rid: str, path: str, **sla) -> bool:
        """Decode + enqueue; False = decode failed (error line emitted).

        ``sla``: per-request ``priority``/``deadline_ms``/``tenant``
        from the request line.  With --admission the enqueue is
        non-blocking: a typed rejection (queue full / quota / brownout)
        becomes an immediate error line naming its cause instead of the
        accept loop stalling behind a flood."""
        try:
            img = _load_image(path, size)
        except Exception as e:  # noqa: BLE001
            out.write(json.dumps({"id": rid, "error": f"decode: {e}"}) + "\n")
            out.flush()
            return False
        try:
            if engine.admission is not None:
                sla.setdefault("timeout", 0)
            pending.append((rid, engine.submit(img, **sla)))
        except AdmissionError as e:
            out.write(json.dumps({"id": rid, "error": str(e),
                                  "cause": e.cause,
                                  "priority": e.priority}) + "\n")
            out.flush()
            return True  # the request was handled: verdict delivered
        except (ValueError, TypeError) as e:
            # Bad SLA fields (unknown priority, non-numeric deadline)
            # are the request's problem, not the server's.
            out.write(json.dumps({"id": rid, "error": str(e)}) + "\n")
            out.flush()
            return True
        drain(block=False)  # opportunistic: decode overlaps device work
        return True

    try:
        if args.watch:
            exts = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp")
            seen: set = set()
            attempts: dict = {}
            while not guard.triggered:
                fresh = sorted(
                    f for f in os.listdir(args.watch)
                    if f.lower().endswith(exts) and f not in seen)
                for f in fresh:
                    if guard.triggered:
                        break  # stop ACCEPTING; in-flight drains below
                    if submit(f, os.path.join(args.watch, f)):
                        seen.add(f)
                        attempts.pop(f, None)
                    else:
                        # A file mid-copy decodes as truncated; retry on
                        # later ticks, give up (and stop re-erroring)
                        # after 3 — in --once mode immediately, there is
                        # no later tick.
                        attempts[f] = attempts.get(f, 0) + 1
                        if args.once or attempts[f] >= 3:
                            seen.add(f)
                drain(block=False)
                _beat()
                if args.prom_dump:
                    # Per-tick refresh: a textfile collector scraping the
                    # dump sees live counters, not only the final state.
                    # Guarded: monitoring must never take down serving
                    # (disk-full on the textfile path is not our outage).
                    try:
                        write_exposition(args.prom_dump, _prom_text())
                    except OSError as e:
                        print(f"[serve] prom dump failed: {e}",
                              file=sys.stderr)
                if args.once and not fresh and not pending:
                    break
                if args.once:
                    drain(block=True)
                    break
                time.sleep(args.poll_s)
        else:
            def handle(line: str) -> None:
                line = line.strip()
                if not line:
                    return
                try:
                    req = json.loads(line)
                    path = req["path"]
                except (ValueError, KeyError, TypeError):
                    out.write(json.dumps(
                        {"error": f"bad request line: {line[:80]}"}) + "\n")
                    out.flush()
                    return
                # Optional SLA fields per request line — honored only
                # under --admission (docs/serving.md): without the
                # operator opt-in, a client self-assigning "high" could
                # evict other clients' queued requests on a server
                # whose policy is plain FIFO.
                sla = {}
                if engine.admission is not None:
                    sla = {k: req[k] for k in ("priority", "deadline_ms",
                                               "tenant") if req.get(k)
                           is not None}
                submit(str(req.get("id", path)), path, **sla)

            # select()-gated RAW reads, not ``for line in sys.stdin``: a
            # signal handler only sets the latch and PEP 475 would resume
            # a blocked readline — an idle server would never observe
            # SIGTERM. With a select timeout the loop re-checks the latch
            # (and opportunistically drains) at least every 200 ms. Raw
            # os.read + explicit line splitting, because Python's stdin
            # buffering would hide burst-written lines from select (the
            # bytes sit in the TextIOWrapper, not at the fd) and stall
            # every request after the first. A non-fd stdin (tests feeding
            # a StringIO) can't select; it reads unguarded, the
            # pre-rewrite behavior.
            import select
            try:
                stdin_fd = sys.stdin.fileno()
            except (ValueError, OSError, AttributeError):
                stdin_fd = None
            if stdin_fd is None:
                for line in sys.stdin:
                    if guard.triggered:
                        break
                    handle(line)
            else:
                tail = b""
                while not guard.triggered:
                    try:
                        ready, _, _ = select.select([stdin_fd], [], [], 0.2)
                    except (OSError, ValueError):  # stdin closed under us
                        break
                    if not ready:
                        drain(block=False)
                        _beat()
                        continue
                    _beat()
                    chunk = os.read(stdin_fd, 1 << 16)  # ready: won't block
                    if not chunk:
                        break  # EOF
                    *lines, tail = (tail + chunk).split(b"\n")
                    for raw in lines:
                        handle(raw.decode("utf-8", "replace"))
                if tail.strip() and not guard.triggered:
                    handle(tail.decode("utf-8", "replace"))  # unterminated last line
        if guard.triggered:
            # Graceful preemption: everything already accepted drains for
            # up to --drain-timeout; stragglers get explicit error lines.
            print(f"[serve] SIGTERM: draining {len(pending)} in-flight "
                  f"request(s) (timeout {args.drain_timeout:.1f}s)",
                  file=sys.stderr)
            drain(block=True,
                  deadline=time.monotonic() + max(0.0, args.drain_timeout))
        else:
            drain(block=True)
    except KeyboardInterrupt:
        drain(block=True,
              deadline=time.monotonic() + max(0.0, args.drain_timeout))
    finally:
        guard.uninstall()
        flood_stop.set()
        engine.close(timeout=max(5.0, args.drain_timeout))
        if prom_server is not None:
            prom_server.close()
        if args.prom_dump:
            try:
                write_exposition(args.prom_dump, _prom_text())
                print(f"[serve] prometheus exposition -> {args.prom_dump}",
                      file=sys.stderr)
            except OSError as e:
                print(f"[serve] prom dump failed: {e}", file=sys.stderr)
        if slo_tracker is not None:
            print(f"[serve] slo: {slo_tracker.summary_line()}",
                  file=sys.stderr)
        if admission_ctl is not None:
            # Attribution companion to the [slo] line: the rejected_by
            # split says whether budget burn came from sheds (deadline /
            # brownout causes) or from slow service (no sheds, blown
            # attainment).
            snap = engine.stats.snapshot()
            print(f"[admission] state={json.dumps(admission_ctl.state())} "
                  f"rejected_by={json.dumps(snap['rejected_by'])}",
                  file=sys.stderr)
        print(f"[serve] served {served} requests; stats: "
              f"{json.dumps(engine.stats.snapshot())}", file=sys.stderr)
        if out is not sys.stdout:
            out.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
